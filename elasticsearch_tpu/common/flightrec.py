"""Flight recorder: an always-on cluster event journal + SLO burn-rate
watchdog with automatic post-mortem capture.

Reference: the chaos harness (``scripts/bench_chaos.py``) proved the
cluster survives a kill-and-rejoin, but the only record of *what
happened* during the failure window was the bench's pass/fail gates.
Operators of the reference get ``hot_threads``, the health report, and
(via APM) a durable event trail; this module is that trail for the
TPU-native stack, in three parts:

- :class:`FlightRecorder` — a lock-light, bounded ring journal of
  structured events (plane swap/repack, warm-handoff manifest/chunk/
  done, search failover waves and copy exhaustion, breaker trips,
  allocation verdicts, watchdog transitions, dispatches slower than a
  settings-driven threshold). Every event is stamped with wall +
  monotonic time, the ambient ``trace.id``/task id
  (``common/tracing.py`` context), and the emitting node. The ring is
  bounded (``flightrec.journal.size`` / ``ES_TPU_FLIGHTREC_CAP``);
  evicted events are counted in ``es_flightrec_dropped_total``, kept
  events in ``es_flightrec_events_total{type}``.

- :class:`SloBurnEngine` — multi-window burn-rate evaluation (the SRE
  multi-window multi-burn-rate alert shape) over the
  ``es_query_latency_ms`` stream and a failure rate derived from
  ``es_search_retries_total``/``es_shard_failovers_total``. Burn rate =
  (bad fraction in window) / (error budget); RED requires BOTH the fast
  (~1m) and slow (~10m) windows to burn past the threshold, so a single
  p99 spike (fast-window blip) can never fire a capture, while a step-
  function degradation trips fast-then-slow in order and recovery
  clears fast-then-slow the same way.

- :class:`Watchdog` — a background thread (with a real teardown:
  :meth:`Watchdog.close` joins it — ESTP-T01) that ticks the engine,
  publishes ``es_slo_burn_rate{window}``, journals every status
  transition, and on the green/yellow→RED transition fires an automatic
  diagnostic capture — hot-threads sample, telemetry snapshot, recent
  journal slice, micro-batcher queue depths, device stats — into a
  bounded capture store (``GET /_flight_recorder/captures``), counted
  in ``es_watchdog_captures_total{trigger}``.

The journal and watchdog are PROCESS-scoped singletons (the documented
pattern of ``breakers.DEFAULT`` / ``tracing.DEFAULT_STORE``): in a real
deployment one process IS one node, so the ring is the per-node journal;
in-process multi-node test clusters share it, every event carries its
``node``, and the cluster fan-in dedupes by the process-unique ``seq``.

Lock discipline: one flat lock per structure, held for O(1) appends and
snapshot copies only; NOTHING here is called while a serving lock is
held (``estpulint`` ESTP-L02 treats this module like ``telemetry``/
``tracing`` — a recorder write under a serving-module lock is a
finding). Emission is a dict build + deque append + one counter inc.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from contextvars import ContextVar
from typing import Callable, Dict, List, Optional

import weakref

from .settings import CLUSTER_SETTINGS, Setting

__all__ = [
    "FlightRecorder", "SloBurnEngine", "Watchdog", "DEFAULT", "ENGINE",
    "record", "observe_query_latency", "bind_ambient", "reset_ambient",
    "bind_shape", "reset_shape", "set_shape", "current_shape",
    "ensure_watchdog", "get_watchdog", "register_node",
    "slow_dispatch_threshold_ms",
]

GREEN, YELLOW, RED = "green", "yellow", "red"

# -- settings (registered like common/retry.py's timeout lanes, with env
# -- overrides so benches/chaos harnesses tune per process) -----------------

SETTING_JOURNAL_SIZE = CLUSTER_SETTINGS.register(
    Setting.int_setting("flightrec.journal.size", 4096,
                        scope="cluster", dynamic=True, min_value=64))
SETTING_SLOW_DISPATCH_MS = CLUSTER_SETTINGS.register(
    Setting.float_setting("flightrec.slow_dispatch_ms", 250.0,
                          scope="cluster", dynamic=True))
SETTING_SLO_LATENCY_MS = CLUSTER_SETTINGS.register(
    Setting.float_setting("slo.latency.threshold_ms", 1000.0,
                          scope="cluster", dynamic=True))
SETTING_SLO_LATENCY_BUDGET = CLUSTER_SETTINGS.register(
    Setting.float_setting("slo.latency.budget", 0.01,
                          scope="cluster", dynamic=True))
SETTING_SLO_FAILURE_BUDGET = CLUSTER_SETTINGS.register(
    Setting.float_setting("slo.failure.budget", 0.01,
                          scope="cluster", dynamic=True))
SETTING_SLO_FAST_S = CLUSTER_SETTINGS.register(
    Setting.float_setting("slo.window.fast_seconds", 60.0,
                          scope="cluster", dynamic=True))
SETTING_SLO_SLOW_S = CLUSTER_SETTINGS.register(
    Setting.float_setting("slo.window.slow_seconds", 600.0,
                          scope="cluster", dynamic=True))
SETTING_SLO_BURN_RED = CLUSTER_SETTINGS.register(
    Setting.float_setting("slo.burn_rate.red", 8.0,
                          scope="cluster", dynamic=True))
SETTING_SLO_MIN_QUERIES = CLUSTER_SETTINGS.register(
    Setting.int_setting("slo.min_window_queries", 16,
                        scope="cluster", dynamic=True, min_value=1))


def _envf(name: str, setting) -> float:
    raw = os.environ.get(name)
    if raw is not None:
        try:
            return float(raw)
        except ValueError:
            pass
    return float(setting.default)


#: live cluster-settings overlay (``apply_cluster_settings``); env
#: overrides still win, reads/writes under the lock so a REST update
#: racing a dispatcher's threshold read is never a torn view
_SETTINGS_LOCK = threading.Lock()
_SETTINGS = None


def apply_cluster_settings(values: dict) -> None:
    """``PUT /_cluster/settings`` hook for the dynamic ``slo.*`` /
    ``flightrec.*`` knobs: re-resolve the SLO engine thresholds and
    stash the overlay for the per-call resolvers. The journal ring's
    SIZE stays fixed at construction (a deque cannot re-bound in
    place); everything else takes effect on the next tick/dispatch."""
    from .settings import Settings
    global _SETTINGS
    s = Settings(values)
    with _SETTINGS_LOCK:
        _SETTINGS = s
    ENGINE.configure(s)


def slow_dispatch_threshold_ms() -> float:
    """Micro-batch dispatches slower than this journal a
    ``slow_dispatch`` event (``ES_TPU_FLIGHTREC_SLOW_MS`` env override,
    then the live ``flightrec.slow_dispatch_ms`` cluster setting)."""
    raw = os.environ.get("ES_TPU_FLIGHTREC_SLOW_MS")
    if raw is not None:
        try:
            return float(raw)
        except ValueError:
            pass
    with _SETTINGS_LOCK:
        s = _SETTINGS
    if s is not None:
        try:
            return float(SETTING_SLOW_DISPATCH_MS.get(s))
        except Exception:   # noqa: BLE001 — malformed live value
            pass
    return float(SETTING_SLOW_DISPATCH_MS.default)


# -- ambient context (node + task id, bound at the REST edge) ---------------

#: (node_id, task_id) ambient pair — mirrors ``tracing._CTX``: bound by
#: the REST dispatcher for the request's lifetime so every emission on
#: the request path stamps both without argument plumbing
_AMBIENT: ContextVar = ContextVar("es_flightrec_ambient", default=None)


def bind_ambient(node: Optional[str] = None, task: Optional[str] = None):
    return _AMBIENT.set((node, task))


def reset_ambient(token) -> None:
    _AMBIENT.reset(token)


def ambient_node() -> Optional[str]:
    """The node id bound for the current request context, if any (the
    dispatch profiler stamps it into slots at enqueue — dispatcher
    threads carry no request context of their own)."""
    amb = _AMBIENT.get()
    return amb[0] if amb is not None else None


#: query shape id ambient holder — a single-slot MUTABLE list so the
#: shard layer can upgrade the id mid-request (the structural
#: fingerprint bound at the REST/index edge becomes the plan-based one
#: once the planner lowers the body) and every later reader — slow
#: log, task ledger, dispatch-profile slots, journal events — sees the
#: final id without re-binding the context
_SHAPE: ContextVar = ContextVar("es_flightrec_shape", default=None)


def bind_shape(shape_id: Optional[str] = None):
    """Bind a fresh shape holder for the current request; returns the
    reset token (``reset_shape`` in a finally, like ``bind_ambient``)."""
    holder = [shape_id]
    try:
        # the continuous profiler samples from a foreign thread, so it
        # cannot read this contextvar — publish the MUTABLE holder into
        # its thread->attribution map (mid-request set_shape upgrades
        # stay visible with no further hooks)
        from . import contprof as _contprof
        _contprof.note_shape_holder(holder)
    except Exception:   # noqa: BLE001 — profiling must never break
        pass            # the request binding it
    return _SHAPE.set(holder)


def reset_shape(token) -> None:
    _SHAPE.reset(token)


def set_shape(shape_id: Optional[str]) -> None:
    """Upgrade the bound holder's shape id in place (no-op when no
    holder is bound — direct shard-level calls in tests)."""
    holder = _SHAPE.get()
    if holder is not None:
        if holder[0] != shape_id:
            try:
                # profile samples folded under the early structural id
                # converge onto this final id at render time
                from . import contprof as _contprof
                _contprof.note_shape_alias(holder[0], shape_id)
            except Exception:   # noqa: BLE001 — profiling must never
                pass            # break the request
        holder[0] = shape_id


def current_shape() -> Optional[str]:
    """The query shape id bound for the current request, if any."""
    holder = _SHAPE.get()
    return holder[0] if holder is not None else None


def has_shape_holder() -> bool:
    """True when a shape holder is already bound on this context (the
    REST edge binds one per search; inner layers then upgrade it in
    place rather than shadowing it with a second scope)."""
    return _SHAPE.get() is not None


# -- the ring journal -------------------------------------------------------

_SEQ = itertools.count(1)


class FlightRecorder:
    """Bounded per-node ring journal of structured events."""

    def __init__(self, cap: Optional[int] = None, registry=None):
        if cap is None:
            cap = int(_envf("ES_TPU_FLIGHTREC_CAP", SETTING_JOURNAL_SIZE))
        self.cap = max(int(cap), 64)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.cap)
        self._dropped = 0
        self._emitted = 0
        self._registry = registry
        self._counters: Dict[str, object] = {}
        # the dropped family exists from construction so its presence is
        # deterministic for the telemetry lint (events_total appears with
        # the first emit, which the lint workload drives)
        self._reg().counter(
            "es_flightrec_dropped_total",
            help="journal events evicted from the bounded flight-recorder "
                 "ring before being read").inc(0)

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from . import telemetry as _tm
        return _tm.DEFAULT

    def emit(self, type_: str, *, node: Optional[str] = None,
             trace_id: Optional[str] = None,
             task: Optional[str] = None, **attrs) -> dict:
        """Append one structured event. O(1): dict build + locked deque
        append + one counter inc. Never raises (an observability write
        must not fail the operation it observes)."""
        try:
            from . import tracing as _tracing
            amb = _AMBIENT.get()
            if node is None and amb is not None:
                node = amb[0]
            if task is None and amb is not None:
                task = amb[1]
            if trace_id is None:
                trace_id = _tracing.current_trace_id()
            shape = current_shape()
            ev = {"seq": next(_SEQ), "type": str(type_),
                  "ts_ms": round(time.time() * 1e3, 3),
                  "mono_ms": round(time.monotonic() * 1e3, 3)}
            if node:
                ev["node"] = node
            if trace_id:
                ev["trace_id"] = trace_id
            if task:
                ev["task"] = task
            if shape:
                ev["shape"] = shape
            if attrs:
                ev["attrs"] = attrs
            with self._lock:
                evicted = len(self._ring) >= self.cap
                self._ring.append(ev)
                self._emitted += 1
                if evicted:
                    self._dropped += 1
                c = self._counters.get(type_)
            if c is None:
                c = self._reg().counter(
                    "es_flightrec_events_total", {"type": str(type_)},
                    help="flight-recorder journal events by type")
                with self._lock:
                    self._counters[type_] = c
            c.inc()
            if evicted:
                self._reg().counter("es_flightrec_dropped_total").inc()
            return ev
        except Exception:   # noqa: BLE001 — journaling is best-effort
            return {}

    def events(self, type_: Optional[str] = None,
               since_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               limit: int = 256) -> List[dict]:
        """Chronological (oldest→newest) filtered slice of the retained
        ring, capped to the NEWEST ``limit`` matches. ``type_`` may be a
        comma-separated list; ``since_ms`` is a wall epoch-ms floor."""
        types = None
        if type_:
            types = {t.strip() for t in str(type_).split(",") if t.strip()}
        with self._lock:
            snap = list(self._ring)
        out = []
        for ev in snap:
            if types is not None and ev.get("type") not in types:
                continue
            if since_ms is not None and ev.get("ts_ms", 0) < since_ms:
                continue
            if trace_id is not None and ev.get("trace_id") != trace_id:
                continue
            out.append(ev)
        if limit and limit > 0:
            out = out[-int(limit):]
        return out

    def stats_doc(self) -> dict:
        with self._lock:
            return {"retained": len(self._ring), "cap": self.cap,
                    "emitted": self._emitted, "dropped": self._dropped}


#: PROCESS-scoped journal (documented singleton, like breakers.DEFAULT)
DEFAULT = FlightRecorder()


def record(type_: str, **kw) -> dict:
    """Module entry every emission site uses: journal one event into the
    process ring (node/trace/task resolved from the ambient context
    unless passed explicitly)."""
    return DEFAULT.emit(type_, **kw)


# -- SLO burn-rate engine ---------------------------------------------------

class SloBurnEngine:
    """Multi-window burn-rate evaluation over the query-latency stream
    plus an externally-fed failure count.

    Observations aggregate into per-second buckets (bounded by the slow
    window), so a 10-minute window over production qps costs O(600)
    memory, not O(queries). All thresholds resolve from settings with
    ``ES_TPU_SLO_*`` env overrides; ``clock`` is injectable (the
    burn-rate tests drive synthetic latency streams through fake
    time)."""

    def __init__(self, *, latency_threshold_ms: Optional[float] = None,
                 latency_budget: Optional[float] = None,
                 failure_budget: Optional[float] = None,
                 fast_s: Optional[float] = None,
                 slow_s: Optional[float] = None,
                 burn_red: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.configure(
            latency_threshold_ms=latency_threshold_ms,
            latency_budget=latency_budget, failure_budget=failure_budget,
            fast_s=fast_s, slow_s=slow_s, burn_red=burn_red)
        self.clock = clock
        self._lock = threading.Lock()
        # per-second rows: [sec, queries, bad_latency, failures]
        self._buckets: deque = deque()

    def configure(self, settings=None, *,
                  latency_threshold_ms: Optional[float] = None,
                  latency_budget: Optional[float] = None,
                  failure_budget: Optional[float] = None,
                  fast_s: Optional[float] = None,
                  slow_s: Optional[float] = None,
                  burn_red: Optional[float] = None) -> None:
        """(Re-)resolve every threshold from (explicit kwarg, env
        override, ``settings`` value, registered default) — the
        ``retry.RpcTimeouts.configure`` shape, so the dynamic
        ``slo.*`` cluster settings have a live re-resolve hook instead
        of being a dead control."""
        def pick(explicit, env_name, setting):
            if explicit is not None:
                return float(explicit)
            raw = os.environ.get(env_name)
            if raw is not None:
                try:
                    return float(raw)
                except ValueError:
                    pass
            if settings is not None:
                return float(setting.get(settings))
            return float(setting.default)

        self.latency_threshold_ms = pick(
            latency_threshold_ms, "ES_TPU_SLO_LATENCY_MS",
            SETTING_SLO_LATENCY_MS)
        self.latency_budget = pick(
            latency_budget, "ES_TPU_SLO_LATENCY_BUDGET",
            SETTING_SLO_LATENCY_BUDGET)
        self.failure_budget = pick(
            failure_budget, "ES_TPU_SLO_FAILURE_BUDGET",
            SETTING_SLO_FAILURE_BUDGET)
        self.fast_s = pick(fast_s, "ES_TPU_SLO_FAST_S",
                           SETTING_SLO_FAST_S)
        self.slow_s = pick(slow_s, "ES_TPU_SLO_SLOW_S",
                           SETTING_SLO_SLOW_S)
        self.burn_red = pick(burn_red, "ES_TPU_SLO_BURN_RED",
                             SETTING_SLO_BURN_RED)
        #: a window with fewer SAMPLES than this carries no burn signal
        #: at all: one recovered RPC retry on an idle cluster must not
        #: read as a 100% failure rate and fire a capture (the
        #: single-blip invariant, volume-floored)
        self.min_window_queries = int(pick(
            None, "ES_TPU_SLO_MIN_QUERIES", SETTING_SLO_MIN_QUERIES))

    # -- feeds --------------------------------------------------------------

    def _bucket(self, now: Optional[float]):
        """The row for int(now) (caller holds the lock)."""
        sec = int(now if now is not None else self.clock())
        if self._buckets and self._buckets[-1][0] == sec:
            return self._buckets[-1]
        row = [sec, 0, 0, 0]
        self._buckets.append(row)
        floor = sec - int(self.slow_s) - 1
        while self._buckets and self._buckets[0][0] < floor:
            self._buckets.popleft()
        return row

    def observe(self, latency_ms: float,
                now: Optional[float] = None) -> None:
        """One served query's wall latency (the es_query_latency_ms
        stream)."""
        with self._lock:
            row = self._bucket(now)
            row[1] += 1
            if latency_ms > self.latency_threshold_ms:
                row[2] += 1

    def note_failures(self, n: int, now: Optional[float] = None) -> None:
        """``n`` failure events since the last feed (deltas of
        es_search_retries_total / es_shard_failovers_total, sampled by
        the watchdog tick)."""
        if n <= 0:
            return
        with self._lock:
            self._bucket(now)[3] += int(n)

    # -- evaluation ---------------------------------------------------------

    def _window(self, now: float, span_s: float):
        floor = int(now) - int(span_s)
        q = bad = fails = 0
        for sec, nq, nb, nf in self._buckets:
            if sec > floor:
                q += nq
                bad += nb
                fails += nf
        return q, bad, fails

    def burn_rates(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Per-window burn rates: ``burn`` is the max of the latency and
        failure burn (each = bad fraction / its budget)."""
        t = now if now is not None else self.clock()
        with self._lock:
            out = {}
            for name, span in (("fast", self.fast_s),
                               ("slow", self.slow_s)):
                q, bad, fails = self._window(t, span)
                # the failure denominator counts COMPLETED queries plus
                # the failure events themselves: during a total outage
                # nothing completes (the latency observe happens after
                # a successful return), and a completed-only
                # denominator would leave the watchdog green through
                # the very incident it exists to capture
                denom = q + fails
                if denom < self.min_window_queries:
                    # not enough samples to judge: no burn (a lone
                    # failure event with ~zero traffic is a blip, not
                    # an incident — it would otherwise read as a 100%
                    # failure rate and trip BOTH windows at once)
                    lat_frac = fail_frac = 0.0
                else:
                    lat_frac = bad / q if q else 0.0
                    fail_frac = fails / denom
                lat_burn = lat_frac / max(self.latency_budget, 1e-9)
                fail_burn = fail_frac / max(self.failure_budget, 1e-9)
                out[name] = {
                    "queries": q, "bad_latency": bad, "failures": fails,
                    "latency_burn": round(lat_burn, 3),
                    "failure_burn": round(fail_burn, 3),
                    "burn": round(max(lat_burn, fail_burn), 3)}
        return out

    def status(self, now: Optional[float] = None) -> tuple:
        """(status, burn_rates): RED only when BOTH windows burn past
        the threshold (a fast-window blip — one p99 spike — can never go
        red alone); YELLOW when either window burns (onset, or the slow
        window still draining through recovery)."""
        rates = self.burn_rates(now)
        fast, slow = rates["fast"]["burn"], rates["slow"]["burn"]
        if fast >= self.burn_red and slow >= self.burn_red:
            return RED, rates
        if fast >= self.burn_red or slow >= self.burn_red:
            return YELLOW, rates
        return GREEN, rates


#: PROCESS-scoped engine the query-latency observation site feeds
ENGINE = SloBurnEngine()


def observe_query_latency(latency_ms: float) -> None:
    """Feed one query latency into the SLO engine (called where
    ``es_query_latency_ms`` is observed — O(1), one locked bucket
    update)."""
    ENGINE.observe(latency_ms)


# -- the watchdog -----------------------------------------------------------

#: registered node APIs whose serving surfaces captures walk (weak — a
#: retired test node must not pin itself through the watchdog)
_PROVIDERS: "weakref.WeakSet" = weakref.WeakSet()


def register_node(api) -> None:
    _PROVIDERS.add(api)


class Watchdog:
    """Ticks the SLO engine, journals transitions, and fires automatic
    diagnostic captures on the RED transition.

    Owns ONE background thread (``start()``); :meth:`close` signals and
    joins it (ESTP-T01 — the thread must never outlive its owner).
    ``tick()`` is callable directly (tests, the lint workload) without
    the thread."""

    #: capture triggers, pre-created so the counter's label space is
    #: stable for the telemetry lint
    TRIGGERS = ("slo_red", "manual")

    def __init__(self, recorder: Optional[FlightRecorder] = None,
                 engine: Optional[SloBurnEngine] = None,
                 registry=None,
                 interval_s: Optional[float] = None,
                 capture_cap: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 qos_controller=None):
        self.recorder = recorder or DEFAULT
        self.engine = engine or ENGINE
        self._registry = registry
        #: QoS controller to push overload signals to each tick (queue
        #: depth, burn status, breaker fraction → common/qos.py shed
        #: hysteresis). Injected, NOT defaulted to the process
        #: controller: test/lint watchdogs that drive synthetic RED
        #: burns must not engage shedding for every other test in the
        #: process — only ensure_watchdog's serving singleton (and
        #: benches that opt in) feed the real controller.
        self.qos_controller = qos_controller
        # default tick 5s: the windows are ~1m/~10m, so 5s still
        # samples the fast window 12x while keeping the always-on
        # thread near-inert (benches with second-scale windows set
        # ES_TPU_WATCHDOG_TICK_S down explicitly). Env parsing is
        # guarded: a malformed value must degrade to the default, not
        # crash every node constructor in the process.
        def _env_num(name, default, cast):
            try:
                return cast(os.environ.get(name, default))
            except (TypeError, ValueError):
                return cast(default)

        self.interval_s = interval_s if interval_s is not None else \
            _env_num("ES_TPU_WATCHDOG_TICK_S", "5.0", float)
        self.capture_cap = capture_cap if capture_cap is not None else \
            _env_num("ES_TPU_WATCHDOG_CAPTURES", "8", int)
        self.clock = clock
        self._lock = threading.Lock()
        self._captures: deque = deque(maxlen=max(self.capture_cap, 1))
        self._status = GREEN
        self._last_rates: Dict[str, dict] = {}
        self._fail_seen: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = self._reg()
        for t in self.TRIGGERS:
            reg.counter("es_watchdog_captures_total", {"trigger": t},
                        help="automatic post-mortem captures by "
                             "trigger").inc(0)
        for w in ("fast", "slow"):
            reg.gauge("es_slo_burn_rate", {"window": w},
                      help="SLO burn rate per evaluation window (bad "
                           "fraction / error budget; >=red threshold "
                           "in BOTH windows fires a capture)").set(0.0)

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from . import telemetry as _tm
        return _tm.DEFAULT

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Watchdog":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                t = threading.Thread(target=self._run,
                                     name="es-watchdog-slo", daemon=True)
                self._thread = t
                t.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        """Signal and JOIN the watchdog thread (orderly teardown)."""
        self._stop.set()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            t.join(timeout)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:   # noqa: BLE001 — the watchdog must
                pass            # survive any broken surface it samples

    # -- evaluation ---------------------------------------------------------

    def _failure_count(self) -> float:
        """Cumulative failure-ish events from the registry: search copy
        retries/exhaustions + master-side shard failovers. Targeted
        two-family point reads — a full registry snapshot would
        quantile-sort every histogram ring on every tick."""
        reg = self._reg()
        total = sum(
            v for labels, v in reg.family_values("es_search_retries_total")
            if labels.get("outcome") in ("retried", "exhausted"))
        total += sum(v for _labels, v in
                     reg.family_values("es_shard_failovers_total"))
        return total

    def tick(self, now: Optional[float] = None) -> str:
        """One evaluation round: fold failure-counter deltas into the
        engine, compute burn rates, publish gauges, journal transitions,
        and fire a capture on the RED transition. Returns the status."""
        t = now if now is not None else self.clock()
        fails = self._failure_count()
        with self._lock:
            seen = self._fail_seen
            self._fail_seen = fails
        if seen is not None and fails > seen:
            self.engine.note_failures(int(fails - seen), now=t)
        status, rates = self.engine.status(now=t)
        reg = self._reg()
        for w in ("fast", "slow"):
            reg.gauge("es_slo_burn_rate", {"window": w}).set(
                rates[w]["burn"])
        with self._lock:
            prev = self._status
            self._status = status
            self._last_rates = rates
        if status != prev:
            self.recorder.emit(
                "watchdog", transition=f"{prev}->{status}",
                fast_burn=rates["fast"]["burn"],
                slow_burn=rates["slow"]["burn"])
            if status == RED:
                self.capture("slo_red", rates=rates)
        total_depth = self._sample_batcher_queues()
        if self.qos_controller is not None:
            # push this tick's overload evidence into the QoS shed
            # hysteresis — the edge then reads O(1) state per request
            # instead of walking batchers itself
            try:
                self.qos_controller.note_signals(
                    queue_depth=total_depth, burn_status=status,
                    breaker_fraction=self._breaker_fraction())
            except Exception:   # noqa: BLE001 — QoS must not kill the
                pass            # tick that feeds it
        # the same tick feeds the downsampling history ring — one poll
        # cadence for every windowed consumer (lazy import: history is
        # optional for watchdog-less embedders)
        from . import metrics_history as _mh
        _mh.record_tick()
        return status

    def _sample_batcher_queues(self) -> int:
        """Periodic ``es_batcher_queue_depth{index,kind,class}`` gauges
        — queue depth was only visible inside watchdog CAPTURES before;
        sampling it on the existing tick makes the convoy signal a
        scrapeable time series with no new thread. Depths sum per
        (index, kind, priority class) over a cache's live generations
        (several generations of one index share the serving load).
        Returns the TOTAL depth across all series — the QoS shed
        signal."""
        reg = self._reg()
        depths: Dict[tuple, int] = {}
        for d in self._batcher_queues():
            by_class = d.get("by_class") or {"interactive":
                                             int(d.get("depth", 0))}
            for cls, n in by_class.items():
                key = (d.get("index"), d.get("kind", "text"), str(cls))
                depths[key] = depths.get(key, 0) + int(n)
        total = sum(depths.values())
        # series whose batcher disappeared (index deleted, cache torn
        # down) zero out instead of freezing at their last sampled
        # depth — a stale nonzero depth would alert forever on a
        # nonexistent index (zeroed once; dropped from tracking after)
        live = set(depths)
        prev = getattr(self, "_queue_depth_keys", set())
        for index, kind, cls in prev - live:
            depths[(index, kind, cls)] = 0
        self._queue_depth_keys = live
        for (index, kind, cls), depth in depths.items():
            reg.gauge(
                "es_batcher_queue_depth",
                {"index": str(index), "kind": str(kind),
                 "class": str(cls)},
                help="micro-batcher slots waiting for a dispatch by "
                     "priority class, sampled per watchdog "
                     "tick").set(depth)
        return total

    @staticmethod
    def _breaker_fraction() -> float:
        """Parent-breaker memory pressure as a 0..1 fraction (the third
        QoS shed signal, next to queue depth and burn status)."""
        try:
            from .breakers import DEFAULT as _brk
            limit = float(_brk.parent.limit)
            if limit <= 0:
                return 0.0
            return float(_brk.parent.total_used()) / limit
        except Exception:   # noqa: BLE001 — breaker-less embedder
            return 0.0

    # -- captures -----------------------------------------------------------

    def capture(self, trigger: str, rates: Optional[dict] = None) -> dict:
        """One diagnostic capture into the bounded store: hot-threads
        sample, telemetry snapshot, recent journal slice, micro-batcher
        queue depths, device stats. Runs on the watchdog thread (or the
        caller for ``manual``), NEVER on a serving path."""
        cap_id = f"cap-{next(_SEQ):08x}"
        doc: dict = {"id": cap_id, "trigger": trigger,
                     "ts_ms": round(time.time() * 1e3, 3),
                     "status": self._status,
                     "burn_rates": rates or self.engine.burn_rates()}
        try:
            from ..utils.hot_threads import hot_threads
            doc["hot_threads"] = hot_threads(
                threads=3, interval_ms=60.0, snapshots=3)
        except Exception as e:   # noqa: BLE001 — partial captures beat
            doc["hot_threads"] = f"<failed: {e}>"        # no capture
        try:
            doc["telemetry"] = self._reg().metrics_doc()
        except Exception:   # noqa: BLE001
            doc["telemetry"] = {}
        doc["journal"] = self.recorder.events(limit=128)
        doc["batcher_queues"] = self._batcher_queues()
        try:
            # attributed CPU profile slice: the live sampler's windows,
            # or a short burst when the always-on thread is gated off —
            # SLO-red post-mortems answer "where was the CPU going"
            from . import contprof as _contprof
            doc["profile"] = _contprof.capture_doc()
        except Exception:   # noqa: BLE001 — partial captures beat none
            doc["profile"] = {}
        try:
            from . import telemetry as _tm
            doc["device"] = _tm.device_stats_doc()
        except Exception:   # noqa: BLE001
            doc["device"] = {}
        with self._lock:
            self._captures.append(doc)
        self._reg().counter("es_watchdog_captures_total",
                            {"trigger": str(trigger)}).inc()
        self.recorder.emit("capture", id=cap_id, trigger=trigger)
        return doc

    @staticmethod
    def _batcher_queues() -> List[dict]:
        out = []
        try:
            providers = list(_PROVIDERS)
        except RuntimeError:    # racing a node registration: skip this
            return out          # capture's queue section, keep the rest
        for api in providers:
            try:
                for name, svc in list(api.indices.indices.items()):
                    for b in svc.plane_cache.serving_batchers():
                        doc = {
                            "node": api.node_id, "index": name,
                            "plane": type(b.plane).__name__,
                            "kind": getattr(b, "kind", "text"),
                            "depth": b.queue_depth(),
                            "dispatches": b.n_dispatches}
                        by_cls = getattr(b, "queue_depth_by_class",
                                         None)
                        if by_cls is not None:
                            # per-priority-class split (foreign
                            # batchers without it fold into
                            # class="interactive" at sampling)
                            doc["by_class"] = by_cls()
                        out.append(doc)
            except Exception:   # noqa: BLE001 — a mid-teardown node
                continue        # contributes nothing
        return out

    def captures(self) -> List[dict]:
        """Newest-last capture summaries (without the heavy payloads)."""
        with self._lock:
            snap = list(self._captures)
        return [{k: c[k] for k in ("id", "trigger", "ts_ms", "status",
                                   "burn_rates") if k in c}
                for c in snap]

    def get_capture(self, cap_id: str) -> Optional[dict]:
        with self._lock:
            for c in self._captures:
                if c["id"] == cap_id:
                    return c
        return None

    def status_doc(self) -> dict:
        with self._lock:
            return {"status": self._status,
                    "burn_rates": dict(self._last_rates),
                    "captures": len(self._captures),
                    "interval_s": self.interval_s,
                    "running": self._thread is not None
                    and self._thread.is_alive()}


# -- process singleton ------------------------------------------------------

_WATCHDOG_LOCK = threading.Lock()
_WATCHDOG: Optional[Watchdog] = None


def ensure_watchdog() -> Optional[Watchdog]:
    """Start (once) the process watchdog thread. ``ES_TPU_WATCHDOG=0``
    disables it (returns None). Idempotent — every node constructed in
    this process shares the one watchdog, the way they share the breaker
    service and the telemetry registry."""
    if os.environ.get("ES_TPU_WATCHDOG", "1").lower() in ("0", "false"):
        return None
    global _WATCHDOG
    with _WATCHDOG_LOCK:
        if _WATCHDOG is None:
            # the serving singleton feeds the process QoS controller
            # (test-constructed Watchdogs don't — see __init__)
            try:
                from . import qos as _qos
                ctl = _qos.controller()
            except Exception:   # noqa: BLE001
                ctl = None
            _WATCHDOG = Watchdog(qos_controller=ctl)
            _WATCHDOG.start()
        return _WATCHDOG


def get_watchdog() -> Optional[Watchdog]:
    with _WATCHDOG_LOCK:
        return _WATCHDOG
