"""Shared RPC timeout classes + jittered-backoff retry for the cluster.

Before this module every cluster RPC call site carried its own literal
timeout (2.0 / 5.0 / 10.0 / 15.0 scattered through ``node/cluster_rest``
and ``node/cluster_node``) and its own ad-hoc retry loop, so tuning the
cluster for chaos-induced slowness (fault-injected delay, a GC-stalled
peer) meant editing call sites. The reference keys every transport
request to a named timeout setting (``TransportRequestOptions`` /
``cluster.*.timeout`` settings); this is that discipline reduced to the
four lanes this codebase actually has:

- ``fast``    — liveness-class metadata probes (ping follow-ups,
  shard:insync, shard:refresh): cheap, retried elsewhere, fail fast.
- ``data``    — routed document ops and replica-channel fan-out.
- ``meta``    — master metadata ops / whole-request forwarding: these
  wait on publications, so they get the long lane.
- ``search``  — per-ATTEMPT budget of one ``search:shards`` /
  ``search:stats`` RPC; the coordinator's copy-failover loop spends
  several of these, each against a different shard copy.

Every value is settings-driven (``cluster.rpc.timeout.*``, registered in
:mod:`~elasticsearch_tpu.common.settings`) with environment overrides
(``ES_TPU_RPC_TIMEOUT_<LANE>``) so the chaos bench can tighten the
cluster without code edits.

The retry half is ONE shared jittered-backoff policy
(:func:`backoff_delays` — full jitter over an exponentially growing cap,
the AWS-architecture-blog shape that avoids retry synchronization after
a node death) consumed by the search failover loop, recovery chunk
transfer, and the agg-partials fan-out, instead of three hand-rolled
sleep loops.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Iterator, Optional

from .settings import CLUSTER_SETTINGS, Setting

#: registered cluster-scope settings (dynamic: a reroute/chaos harness
#: may retune a live cluster)
SETTING_RPC_TIMEOUT_FAST = CLUSTER_SETTINGS.register(
    Setting.float_setting("cluster.rpc.timeout.fast", 2.0,
                          scope="cluster", dynamic=True))
SETTING_RPC_TIMEOUT_DATA = CLUSTER_SETTINGS.register(
    Setting.float_setting("cluster.rpc.timeout.data", 5.0,
                          scope="cluster", dynamic=True))
SETTING_RPC_TIMEOUT_META = CLUSTER_SETTINGS.register(
    Setting.float_setting("cluster.rpc.timeout.meta", 10.0,
                          scope="cluster", dynamic=True))
SETTING_RPC_TIMEOUT_SEARCH = CLUSTER_SETTINGS.register(
    Setting.float_setting("cluster.rpc.timeout.search", 15.0,
                          scope="cluster", dynamic=True))
SETTING_RPC_RETRY_ATTEMPTS = CLUSTER_SETTINGS.register(
    Setting.int_setting("cluster.rpc.retry.attempts", 3,
                        scope="cluster", dynamic=True, min_value=1))
SETTING_RPC_RETRY_BACKOFF_BASE = CLUSTER_SETTINGS.register(
    Setting.float_setting("cluster.rpc.retry.backoff_base", 0.05,
                          scope="cluster", dynamic=True))
SETTING_RPC_RETRY_BACKOFF_CAP = CLUSTER_SETTINGS.register(
    Setting.float_setting("cluster.rpc.retry.backoff_cap", 0.5,
                          scope="cluster", dynamic=True))


class RpcTimeouts:
    """The four timeout lanes + retry knobs, resolved once per process
    from (env override, settings value, registered default) and
    re-resolvable at runtime via :meth:`configure`."""

    _LANES = ("fast", "data", "meta", "search")

    def __init__(self):
        self._lock = threading.Lock()
        self._values = {}
        self.configure(None)

    @staticmethod
    def _env(lane: str) -> Optional[float]:
        raw = os.environ.get(f"ES_TPU_RPC_TIMEOUT_{lane.upper()}")
        if raw is None:
            return None
        try:
            return float(raw)
        except ValueError:
            return None

    def configure(self, settings=None) -> None:
        """Re-resolve every lane. ``settings`` is a
        :class:`~elasticsearch_tpu.common.settings.Settings` (or None for
        registered defaults); env overrides always win — the chaos bench
        tunes per-process without threading a settings object through."""
        from .settings import Settings
        s = settings or Settings.EMPTY
        by_lane = {
            "fast": SETTING_RPC_TIMEOUT_FAST,
            "data": SETTING_RPC_TIMEOUT_DATA,
            "meta": SETTING_RPC_TIMEOUT_META,
            "search": SETTING_RPC_TIMEOUT_SEARCH,
        }
        vals = {}
        for lane, setting in by_lane.items():
            env = self._env(lane)
            vals[lane] = env if env is not None else float(setting.get(s))
        vals["retry_attempts"] = int(
            os.environ.get("ES_TPU_RPC_RETRY_ATTEMPTS",
                           SETTING_RPC_RETRY_ATTEMPTS.get(s)))
        vals["backoff_base"] = float(
            os.environ.get("ES_TPU_RPC_BACKOFF_BASE",
                           SETTING_RPC_RETRY_BACKOFF_BASE.get(s)))
        vals["backoff_cap"] = float(
            os.environ.get("ES_TPU_RPC_BACKOFF_CAP",
                           SETTING_RPC_RETRY_BACKOFF_CAP.get(s)))
        with self._lock:
            self._values = vals

    def _get(self, key: str) -> float:
        with self._lock:
            return self._values[key]

    @property
    def fast(self) -> float:
        return self._get("fast")

    @property
    def data(self) -> float:
        return self._get("data")

    @property
    def meta(self) -> float:
        return self._get("meta")

    @property
    def search(self) -> float:
        return self._get("search")

    @property
    def retry_attempts(self) -> int:
        return int(self._get("retry_attempts"))

    @property
    def backoff_base(self) -> float:
        return self._get("backoff_base")

    @property
    def backoff_cap(self) -> float:
        return self._get("backoff_cap")


#: process-wide instance every cluster call site reads
TIMEOUTS = RpcTimeouts()


def backoff_delays(attempts: Optional[int] = None,
                   base: Optional[float] = None,
                   cap: Optional[float] = None,
                   rng: Optional[random.Random] = None
                   ) -> Iterator[float]:
    """Yield up to ``attempts`` jittered backoff delays (seconds): full
    jitter over an exponentially growing window —
    ``uniform(0, min(cap, base * 2**i))`` — so a fleet of coordinators
    retrying into the copies of one dead node's shards never
    synchronizes into a thundering herd. A seeded ``rng`` makes the
    schedule deterministic (the chaos harness passes one)."""
    n = attempts if attempts is not None else TIMEOUTS.retry_attempts
    b = base if base is not None else TIMEOUTS.backoff_base
    c = cap if cap is not None else TIMEOUTS.backoff_cap
    r = rng or random
    for i in range(n):
        yield r.uniform(0.0, min(c, b * (2 ** i)))


def retry_with_backoff(fn, attempts: Optional[int] = None,
                       rng: Optional[random.Random] = None,
                       sleep=None, on_retry=None):
    """Call ``fn()`` up to ``attempts`` times with jittered backoff
    between failures; re-raises the last exception. ``on_retry(i, e)``
    observes each failed attempt (telemetry hooks). ``sleep`` is
    injectable for tests."""
    import time as _time
    do_sleep = sleep or _time.sleep
    n = attempts if attempts is not None else TIMEOUTS.retry_attempts
    last: Optional[Exception] = None
    for i, delay in enumerate(backoff_delays(n, rng=rng)):
        try:
            return fn()
        except Exception as e:   # noqa: BLE001 — caller-scoped retry
            last = e
            if on_retry is not None:
                on_retry(i, e)
            if i + 1 < n:
                do_sleep(delay)
    raise last if last is not None else RuntimeError("no attempts")
