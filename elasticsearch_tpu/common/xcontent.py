"""x-content: pluggable content formats on the REST boundary.

Reference: ``libs/x-content`` (``XContentType.java``: JSON, SMILE, YAML,
CBOR — negotiated from Content-Type/Accept). Here JSON is the native
in-process form; YAML rides the bundled pyyaml and CBOR is a self-
contained RFC 8949 codec below (no cbor wheel in the image). SMILE has no
stdlib-feasible codec and is rejected with the same error shape an
unknown content type gets from the reference's ``RestController``.

The REST layer calls :func:`decode_request` to normalize an incoming body
to the parsed-JSON-equivalent bytes and :func:`encode_response` to render
the response in the Accept'ed format.
"""
from __future__ import annotations

import json
import struct
from typing import Any, Optional, Tuple

from .errors import ElasticsearchError


class UnsupportedContentType(ElasticsearchError):
    status = 406
    error_type = "status_exception"


# ---------------------------------------------------------------------------
# CBOR (RFC 8949 subset: the JSON-representable data model)
# ---------------------------------------------------------------------------

def cbor_encode(obj: Any) -> bytes:
    out = bytearray()
    _cb_enc(obj, out)
    return bytes(out)


def _cb_head(major: int, n: int, out: bytearray) -> None:
    if n < 24:
        out.append((major << 5) | n)
    elif n < 0x100:
        out.append((major << 5) | 24)
        out.append(n)
    elif n < 0x10000:
        out.append((major << 5) | 25)
        out.extend(struct.pack(">H", n))
    elif n < 0x100000000:
        out.append((major << 5) | 26)
        out.extend(struct.pack(">I", n))
    else:
        out.append((major << 5) | 27)
        out.extend(struct.pack(">Q", n))


def _cb_enc(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(0xF6)
    elif obj is True:
        out.append(0xF5)
    elif obj is False:
        out.append(0xF4)
    elif isinstance(obj, int):
        if obj >= 0:
            _cb_head(0, obj, out)
        else:
            _cb_head(1, -1 - obj, out)
    elif isinstance(obj, float):
        out.append(0xFB)
        out.extend(struct.pack(">d", obj))
    elif isinstance(obj, bytes):
        _cb_head(2, len(obj), out)
        out.extend(obj)
    elif isinstance(obj, str):
        bs = obj.encode("utf-8")
        _cb_head(3, len(bs), out)
        out.extend(bs)
    elif isinstance(obj, (list, tuple)):
        _cb_head(4, len(obj), out)
        for item in obj:
            _cb_enc(item, out)
    elif isinstance(obj, dict):
        _cb_head(5, len(obj), out)
        for k, v in obj.items():
            _cb_enc(str(k), out)
            _cb_enc(v, out)
    else:
        raise ElasticsearchError(
            f"cannot CBOR-encode type [{type(obj).__name__}]")


class _CborReader:
    def __init__(self, data: bytes):
        self.data = data
        self.i = 0

    def byte(self) -> int:
        b = self.data[self.i]
        self.i += 1
        return b

    def take(self, n: int) -> bytes:
        chunk = self.data[self.i: self.i + n]
        if len(chunk) != n:
            raise ElasticsearchError("truncated CBOR input")
        self.i += n
        return chunk

    def length(self, info: int) -> Optional[int]:
        if info < 24:
            return info
        if info == 24:
            return self.byte()
        if info == 25:
            return struct.unpack(">H", self.take(2))[0]
        if info == 26:
            return struct.unpack(">I", self.take(4))[0]
        if info == 27:
            return struct.unpack(">Q", self.take(8))[0]
        if info == 31:
            return None                  # indefinite
        raise ElasticsearchError("malformed CBOR length")

    def decode(self) -> Any:
        ib = self.byte()
        major, info = ib >> 5, ib & 0x1F
        if major == 0:
            return self.length(info)
        if major == 1:
            return -1 - self.length(info)
        if major == 2 or major == 3:
            n = self.length(info)
            if n is None:                # indefinite string: concat chunks
                parts = []
                while self.data[self.i] != 0xFF:
                    parts.append(self.decode())
                self.i += 1
                if major == 3:
                    return "".join(parts)
                return b"".join(parts)
            raw = self.take(n)
            return raw.decode("utf-8") if major == 3 else raw
        if major == 4:
            n = self.length(info)
            items = []
            if n is None:
                while self.data[self.i] != 0xFF:
                    items.append(self.decode())
                self.i += 1
            else:
                for _ in range(n):
                    items.append(self.decode())
            return items
        if major == 5:
            n = self.length(info)
            obj = {}
            if n is None:
                while self.data[self.i] != 0xFF:
                    k = self.decode()
                    obj[k] = self.decode()
                self.i += 1
            else:
                for _ in range(n):
                    k = self.decode()
                    obj[k] = self.decode()
            return obj
        if major == 7:
            if info == 20:
                return False
            if info == 21:
                return True
            if info == 22 or info == 23:
                return None
            if info == 25:               # half float
                h = struct.unpack(">H", self.take(2))[0]
                return _half_to_float(h)
            if info == 26:
                return struct.unpack(">f", self.take(4))[0]
            if info == 27:
                return struct.unpack(">d", self.take(8))[0]
        raise ElasticsearchError(f"unsupported CBOR item [{ib:#x}]")


def _half_to_float(h: int) -> float:
    sign = -1.0 if h & 0x8000 else 1.0
    exp = (h >> 10) & 0x1F
    frac = h & 0x3FF
    if exp == 0:
        return sign * frac * 2.0 ** -24
    if exp == 31:
        return sign * (float("inf") if frac == 0 else float("nan"))
    return sign * (1 + frac / 1024.0) * 2.0 ** (exp - 15)


def cbor_decode(data: bytes) -> Any:
    return _CborReader(data).decode()


# ---------------------------------------------------------------------------
# negotiation
# ---------------------------------------------------------------------------

def _base_type(ct: Optional[str]) -> str:
    if not ct:
        return "application/json"
    return ct.split(";")[0].strip().lower()


def decode_request(body: bytes, content_type: Optional[str]) -> bytes:
    """Incoming body → JSON bytes the handlers natively parse."""
    base = _base_type(content_type)
    if base in ("application/json", "application/x-ndjson", "text/plain",
                ""):
        return body
    if base == "application/cbor":
        return json.dumps(cbor_decode(body)).encode()
    if base in ("application/yaml", "text/yaml"):
        import yaml
        return json.dumps(yaml.safe_load(body)).encode()
    if base == "application/smile":
        raise UnsupportedContentType(
            "Content-Type header [application/smile] is not supported")
    raise UnsupportedContentType(
        f"Content-Type header [{content_type}] is not supported")


def encode_response(payload: bytes, json_ct: str,
                    accept: Optional[str]) -> Tuple[bytes, str]:
    """JSON response bytes → the Accept'ed wire format."""
    base = _base_type(accept)
    if base in ("application/json", "", "*/*") or \
            not json_ct.startswith("application/json"):
        return payload, json_ct
    if base == "application/cbor":
        return cbor_encode(json.loads(payload)), "application/cbor"
    if base in ("application/yaml", "text/yaml"):
        import yaml
        return (yaml.safe_dump(json.loads(payload)).encode(),
                "application/yaml")
    if base == "application/smile":
        raise UnsupportedContentType(
            "Accept header [application/smile] is not supported")
    # vnd.elasticsearch+json compat media types serve plain JSON;
    # any other unknown Accept falls back to JSON (permissive, like
    # text/* agents) rather than failing a readable response
    return payload, json_ct
