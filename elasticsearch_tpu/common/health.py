"""Cluster health report: the 8.x indicator API over the telemetry layer.

Reference: ``GET /_health_report`` (``health/HealthService.java`` +
one ``HealthIndicatorService`` per concern) — each indicator evaluates
live node state into ``green``/``yellow``/``red`` with a human
``symptom``, machine ``details``, and, when degraded, reference-shaped
``impacts`` (what stops working) and ``diagnosis`` (cause → action).
The top-level ``status`` is the worst indicator.

The TPU-native indicators are registry-driven — they read the SAME
counters ``/_prometheus/metrics`` exposes, so an alert and the health
report can never disagree:

- ``shards_availability`` — unassigned/active shard counts (the cluster
  front recomputes this from the published routing table, where ``red``
  is reachable; the single-node view caps at ``yellow``).
- ``plane_serving`` — synchronous request-thread plane rebuilds beyond
  the cold builds. Per TELEMETRY.md, ``es_plane_rebuild_total{mode=
  "sync"}`` rising past the cold count is the rebuild-storm signature
  (every refresh repacking the serving plane on request threads).
- ``compile_churn`` — steady-state XLA compiles: compiles recorded past
  what the warmup lattice pre-compiled mean first-hit compiles are
  landing mid-traffic (the multi-second p99 signature). Windowed per
  evaluator since the previous health evaluation (the compile counter
  is process-cumulative while warmed credits die with retired
  batchers; judging all of process history against live batchers only
  would accumulate phantom excess).
- ``breakers`` — circuit-breaker trips (parent trip → red).
- ``indexing_pressure`` — 429 rejections + current bytes vs the budget.
- ``task_backlog`` — live registered tasks and the oldest task's age.

Evaluation is snapshot-time only (never on a request path) and each
indicator is fail-safe: an indicator that throws reports itself
``unknown`` instead of failing the endpoint.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

GREEN, YELLOW, RED, UNKNOWN = "green", "yellow", "red", "unknown"

#: guards the ANN-drift watermark's read-modify-write (concurrent
#: health pollers must not double-count or swallow a drift window)
_ANN_DRIFT_LOCK = threading.Lock()

_RANK = {GREEN: 0, UNKNOWN: 1, YELLOW: 2, RED: 3}


def worst_status(statuses) -> str:
    return max(statuses, key=lambda s: _RANK.get(s, 1), default=GREEN)


def _impact(id_: str, severity: int, description: str,
            areas: List[str]) -> dict:
    return {"id": id_, "severity": severity, "description": description,
            "impact_areas": areas}


def _diagnosis(id_: str, cause: str, action: str,
               affected: Optional[dict] = None) -> dict:
    return {"id": id_, "cause": cause, "action": action,
            "help_url": "TELEMETRY.md",
            "affected_resources": affected or {}}


class HealthService:
    """Evaluates every indicator against one node's live surfaces.

    ``api`` is the node's ``RestAPI`` (indices, task manager, plane
    caches); the process telemetry registry and breaker/pressure
    singletons are read directly."""

    INDICATORS = ("shards_availability", "plane_serving", "plane_tiers",
                  "compile_churn", "breakers", "indexing_pressure",
                  "task_backlog", "slo_burn", "dispatch_efficiency",
                  "query_insights", "qos")

    #: sync non-cold rebuilds: first one turns yellow, a storm turns red
    SYNC_REBUILD_YELLOW = 1
    SYNC_REBUILD_RED = 8
    #: steady-state compiles past the warmed lattice before degrading
    COMPILE_SLACK = 4
    COMPILE_RED = 64
    #: live-task backlog thresholds
    BACKLOG_YELLOW = 64
    BACKLOG_RED = 512
    OLDEST_TASK_YELLOW_S = 60.0
    OLDEST_TASK_RED_S = 300.0
    #: indexing-pressure utilization fraction that reads as saturation
    PRESSURE_YELLOW_FRACTION = 0.8

    def __init__(self, api):
        self.api = api

    # -- entry ---------------------------------------------------------------

    def report(self, indicator: Optional[str] = None,
               verbose: bool = True) -> dict:
        from .errors import ResourceNotFoundError
        names = self.INDICATORS
        if indicator is not None:
            if indicator not in self.INDICATORS:
                raise ResourceNotFoundError(
                    f"health indicator [{indicator}] does not exist; "
                    f"known indicators are {sorted(self.INDICATORS)}")
            names = (indicator,)
        indicators: Dict[str, dict] = {}
        for name in names:
            try:
                doc = getattr(self, f"_ind_{name}")()
            except Exception as e:   # noqa: BLE001 — one broken indicator
                doc = {"status": UNKNOWN,          # must not fail the API
                       "symptom": f"indicator evaluation failed: {e}"}
            if not verbose:
                doc = {k: v for k, v in doc.items()
                       if k in ("status", "symptom")}
            indicators[name] = doc
        return {
            "status": worst_status(d["status"]
                                   for d in indicators.values()),
            "cluster_name": self.api.cluster_name,
            "indicators": indicators,
        }

    # -- indicators ----------------------------------------------------------

    def _ind_shards_availability(self) -> dict:
        h = self.api._health()
        unassigned = int(h.get("unassigned_shards", 0))
        active = int(h.get("active_shards", 0))
        status = {"green": GREEN, "yellow": YELLOW,
                  "red": RED}.get(h.get("status"), UNKNOWN)
        doc = {
            "status": status,
            "symptom": ("This cluster has all shards available."
                        if status == GREEN else
                        f"This cluster has {unassigned} unassigned "
                        f"shard{'s' if unassigned != 1 else ''}."),
            "details": {"active_shards": active,
                        "unassigned_shards": unassigned,
                        "active_primary_shards":
                            int(h.get("active_primary_shards", 0))},
        }
        if status != GREEN:
            doc["impacts"] = [_impact(
                "shards_availability:degraded", 2,
                "Searches may return partial results and writes may be "
                "rejected for unassigned shards.", ["search", "ingest"])]
            doc["diagnosis"] = [_diagnosis(
                "shards_availability:unassigned",
                f"{unassigned} shard copies are not assigned to any "
                f"live node (replica count exceeds allocatable nodes, "
                f"or owning nodes left the cluster).",
                "Add data nodes, lower index.number_of_replicas, or "
                "POST /_cluster/reroute?retry_failed=true.")]
        return doc

    def _ind_plane_serving(self) -> dict:
        sync = cold = background = 0
        delta_serves = 0
        per_index: Dict[str, int] = {}
        for name, svc in list(self.api.indices.indices.items()):
            try:
                rb = svc.plane_cache.rebuild_stats()
            except Exception:   # noqa: BLE001 — no plane cache: skip
                continue
            sync += rb.get("sync", 0)
            cold += rb.get("cold", 0)
            background += rb.get("background", 0)
            delta_serves += rb.get("delta_serves", 0)
            storm_i = rb.get("sync", 0) - rb.get("cold", 0)
            if storm_i > 0:
                per_index[name] = storm_i
        # every cold build is mode="sync"; a sync count past the cold
        # count means NON-cold repacks ran on request threads — the
        # rebuild-storm signature (TELEMETRY.md es_plane_rebuild_total)
        storm = max(sync - cold, 0)
        # ANN recall-config drift: dispatches served with nprobe BELOW
        # the benched default (TELEMETRY.md
        # es_ann_nprobe_below_default_total) — the knn_ivf_recall bench
        # certifies recall@k at the default; lowering nprobe trades
        # recall silently, which is a health concern, not an error.
        # Windowed against the previous health evaluation (watermark on
        # the api object): the counter is cumulative and would latch
        # yellow forever, making its own remediation ("drop the
        # override") unverifiable — yellow means drift SINCE last check.
        # The evaluation CONSUMES the window (first poller wins);
        # rate()-style monitors should read the cumulative
        # ann_below_default_total in details instead.
        from . import telemetry as _tm
        with _ANN_DRIFT_LOCK:
            ann_total = _tm.ann_drift_count()
            seen = getattr(self.api, "_ann_drift_seen", 0)
            ann_drift = max(ann_total - seen, 0)
            self.api._ann_drift_seen = ann_total
            # lexical pruning drift, windowed the same way: requests
            # explicitly forcing prune=off on a block-max plane fall
            # off the benched WAND-as-a-scan serving path (TELEMETRY.md
            # es_lex_prune_off_total) — a latency concern, not an error
            lex_total = _tm.lex_prune_off_count()
            lseen = getattr(self.api, "_lex_drift_seen", 0)
            lex_drift = max(lex_total - lseen, 0)
            self.api._lex_drift_seen = lex_total
        # mesh under-utilization: the serving mesh left devices out of
        # the slice (TELEMETRY.md es_mesh_devices{state="idle"}) — paid
        # chips stream zero corpus bytes. A gauge, not a window: idle
        # devices stay idle until the mesh knobs change.
        idle_devices = _tm.mesh_idle_devices()
        if storm >= self.SYNC_REBUILD_RED:
            status = RED
        elif storm >= self.SYNC_REBUILD_YELLOW or ann_drift > 0 \
                or lex_drift > 0 or idle_devices > 0:
            status = YELLOW
        else:
            status = GREEN
        if storm > 0:
            symptom = (f"{storm} synchronous serving-plane rebuilds ran "
                       f"on request threads (rebuild storm).")
        elif ann_drift > 0:
            symptom = (f"{ann_drift} ANN dispatches served below the "
                       f"benched nprobe (recall-config drift).")
        elif lex_drift > 0:
            symptom = (f"{lex_drift} lexical dispatches forced prune=off "
                       f"on a block-max plane (pruning drift).")
        elif idle_devices > 0:
            symptom = (f"{idle_devices} device(s) stranded idle outside "
                       f"the serving mesh (under-utilization).")
        else:
            symptom = "Serving planes are maintained off the request path."
        doc = {
            "status": status,
            "symptom": symptom,
            "details": {"sync_rebuilds": sync, "cold_builds": cold,
                        "background_repacks": background,
                        "sync_noncold_rebuilds": storm,
                        "delta_served_queries": delta_serves,
                        "ann_below_default_dispatches": ann_drift,
                        "ann_below_default_total": ann_total,
                        "lex_prune_off_dispatches": lex_drift,
                        "lex_prune_off_total": lex_total,
                        "idle_mesh_devices": idle_devices,
                        "storming_indices": per_index},
        }
        if status != GREEN:
            doc["impacts"] = []
            doc["diagnosis"] = []
            if storm > 0:
                doc["impacts"].append(_impact(
                    "plane_serving:rebuild_storm", 1,
                    "Search requests stall behind full plane repacks "
                    "(O(postings) pack + device upload per refresh); p99 "
                    "collapses under live indexing.", ["search"]))
                doc["diagnosis"].append(_diagnosis(
                    "plane_serving:sync_rebuilds",
                    "Refreshes are invalidating serving planes faster "
                    "than the background repack absorbs them, or "
                    "delta-tier serving is disabled (ES_TPU_PLANE_DELTA"
                    "=0).",
                    "Re-enable delta serving, raise "
                    "ES_TPU_PLANE_DELTA_FRACTION, or lower the refresh "
                    "rate; watch es_plane_rebuild_total{mode=\"sync\"}.",
                    {"indices": sorted(per_index)}))
            if ann_drift > 0:
                doc["impacts"].append(_impact(
                    "plane_serving:ann_recall_drift", 3,
                    "kNN results may fall below the benched recall@k: "
                    "queries are probing fewer IVF clusters than the "
                    "knn_ivf_recall bench certified.", ["search"]))
                doc["diagnosis"].append(_diagnosis(
                    "plane_serving:ann_nprobe_below_default",
                    "Requests set [knn.nprobe] below the serving "
                    "default the recall bench measured.",
                    "Drop the explicit nprobe override (or re-bench "
                    "knn_ivf_recall at the lower nprobe and accept its "
                    "recall@k); watch "
                    "es_ann_nprobe_below_default_total."))
            if lex_drift > 0:
                doc["impacts"].append(_impact(
                    "plane_serving:lex_prune_drift", 3,
                    "Lexical queries are eager-scoring every posting of "
                    "a corpus the lexical_10m_prune bench serves "
                    "block-max pruned — latency runs over the benched "
                    "profile at large corpora.", ["search"]))
                doc["diagnosis"].append(_diagnosis(
                    "plane_serving:lex_prune_off",
                    "Requests set [prune]=false on an index whose "
                    "serving plane carries a block-max tier (results "
                    "are identical either way — pruning is rank-safe).",
                    "Drop the explicit prune override, or accept the "
                    "eager latency profile; watch "
                    "es_lex_blocks_skipped_total and "
                    "es_lex_prune_off_total."))
            if idle_devices > 0:
                doc["impacts"].append(_impact(
                    "plane_serving:mesh_underutilization", 3,
                    "Devices outside the serving mesh hold no corpus "
                    "partition and serve no queries — per-chip corpus "
                    "bytes and throughput are worse than the slice "
                    "could deliver.", ["search"]))
                doc["diagnosis"].append(_diagnosis(
                    "plane_serving:idle_mesh_devices",
                    "ES_TPU_MESH_SHARDS x ES_TPU_MESH_REPLICAS covers "
                    "fewer devices than the slice provides.",
                    "Raise ES_TPU_MESH_SHARDS (corpus capacity) or "
                    "ES_TPU_MESH_REPLICAS (query throughput) to cover "
                    "the slice; watch es_mesh_devices{state=\"idle\"}."))
        return doc

    #: tier transitions per health window that read as promotion churn
    #: (planes ping-ponging between HBM and host — the working set does
    #: not fit the configured budget)
    TIER_CHURN_YELLOW = 8
    TIER_CHURN_RED = 64

    def _ind_plane_tiers(self) -> dict:
        """Storage-tier pressure: per-tier resident bytes plus WINDOWED
        promote/demote churn (the ann-drift watermark pattern — the
        counters are cumulative, and latched yellow would make 'raise
        the budget' unverifiable). Steady demotion under a budget is by
        design; sustained promotion churn means the Zipf hot set is
        larger than the HBM budget and every probe is paying a
        host→device re-upload."""
        promotions = demotions = 0
        hot_b = warm_b = cold_b = 0
        warm_planes = cold_planes = 0
        budgeted = False
        for _name, svc in list(self.api.indices.indices.items()):
            try:
                tiers = svc.plane_cache.tiers
                st = tiers.stats()
            except Exception:   # noqa: BLE001 — no plane cache: skip
                continue
            budgeted = budgeted or tiers.enabled()
            promotions += st["promotions"]
            demotions += st["demotions"]
            hot_b += st["hot_bytes"]
            warm_b += st["warm_bytes"]
            cold_b += st["cold_bytes"]
            warm_planes += st["warm_planes"]
            cold_planes += st["cold_planes"]
        with _ANN_DRIFT_LOCK:
            seen = getattr(self.api, "_tier_churn_seen", None)
            total = promotions + demotions
            self.api._tier_churn_seen = total
            churn = 0 if seen is None else max(total - seen, 0)
        if churn >= self.TIER_CHURN_RED:
            status = RED
        elif churn >= self.TIER_CHURN_YELLOW:
            status = YELLOW
        else:
            status = GREEN
        doc = {
            "status": status,
            "symptom": (f"{churn} plane tier transitions since the last "
                        f"evaluation (promotion churn)."
                        if status != GREEN else
                        ("Plane storage tiers are stable under the "
                         "configured budgets." if budgeted else
                         "Plane tiering is not budget-constrained "
                         "(every plane device-resident).")),
            "details": {"tier_transitions_window": churn,
                        "promotions_total": promotions,
                        "demotions_total": demotions,
                        "hot_bytes": hot_b, "warm_bytes": warm_b,
                        "cold_bytes": cold_b,
                        "warm_planes": warm_planes,
                        "cold_planes": cold_planes,
                        "budgeted": budgeted},
        }
        if status != GREEN:
            doc["impacts"] = [_impact(
                "plane_tiers:promotion_churn", 2,
                "Serving planes ping-pong between HBM and host tiers: "
                "promoted planes are evicted before their next access, "
                "so dispatches repeatedly pay host→device streaming and "
                "re-upload instead of HBM-resident scans.", ["search"])]
            doc["diagnosis"] = [_diagnosis(
                "plane_tiers:working_set_over_budget",
                "The query mix's hot set is larger than "
                "ES_TPU_PLANE_HBM_BUDGET_BYTES: LRU demotion and demand "
                "promotion are fighting over the same planes.",
                "Raise ES_TPU_PLANE_HBM_BUDGET_BYTES (or add shard "
                "devices to shrink per-device plane bytes); watch "
                "es_plane_tier_promotions_total vs "
                "es_plane_tier_bytes{tier=\"hot\"}.")]
        return doc

    def _ind_compile_churn(self) -> dict:
        from . import telemetry as _tm
        compiles = _tm.compile_count()
        live_warmed = 0
        doc_reg = _tm.DEFAULT.stats_doc().get(
            "es_plane_serving_warmed_shapes_total")
        if doc_reg:
            live_warmed = int(sum(s["value"]
                                  for s in doc_reg["series"]))
        # warmed credit comes from the PROCESS-CUMULATIVE counter
        # (telemetry.record_warmed_shapes), not the live batchers'
        # rollup: per-batcher credits die with their weakref'd
        # collectors when a generation retires, so a repack inside one
        # window would otherwise cancel its replacement's warmup credit
        # and read as phantom churn.
        warmed = max(_tm.warmed_shapes_count(), live_warmed)
        # windowed against the previous health evaluation (watermark on
        # the api object, the ann-drift pattern above): both counters
        # are monotone, so churn is judged on compiles SINCE the last
        # evaluation vs warmed since the last evaluation; the first
        # evaluation baselines the watermark (process history has no
        # matching warmed history).
        if self.api is not None:
            with _ANN_DRIFT_LOCK:
                seen_c = getattr(self.api, "_compile_seen", None)
                seen_w = getattr(self.api, "_warmed_seen", 0)
                self.api._compile_seen = compiles
                self.api._warmed_seen = warmed
            if seen_c is None:
                excess = 0
            else:
                excess = max((compiles - seen_c)
                             - max(warmed - seen_w, 0), 0)
        else:
            excess = max(compiles - warmed, 0)
        if excess > self.COMPILE_RED:
            status = RED
        elif excess > self.COMPILE_SLACK:
            status = YELLOW
        else:
            status = GREEN
        doc = {
            "status": status,
            "symptom": ("XLA compiles are covered by the warmup "
                        "lattice." if status == GREEN else
                        f"{excess} XLA compiles landed outside the "
                        f"warmup lattice (steady-state compile churn)."),
            "details": {"compiles_total": compiles,
                        "warmed_shapes_total": warmed,
                        "excess_compiles": excess},
        }
        if status != GREEN:
            doc["impacts"] = [_impact(
                "compile_churn:first_hit_compiles", 2,
                "First requests of an uncompiled shape pay multi-second "
                "XLA compiles mid-traffic (serving p99 spikes).",
                ["search"])]
            doc["diagnosis"] = [_diagnosis(
                "compile_churn:unwarmed_shapes",
                "Serving dispatches hit input shapes the warmup lattice "
                "never pre-compiled (new k buckets, ragged batch sizes, "
                "or ES_TPU_SERVING_WARMUP=0).",
                "Check es_xla_compiles_by_shape_total for the offending "
                "shapes and widen the warmup ks / batch lattice.")]
        return doc

    def _ind_breakers(self) -> dict:
        from .breakers import DEFAULT as svc
        tripped = {}
        details = {}
        for name, st in svc.stats().items():
            details[name] = {
                "estimated_bytes": st["estimated_size_in_bytes"],
                "limit_bytes": st["limit_size_in_bytes"],
                "tripped": st["tripped"]}
            if st["tripped"]:
                tripped[name] = st["tripped"]
        if tripped.get("parent"):
            status = RED
        elif tripped:
            status = YELLOW
        else:
            status = GREEN
        doc = {
            "status": status,
            "symptom": ("No circuit breakers have tripped."
                        if status == GREEN else
                        f"Circuit breakers tripped: "
                        f"{', '.join(sorted(tripped))}."),
            "details": details,
        }
        if status != GREEN:
            doc["impacts"] = [_impact(
                "breakers:rejections", 1 if status == RED else 2,
                "Requests over the tripped budget are rejected with "
                "429 circuit_breaking_exception.", ["search", "ingest"])]
            doc["diagnosis"] = [_diagnosis(
                "breakers:memory_pressure",
                f"Memory budgets exhausted on "
                f"{', '.join(sorted(tripped))}.",
                "Reduce concurrent request size/fan-out, shrink "
                "fielddata usage, or raise the breaker limits.")]
        return doc

    def _ind_indexing_pressure(self) -> dict:
        from .indexing_pressure import DEFAULT as ip
        frac = (ip.current_bytes / ip.limit_bytes) if ip.limit_bytes else 0
        if ip.rejections and frac >= self.PRESSURE_YELLOW_FRACTION:
            status = RED
        elif ip.rejections or frac >= self.PRESSURE_YELLOW_FRACTION:
            status = YELLOW
        else:
            status = GREEN
        doc = {
            "status": status,
            "symptom": ("Indexing pressure is within budget."
                        if status == GREEN else
                        f"Indexing pressure degraded: {ip.rejections} "
                        f"rejections, {int(frac * 100)}% of the byte "
                        f"budget in flight."),
            "details": {"current_bytes": ip.current_bytes,
                        "limit_bytes": ip.limit_bytes,
                        "total_bytes": ip.total_bytes,
                        "rejections": ip.rejections},
        }
        if status != GREEN:
            doc["impacts"] = [_impact(
                "indexing_pressure:rejections", 2,
                "Bulk/index requests beyond the byte budget are "
                "rejected with 429.", ["ingest"])]
            doc["diagnosis"] = [_diagnosis(
                "indexing_pressure:saturation",
                "Concurrent indexing payload bytes exceed the node's "
                "indexing-pressure budget.",
                "Reduce bulk concurrency/size or add indexing "
                "capacity.")]
        return doc

    def _ind_slo_burn(self) -> dict:
        """SLO burn-rate watchdog (``common/flightrec.py``): multi-window
        burn over ``es_slo_burn_rate{window}`` — red means BOTH the fast
        and slow windows burned past the threshold and an automatic
        post-mortem capture fired (``GET /_flight_recorder/captures``);
        yellow means one window is burning (onset, or the slow window
        still draining through recovery)."""
        from . import flightrec
        wd = flightrec.get_watchdog()
        if wd is None:
            return {"status": GREEN,
                    "symptom": "The SLO watchdog is disabled "
                               "(ES_TPU_WATCHDOG=0).",
                    "details": {"watchdog": "disabled"}}
        st = wd.status_doc()
        status = {flightrec.GREEN: GREEN, flightrec.YELLOW: YELLOW,
                  flightrec.RED: RED}.get(st.get("status"), UNKNOWN)
        rates = st.get("burn_rates") or {}
        fast = (rates.get("fast") or {}).get("burn", 0.0)
        slow = (rates.get("slow") or {}).get("burn", 0.0)
        doc = {
            "status": status,
            "symptom": ("Error-budget burn is within the SLO."
                        if status == GREEN else
                        f"SLO burn rate fast={fast} slow={slow} "
                        f"(red threshold {wd.engine.burn_red}); "
                        f"{st.get('captures', 0)} post-mortem capture(s) "
                        f"retained."),
            "details": {"burn_rates": rates,
                        "burn_red_threshold": wd.engine.burn_red,
                        "latency_threshold_ms":
                            wd.engine.latency_threshold_ms,
                        "windows_s": {"fast": wd.engine.fast_s,
                                      "slow": wd.engine.slow_s},
                        "captures": st.get("captures", 0),
                        "watchdog_running": st.get("running", False)},
        }
        if status not in (GREEN, UNKNOWN):
            doc["impacts"] = [_impact(
                "slo_burn:error_budget", 1 if status == RED else 2,
                "Queries are breaching the latency/failure SLO fast "
                "enough to exhaust the error budget; users are seeing "
                "slow or failed searches now.", ["search"])]
            doc["diagnosis"] = [_diagnosis(
                "slo_burn:degradation",
                "Sustained latency over the SLO threshold or elevated "
                "search failover/retry rates across both burn windows.",
                "Read the automatic capture (GET /_flight_recorder/"
                "captures — hot threads, journal slice, batcher queue "
                "depths taken AT the red transition) and watch "
                "es_slo_burn_rate{window} + es_watchdog_captures_total.")]
        return doc

    def _ind_query_insights(self) -> dict:
        """Query-shape dominance (``search/query_insight.py``): yellow
        when one query shape OR one tenant accounts for more than the
        configured fraction (``insights.dominance_fraction`` /
        ``ES_TPU_INSIGHTS_DOMINANCE``, default 0.5) of the windowed
        device-ms on this node — the "one tenant's 10M-doc agg starves
        point queries" signal, with the shape id and its retained
        sample body in the diagnosis so the offending request is
        reproducible without log archaeology. Windows below the
        observation volume floor carry no signal (the SLO engine's
        min_window_queries shape)."""
        from ..search import query_insight as _qi
        if not _qi.insights_enabled():
            return {"status": GREEN,
                    "symptom": "Query insights are disabled "
                               "(ES_TPU_INSIGHTS=0).",
                    "details": {"insights": "disabled"}}
        store = _qi.store_for(getattr(self.api, "node_id", None))
        dom = store.dominance()
        frac_limit = _qi.dominance_fraction()
        min_obs = _qi.min_window_observations()
        obs = int(dom.get("observations", 0))
        details = {"dominance": dom,
                   "dominance_fraction_threshold": frac_limit,
                   "min_window_observations": min_obs}
        if obs < min_obs:
            return {"status": GREEN,
                    "symptom": f"Below the insight volume floor "
                               f"({obs}/{min_obs} windowed "
                               f"observations): no dominance signal.",
                    "details": details}
        offenders = []
        for dim in ("shape", "tenant"):
            ent = dom.get(dim)
            if ent and float(ent.get("fraction", 0.0)) > frac_limit:
                offenders.append((dim, ent))
        if not offenders:
            return {"status": GREEN,
                    "symptom": "No query shape or tenant dominates the "
                               "windowed device time.",
                    "details": details}
        dim, ent = offenders[0]
        key = ent.get("key")
        frac_pct = round(float(ent.get("fraction", 0.0)) * 100, 1)
        doc = {
            "status": YELLOW,
            "symptom": (f"One {dim} [{key}] accounts for {frac_pct}% "
                        f"of windowed device time (threshold "
                        f"{round(frac_limit * 100, 1)}%)."),
            "details": details,
            "impacts": [_impact(
                "query_insights:dominance", 2,
                "A single query shape or tenant is consuming most of "
                "the device budget; other tenants' queries queue "
                "behind its dispatches.", ["search"])],
        }
        affected = {dim: [key] if key else []}
        sample = ent.get("sample")
        if sample is not None:
            affected["sample_body"] = sample
        doc["diagnosis"] = [_diagnosis(
            "query_insights:dominance",
            f"The {dim} [{key}] burned "
            f"{ent.get('device_ms', 0)} device-ms of the recent "
            f"insight windows — {frac_pct}% of the node total.",
            "Inspect GET /_insights/top_queries (the shape's exemplar "
            "trace id links to GET /_trace/{id}); throttle or rewrite "
            "the offending request, or isolate the tenant.",
            affected)]
        return doc

    def _ind_qos(self) -> dict:
        """Multi-tenant QoS (``common/qos.py``): green while the edge
        admits everything, yellow while load shedding is engaged (the
        cluster is deliberately bouncing non-interactive traffic with
        429s), red when shedding has stayed engaged past
        ``qos.shed.sustained_seconds`` — sustained shedding means the
        overload is not draining and interactive traffic is next. The
        diagnosis names the dominant shed tenant so the abusive
        workload is actionable, and the trigger evidence (queue depth,
        breaker fraction, SLO burn) rides in the details — the same
        evidence each ``qos_shed`` flight-recorder event carries."""
        from . import qos as _qos
        doc = _qos.controller().status_doc()
        details = {"qos": doc}
        if not doc.get("enabled", True):
            return {"status": GREEN,
                    "symptom": "QoS admission control is disabled "
                               "(ES_TPU_QOS=0).",
                    "details": details}
        if not doc.get("engaged"):
            return {"status": GREEN,
                    "symptom": "No load shedding: all tenants within "
                               "their token budgets.",
                    "details": details}
        sheds = doc.get("sheds_by_tenant") or {}
        top_tenant = max(sheds, key=lambda t: sheds[t]) if sheds else None
        sustained = bool(doc.get("sustained"))
        engaged_for = doc.get("engaged_for_s", 0.0)
        status = RED if sustained else YELLOW
        severity = 1 if sustained else 2
        out = {
            "status": status,
            "symptom": (f"Load shedding has been engaged for "
                        f"{engaged_for}s"
                        + (" (sustained past the "
                           "qos.shed.sustained_seconds bound)."
                           if sustained else ".")),
            "details": details,
            "impacts": [_impact(
                "qos:shedding", severity,
                "The REST edge is rejecting bulk/analytics traffic "
                "with 429s to protect interactive latency"
                + ("; sustained shedding means the overload is not "
                   "draining and interactive requests shed next."
                   if sustained else "."),
                ["search", "ingest"])],
        }
        affected = {"tenants": [top_tenant] if top_tenant else []}
        cause = (f"Overload signals tripped the shed state machine: "
                 f"{doc.get('signals')}.")
        if top_tenant is not None:
            cause += (f" Tenant [{top_tenant}] absorbed the most sheds "
                      f"({sheds[top_tenant]}).")
        out["diagnosis"] = [_diagnosis(
            "qos:shedding", cause,
            "Inspect GET /_flight_recorder?type=qos_shed for the "
            "engage evidence and GET /_insights/top_queries for the "
            "shed-heavy shapes; throttle the dominant tenant "
            "(qos.tenant.refill_per_s) or raise capacity.",
            affected)]
        return out

    def _ind_dispatch_efficiency(self) -> dict:
        """Continuous roofline audit (``common/roofline.py``): every
        serving dispatch's achieved bandwidth is compared against the
        ROOFLINE.md bytes model; this indicator judges the windowed
        mean efficiency per kernel family SINCE the last evaluation
        (the compile_churn windowed-watermark pattern — the underlying
        accumulators are process-cumulative). Yellow means a kernel's
        window drifted below the floor: an explicit
        ``dispatch_efficiency.floor_pct`` / ``ES_TPU_DISPATCH_EFF_
        FLOOR_PCT`` when set, else ``drift_fraction`` of the session's
        best windowed mean for that kernel (auto mode — absolute
        efficiency differs per backend, drift does not). Windows below
        the ``min_dispatches`` volume floor carry no signal and are NOT
        consumed, so trickle traffic accumulates until judgeable (the
        SLO engine's min_window_queries shape). Status transitions are
        journaled to the flight recorder."""
        from . import flightrec as _fr
        from . import roofline as _rl
        totals = _rl.audit_totals()
        floor = _rl.efficiency_floor_pct()
        drift_frac = _rl.efficiency_drift_fraction()
        min_d = _rl.efficiency_min_dispatches()
        drifting: Dict[str, dict] = {}
        kernels: Dict[str, dict] = {}
        with _ANN_DRIFT_LOCK:
            seen = dict(getattr(self.api, "_eff_seen", {}))
            baselines = dict(getattr(self.api, "_eff_baseline", {}))
            for kern, (n, s) in sorted(totals.items()):
                n0, s0 = seen.get(kern, (0, 0.0))
                wn, ws = n - n0, s - s0
                if wn < min_d:
                    # below the volume floor: no signal, window NOT
                    # consumed (one slow dispatch on an idle node is a
                    # blip, not drift)
                    kernels[kern] = {"window_dispatches": wn,
                                     "pending": True}
                    continue
                mean = ws / wn
                seen[kern] = (n, s)
                base = baselines.get(kern)
                thr = floor if floor > 0 else (
                    base * drift_frac if base is not None else None)
                # watermark: the best windowed mean seen this session
                # (a drifting window sits below it and never lowers it)
                baselines[kern] = mean if base is None \
                    else max(base, mean)
                kernels[kern] = {
                    "window_dispatches": wn,
                    "window_mean_pct": round(mean, 3),
                    "baseline_pct": round(baselines[kern], 3),
                    "threshold_pct": round(thr, 3)
                    if thr is not None else None}
                if thr is not None and mean < thr:
                    drifting[kern] = kernels[kern]
            self.api._eff_seen = seen
            self.api._eff_baseline = baselines
            prev = getattr(self.api, "_eff_status", GREEN)
            status = YELLOW if drifting else GREEN
            self.api._eff_status = status
        if status != prev:
            _fr.record("dispatch_efficiency",
                       transition=f"{prev}->{status}",
                       kernels=sorted(drifting))
        doc = {
            "status": status,
            "symptom": ("Dispatch bandwidth tracks the roofline model."
                        if status == GREEN else
                        f"Kernel(s) {', '.join(sorted(drifting))} ran "
                        f"below the roofline efficiency floor over the "
                        f"last window."),
            "details": {"kernels": kernels,
                        "floor_pct": floor,
                        "drift_fraction": drift_frac,
                        "min_window_dispatches": min_d,
                        "peak_bandwidth_gbps":
                            _rl.peak_bandwidth_gbps()},
        }
        if status != GREEN:
            doc["impacts"] = [_impact(
                "dispatch_efficiency:bandwidth_drift", 3,
                "Dispatches are moving their modeled bytes slower than "
                "this machine has demonstrated it can — latency and "
                "throughput are degraded relative to the same "
                "hardware's own recent baseline.", ["search"])]
            doc["diagnosis"] = [_diagnosis(
                "dispatch_efficiency:below_floor",
                "Sustained per-dispatch bandwidth below the configured "
                "floor (or the session's watermark): device/host "
                "contention, a throttled container, or a kernel "
                "regression.",
                "Read GET /_profiler/timeline for the dispatch "
                "timeline (queue/prep/execute/fetch overlap per "
                "dispatcher thread) and watch "
                "es_dispatch_efficiency_pct{kernel} / "
                "es_dispatch_bandwidth_gbps{kernel}.",
                {"kernels": sorted(drifting)})]
        return doc

    def _ind_task_backlog(self) -> dict:
        tm = self.api.task_manager
        with tm.lock:
            live = list(tm.tasks.values())
        now = time.time()
        # monitor-lane tasks (including the health-report request
        # itself) are not backlog
        others = [t for t in live if ":monitor/" not in t.action]
        count = len(others)
        oldest_s = max((now - t.start_time for t in others), default=0.0)
        if count > self.BACKLOG_RED or oldest_s > self.OLDEST_TASK_RED_S:
            status = RED
        elif count > self.BACKLOG_YELLOW or \
                oldest_s > self.OLDEST_TASK_YELLOW_S:
            status = YELLOW
        else:
            status = GREEN
        doc = {
            "status": status,
            "symptom": ("The task backlog is nominal."
                        if status == GREEN else
                        f"{count} live tasks; oldest has run "
                        f"{oldest_s:.0f}s."),
            "details": {"running_tasks": len(live),
                        "running_non_monitor_tasks": count,
                        "oldest_task_age_seconds": round(oldest_s, 1)},
        }
        if status != GREEN:
            doc["impacts"] = [_impact(
                "task_backlog:queueing", 3,
                "Requests queue behind a deep task backlog; latency "
                "grows.", ["search", "ingest"])]
            doc["diagnosis"] = [_diagnosis(
                "task_backlog:long_running",
                "Long-running or piling-up tasks (check "
                "GET /_tasks?detailed for their resource_stats).",
                "Cancel runaway tasks via POST /_tasks/{id}/_cancel or "
                "add capacity.")]
        return doc


def merge_reports(local: dict, remote_docs: Dict[str, dict]) -> dict:
    """Cluster fan-in: fold per-node reports into one (the reference
    computes indicators on the coordinating node from cluster state;
    here each node evaluates its registry-local view and the front takes
    the worst per indicator, keeping a per-node status map in details).
    ``remote_docs``: node_id -> that node's local report."""
    merged = {"cluster_name": local.get("cluster_name"),
              "indicators": {}}
    all_docs = dict(remote_docs)
    names = set(local.get("indicators", ()))
    for doc in all_docs.values():
        names.update(doc.get("indicators", ()))
    for name in sorted(names):
        per_node = {}
        worst_doc = None
        worst = GREEN
        for node_id, rep in all_docs.items():
            ind = (rep.get("indicators") or {}).get(name)
            if not ind:
                continue
            per_node[node_id] = ind.get("status", UNKNOWN)
            if worst_doc is None or \
                    _RANK.get(ind.get("status"), 1) > _RANK.get(worst, 1):
                worst_doc = ind
                worst = ind.get("status", UNKNOWN)
        out = dict(worst_doc or {"status": UNKNOWN,
                                 "symptom": "no node reported"})
        details = dict(out.get("details") or {})
        details["nodes"] = per_node
        out["details"] = details
        merged["indicators"][name] = out
    merged["status"] = worst_status(
        d["status"] for d in merged["indicators"].values())
    return merged
