"""Indexing pressure: a byte budget on concurrent indexing work.

Reference: ``index/IndexingPressure.java:31`` — every bulk/index request
reserves its payload bytes against ``indexing_pressure.memory.limit``
(default 10% heap) for its whole lifetime; requests beyond the budget are
rejected with 429 ``es_rejected_execution_exception`` instead of letting
host memory grow unboundedly. Stats surface under nodes stats
``indexing_pressure.memory``.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from .errors import ElasticsearchError

#: default budget — a fixed figure standing in for "10% of heap"
DEFAULT_LIMIT_BYTES = 512 * 1024 * 1024


class EsRejectedExecutionError(ElasticsearchError):
    status = 429
    error_type = "es_rejected_execution_exception"


class IndexingPressure:
    def __init__(self, limit_bytes: int = DEFAULT_LIMIT_BYTES):
        self.limit_bytes = int(limit_bytes)
        self._lock = threading.Lock()
        self.current_bytes = 0
        self.total_bytes = 0
        self.rejections = 0

    @contextmanager
    def coordinating(self, bytes_: int, desc: str = "bulk"):
        """Reserve ``bytes_`` for the scope of one indexing operation;
        raises 429 when the budget is exhausted."""
        bytes_ = max(int(bytes_), 0)
        with self._lock:
            if self.current_bytes + bytes_ > self.limit_bytes:
                self.rejections += 1
                cur = self.current_bytes
                raise EsRejectedExecutionError(
                    f"rejected execution of {desc} ["
                    f"coordinating_and_primary_bytes={cur}, "
                    f"operation_bytes={bytes_}, "
                    f"max_coordinating_and_primary_bytes="
                    f"{self.limit_bytes}]")
            self.current_bytes += bytes_
            self.total_bytes += bytes_
        try:
            yield
        finally:
            with self._lock:
                self.current_bytes -= bytes_

    def stats_doc(self) -> dict:
        def shape(n: int) -> dict:
            return {"combined_coordinating_and_primary_in_bytes": n,
                    "coordinating_in_bytes": n, "primary_in_bytes": 0,
                    "replica_in_bytes": 0, "all_in_bytes": n}
        return {"memory": {
            "current": shape(self.current_bytes),
            "total": dict(shape(self.total_bytes),
                          coordinating_rejections=self.rejections,
                          primary_rejections=0, replica_rejections=0),
            "limit_in_bytes": self.limit_bytes,
        }}


#: process-wide default (same documented-singleton pattern as breakers)
DEFAULT = IndexingPressure()
