"""Data-only wire codec for structured intra-cluster payloads.

The reference never ships native object serialization between nodes: every
message is a versioned, hand-rolled structured format
(``common/io/stream/StreamInput.java`` — data in, data out, no code).
Aggregation partials here are arbitrary nested Python data (dicts with
non-string keys, tuples, numpy arrays); ``pickle`` would round-trip them
but gives any peer that can reach the transport port arbitrary code
execution. This codec covers exactly the closed set of data shapes the
aggregators produce and nothing else — decoding cannot instantiate
arbitrary classes.

Encoding: every container is a tagged JSON array ``[tag, payload...]``;
plain scalars (None/bool/int/float/str) encode as themselves. Since no
aggregator partial contains a *bare* JSON array or object (they all pass
through :func:`encode`), decoding is unambiguous.
"""

from __future__ import annotations

import base64
import json
from typing import Any

import numpy as np

__all__ = ["dumps_b64", "loads_b64", "encode", "decode"]


def encode(o: Any):
    if o is None or isinstance(o, (bool, int, str)):
        return o
    if isinstance(o, float):
        return o                           # Python json handles nan/inf
    if isinstance(o, np.generic):
        return encode(o.item())
    if isinstance(o, dict):
        return ["D", [[encode(k), encode(v)] for k, v in o.items()]]
    if isinstance(o, list):
        return ["L", [encode(x) for x in o]]
    if isinstance(o, tuple):
        return ["T", [encode(x) for x in o]]
    if isinstance(o, (set, frozenset)):
        return ["S", [encode(x) for x in sorted(o, key=repr)]]
    if isinstance(o, (bytes, bytearray)):
        return ["B", base64.b64encode(bytes(o)).decode()]
    if isinstance(o, np.ndarray):
        c = np.ascontiguousarray(o)
        return ["A", str(c.dtype), list(c.shape),
                base64.b64encode(c.tobytes()).decode()]
    raise TypeError(
        f"not wire-encodable (data-only codec): {type(o).__name__}")


def decode(o: Any):
    if o is None or isinstance(o, (bool, int, float, str)):
        return o
    if isinstance(o, list) and o and isinstance(o[0], str):
        tag = o[0]
        if tag == "D":
            out = {}
            for k, v in o[1]:
                key = decode(k)
                if isinstance(key, list):
                    key = tuple(key)       # dict keys must be hashable
                out[key] = decode(v)
            return out
        if tag == "L":
            return [decode(x) for x in o[1]]
        if tag == "T":
            return tuple(decode(x) for x in o[1])
        if tag == "S":
            return {decode(x) for x in o[1]}
        if tag == "B":
            return base64.b64decode(o[1])
        if tag == "A":
            _, dtype, shape, b = o
            return np.frombuffer(
                base64.b64decode(b), dtype=np.dtype(dtype)).reshape(shape)
    raise ValueError("malformed data-codec payload")


def dumps_b64(o: Any) -> str:
    return base64.b64encode(
        json.dumps(encode(o), allow_nan=True).encode()).decode()


def loads_b64(s: str):
    return decode(json.loads(base64.b64decode(s or "") or b"null"))
