"""Runtime race witness: happens-before + lockset, checked.

The static analyzer (``devtools/rules_races``, ESTP-R01/R02) proves
lockset coverage at the AST; this module is the runtime half of the
cross-check, an Eraser × FastTrack hybrid scaled to the package's needs:

- **vector clocks** per thread, advanced on every witnessed lock
  release and joined on acquire (a release→acquire pair on one lock is
  a happens-before edge), plus fork edges — a package-created
  ``threading.Thread`` child starts with its parent's clock, and
  ``join()`` merges the child's final clock back into the joiner;
- **locksets** per tracked key: the set of witnessed locks held at the
  access, intersected Eraser-style across that key's access history.

A CANDIDATE RACE is reported when two accesses to one key, at least one
a write, are (a) unordered by happens-before AND (b) share no lock.
Requiring both kills the two classic false-positive families: the
lockset alone flags publication patterns (init → fork, result → done
flip under a condition), and happens-before alone misses races the
schedule happened not to exercise — a lock-free access pair that
*today* ran in a benign order still has an empty lockset and only
escapes when an HB edge genuinely orders it.

Tracking is OPT-IN per access site: package code calls
:func:`note_read`/:func:`note_write` (no-ops unless the witness is
installed — one module-global load and a truth test on the serving
path) on the shared state the static family audits: the serving-plane
generation registry, the micro-batcher stats, the monitoring tick.
``key`` should be ``(logical_name, id(owner))`` — :func:`note_read`
builds that from its ``owner=`` argument — so two instances never
cross-contaminate locksets.

Semantics:

- ``ES_TPU_RACEDEP=record`` collects candidates
  (``report()["candidates"]``, both access stacks included);
  ``ES_TPU_RACEDEP=raise`` (or ``1``/``true``) raises
  :class:`CandidateDataRace` at the second access. ``install()`` is
  called by ``tests/conftest.py`` BEFORE package modules create their
  locks (it force-installs the lockdep witness to see lock events and
  wraps ``threading.Thread`` for package-frame creators to see fork/
  join edges).
- Evidence exports as the ``es_racedep_*`` telemetry families
  (TELEMETRY.md): tracked keys, witnessed accesses, threads carrying a
  vector clock, and candidate races (must stay 0).

Known limits (documented, conservative in the false-NEGATIVE direction
— the witness never invents a race): executor worker threads are
created by stdlib frames and carry no fork edge (their first witnessed
lock acquire seeds their clock); only witnessed (package-created) locks
contribute lockset/HB evidence; only instrumented sites are checked.
"""

from __future__ import annotations

import os
import threading
import traceback
import weakref
from typing import Dict, List, Optional, Tuple

from . import lockdep

# The Thread wrappers below call ``lockdep._package_caller()`` from
# THIS file's frames: without skipping them, every ``Thread.start`` in
# the process would look package-made and earn a fork edge — and a
# spurious fork edge ORDERS accesses, silently masking real races.
if os.path.abspath(__file__) not in lockdep._SKIP_FILES:
    lockdep._SKIP_FILES = lockdep._SKIP_FILES + (
        os.path.abspath(__file__),)

__all__ = ["CandidateDataRace", "RaceWitness", "WITNESS", "install",
           "uninstall", "installed", "note_read", "note_write", "report",
           "reset"]

#: bounded candidate evidence ring
_MAX_CANDIDATES = 64

#: frames kept per access stack (evidence, not a profiler)
_STACK_DEPTH = 6


class CandidateDataRace(RuntimeError):
    """Two unordered, lock-disjoint accesses (≥1 write) to one key."""


def _clock_leq(a: Dict[int, int], b: Dict[int, int]) -> bool:
    """a ≤ b pointwise — every event a has seen, b has seen."""
    for tid, c in a.items():
        if c > b.get(tid, 0):
            return False
    return True


class _Access:
    __slots__ = ("tid", "tname", "write", "clock", "lockset", "stack")

    def __init__(self, tid: int, tname: str, write: bool,
                 clock: Dict[int, int], lockset: frozenset, stack: str):
        self.tid = tid
        self.tname = tname
        self.write = write
        self.clock = clock
        self.lockset = lockset
        self.stack = stack


class _KeyState:
    __slots__ = ("last_by_tid", "reported")

    def __init__(self):
        #: last access per thread (the FastTrack-style bounded history:
        #: an access ordered after a thread's LAST access is ordered
        #: after all its earlier ones)
        self.last_by_tid: Dict[int, _Access] = {}
        self.reported = False


class RaceWitness:
    """Process-wide happens-before + lockset race witness."""

    def __init__(self, raise_on_race: Optional[bool] = None):
        if raise_on_race is None:
            raise_on_race = os.environ.get(
                "ES_TPU_RACEDEP", "").lower() not in ("record",)
        self.raise_on_race = raise_on_race
        # the witness's own mutex must be the REAL primitive: it is
        # taken from inside every hooked acquire — a witnessed lock here
        # would both recurse and pollute every tracked lockset
        self._mutex = lockdep._REAL_RLOCK()
        self._tls = threading.local()
        #: lock name -> clock snapshot at its last release
        self._lock_clocks: Dict[str, Dict[int, int]] = {}
        self._keys: Dict[object, _KeyState] = {}
        self.candidates: List[dict] = []
        self.candidate_count = 0
        self.accesses = 0
        self.threads_witnessed = 0
        self.fork_edges = 0

    # -- per-thread state ----------------------------------------------------

    def _state(self):
        st = getattr(self._tls, "st", None)
        if st is None:
            tid = threading.get_ident()
            seed = _FORK_SEEDS.pop(threading.current_thread(), None)
            clock = dict(seed) if seed else {}
            clock[tid] = clock.get(tid, 0) + 1
            st = self._tls.st = {"clock": clock, "held": []}
            with self._mutex:
                self.threads_witnessed += 1
                if seed:
                    self.fork_edges += 1
        return st

    # -- lock hooks (driven by the lockdep witness) --------------------------

    def on_acquire(self, name: str) -> None:
        st = self._state()
        st["held"].append(name)
        with self._mutex:
            rel = self._lock_clocks.get(name)
        if rel:
            clock = st["clock"]
            for tid, c in rel.items():
                if c > clock.get(tid, 0):
                    clock[tid] = c

    def on_release(self, name: str) -> None:
        st = self._state()
        held = st["held"]
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break
        tid = threading.get_ident()
        clock = st["clock"]
        clock[tid] = clock.get(tid, 0) + 1
        with self._mutex:
            self._lock_clocks[name] = dict(clock)

    # -- fork/join edges -----------------------------------------------------

    def on_fork(self, parent_clock: Dict[int, int],
                child: threading.Thread) -> None:
        _FORK_SEEDS[child] = dict(parent_clock)

    def on_join(self, child_final: Dict[int, int]) -> None:
        st = self._state()
        clock = st["clock"]
        for tid, c in child_final.items():
            if c > clock.get(tid, 0):
                clock[tid] = c

    def thread_clock(self) -> Dict[int, int]:
        return dict(self._state()["clock"])

    # -- tracked accesses ----------------------------------------------------

    def access(self, key: object, write: bool) -> None:
        st = self._state()
        tid = threading.get_ident()
        cur = _Access(tid, threading.current_thread().name, write,
                      dict(st["clock"]), frozenset(st["held"]),
                      "".join(traceback.format_stack(limit=_STACK_DEPTH)
                              [:-1]))
        race_doc = None
        with self._mutex:
            self.accesses += 1
            ks = self._keys.get(key)
            if ks is None:
                ks = self._keys[key] = _KeyState()
            for prev in ks.last_by_tid.values():
                if prev.tid == tid:
                    continue
                if not (prev.write or cur.write):
                    continue
                if prev.lockset & cur.lockset:
                    continue          # a common lock serializes them
                if _clock_leq(prev.clock, cur.clock):
                    continue          # ordered by happens-before
                if ks.reported:
                    break
                ks.reported = True
                self.candidate_count += 1
                race_doc = {
                    "key": repr(key),
                    "kind": ("write/write" if prev.write and cur.write
                             else "read/write"),
                    "first": {"thread": prev.tname,
                              "write": prev.write,
                              "lockset": sorted(prev.lockset),
                              "stack": prev.stack},
                    "second": {"thread": cur.tname,
                               "write": cur.write,
                               "lockset": sorted(cur.lockset),
                               "stack": cur.stack},
                }
                if len(self.candidates) < _MAX_CANDIDATES:
                    self.candidates.append(race_doc)
                break
            ks.last_by_tid[tid] = cur
        if race_doc is not None and self.raise_on_race:
            raise CandidateDataRace(
                f"candidate data race on {race_doc['key']} "
                f"({race_doc['kind']}): {race_doc['first']['thread']} "
                f"(lockset {race_doc['first']['lockset']}) vs "
                f"{race_doc['second']['thread']} (lockset "
                f"{race_doc['second']['lockset']}) — unordered by "
                f"happens-before and no common lock\n"
                f"first stack:\n{race_doc['first']['stack']}"
                f"second stack:\n{race_doc['second']['stack']}")

    # -- evidence ------------------------------------------------------------

    def report(self) -> dict:
        with self._mutex:
            return {
                "tracked_keys": len(self._keys),
                "accesses": self.accesses,
                "threads_witnessed": self.threads_witnessed,
                "fork_edges": self.fork_edges,
                "candidates": list(self.candidates),
                "candidate_count": self.candidate_count,
            }

    def reset(self) -> None:
        """Drop candidates + key history (tests); clocks/locks survive."""
        with self._mutex:
            self._keys.clear()
            self.candidates.clear()
            self.candidate_count = 0

    def telemetry_doc(self) -> dict:
        return {
            "es_racedep_tracked_keys": {
                "type": "gauge",
                "help": "shared-state keys under the race witness",
                "samples": [({}, len(self._keys))]},
            "es_racedep_accesses_total": {
                "type": "counter",
                "help": "witnessed tracked-state accesses",
                "samples": [({}, self.accesses)]},
            "es_racedep_threads_witnessed": {
                "type": "gauge",
                "help": "threads carrying a racedep vector clock",
                "samples": [({}, self.threads_witnessed)]},
            "es_racedep_candidate_races_total": {
                "type": "counter",
                "help": "unordered lock-disjoint access pairs with a "
                        "write (must stay 0)",
                "samples": [({}, self.candidate_count)]},
        }


#: process-wide witness
WITNESS = RaceWitness()

#: child Thread -> parent clock snapshot at start() (fork edges).
#: Weak-keyed: a forked thread that never touches a witnessed lock or
#: tracked key never pops its seed — the entry must die with the Thread
#: object, not pin it.
_FORK_SEEDS: "weakref.WeakKeyDictionary[threading.Thread, Dict[int, int]]" \
    = weakref.WeakKeyDictionary()

_INSTALLED = False
_REAL_START = threading.Thread.start
_REAL_RUN = threading.Thread.run
_REAL_JOIN = threading.Thread.join

#: threads forked by package frames (fork-edge tracked); value True
#: until the thread exits, then its final clock. Weak-keyed so an
#: unjoined daemon thread (plane warmup) doesn't pin its Thread object
#: and clock forever — once nobody holds the Thread, nobody can join
#: it, so dropping the entry loses no edge.
_FORK_TRACKED: "weakref.WeakKeyDictionary[threading.Thread, object]" \
    = weakref.WeakKeyDictionary()


def _start(self) -> None:
    """``Thread.start`` wrapper: a package-frame start is a fork edge —
    the child begins with the parent's clock (stdlib/third-party starts
    are untouched: real behavior, no edge)."""
    if lockdep._package_caller():
        WITNESS.on_fork(WITNESS.thread_clock(), self)
        _FORK_TRACKED[self] = True
    _REAL_START(self)


def _run(self) -> None:
    try:
        _REAL_RUN(self)
    finally:
        if _FORK_TRACKED.get(self) is True:
            _FORK_TRACKED[self] = WITNESS.thread_clock()


def _join(self, timeout: Optional[float] = None) -> None:
    _REAL_JOIN(self, timeout)
    if not self.is_alive():
        final = _FORK_TRACKED.pop(self, None)
        if isinstance(final, dict):
            WITNESS.on_join(final)


def enabled_by_env() -> bool:
    return os.environ.get("ES_TPU_RACEDEP", "").lower() in (
        "1", "true", "record", "raise")


def install(force: bool = False) -> bool:
    """Activate the race witness: force-install the lockdep witness (it
    feeds lock acquire/release events through its hook list) and wrap
    ``threading.Thread.start/run/join`` so package-frame forks and joins
    carry happens-before edges (subclasses overriding ``run`` lose the
    exit-clock capture — their join still merges nothing, which is
    conservative). Call EARLY — ``tests/conftest.py`` does, before
    package module-level locks exist."""
    global _INSTALLED
    if not force and not enabled_by_env():
        return False
    if _INSTALLED:
        return True
    lockdep.install(force=True)
    if (WITNESS.on_acquire, WITNESS.on_release) not in lockdep.RACE_HOOKS:
        lockdep.RACE_HOOKS.append((WITNESS.on_acquire, WITNESS.on_release))
    threading.Thread.start = _start
    threading.Thread.run = _run
    threading.Thread.join = _join
    _INSTALLED = True
    _ensure_collector()
    return True


def uninstall() -> None:
    global _INSTALLED
    try:
        lockdep.RACE_HOOKS.remove((WITNESS.on_acquire, WITNESS.on_release))
    except ValueError:
        pass
    threading.Thread.start = _REAL_START
    threading.Thread.run = _REAL_RUN
    threading.Thread.join = _REAL_JOIN
    _FORK_TRACKED.clear()
    _INSTALLED = False


def installed() -> bool:
    return _INSTALLED


# -- the opt-in instrumentation surface -------------------------------------


def note_read(name: str, owner: object = None) -> None:
    """Record a read of the shared state ``name`` (scoped per ``owner``
    instance). No-op unless the witness is installed."""
    if _INSTALLED:
        WITNESS.access((name, id(owner)) if owner is not None else name,
                       write=False)


def note_write(name: str, owner: object = None) -> None:
    """Record a write — see :func:`note_read`."""
    if _INSTALLED:
        WITNESS.access((name, id(owner)) if owner is not None else name,
                       write=True)


def report() -> dict:
    return WITNESS.report()


def reset() -> None:
    WITNESS.reset()


_COLLECTOR_REGISTERED = False


def _ensure_collector() -> None:
    """Register the es_racedep_* collector once (lazy + fault-tolerant,
    same contract as lockdep's)."""
    global _COLLECTOR_REGISTERED
    if _COLLECTOR_REGISTERED:
        return
    try:
        from . import telemetry
        reg = getattr(telemetry, "DEFAULT", None)
        if reg is None:
            return
        reg.register_collector("racedep",
                               lambda: WITNESS.telemetry_doc())
        _COLLECTOR_REGISTERED = True
    except Exception:   # noqa: BLE001 — witnessing must never break
        pass


def ensure_collector() -> None:
    """Public hook for the telemetry-lint workload: register the
    evidence families without installing the witness."""
    _ensure_collector()
