"""Runtime lockdep witness: observed lock-acquisition order, checked.

The static analyzer (``devtools/rules_locks``, ESTP-L01) proves the
*syntactic* lock graph cycle-free; this module is the runtime half of
the cross-check (modeled on the kernel's lockdep): under
``ES_TPU_LOCKDEP=1`` every ``threading.Lock()`` / ``threading.RLock()``
created by package code is wrapped in a witness that records which lock
classes are held when others are taken. The first acquisition that
would close a cycle in the OBSERVED order graph raises
:class:`LockOrderInversion` naming both witnessed directions — a
deadlock caught deterministically at test time instead of
probabilistically in production. The static graph and the runtime
evidence validate each other: an edge the analyzer missed (a lock
reached through a callback it could not resolve) still shows up here,
and a static cycle that can never execute never fires here.

Lock identity is the *creation site* (file:line of the package frame
that called the factory), the same per-declaration granularity the
static rules use, so the two graphs line up row for row. Two instances
of the same class share a node; same-node nesting (a parent→child
hierarchy of one class) is deliberately NOT an inversion — neither
analyzer can order instances, and raising there would ban legitimate
hierarchies (documented in STATIC_ANALYSIS.md).

Semantics:

- ``install()`` patches ``threading.Lock``/``threading.RLock`` with
  factories that witness locks whose creation site is inside the
  package and leave every other caller (stdlib, third-party) on the
  real primitives. No-op unless ``ES_TPU_LOCKDEP`` ∈ {1, true} or
  ``force=True``; ``uninstall()`` restores the real factories.
- ``ES_TPU_LOCKDEP_MODE=record`` downgrades inversions from raise to
  recorded-only (``report()["inversions"]``) for exploratory runs.
- The witness stamps its evidence into the telemetry registry
  (``es_lockdep_*`` families, catalogued in TELEMETRY.md): locks
  witnessed, acquisitions, max held-lock depth, longest hold, and
  inversions observed — so a CI run's lockdep posture is scrapable
  like any other health signal.

``threading.Condition`` needs no wrapping: it drives the wrapped lock
through ``acquire``/``release`` (and the ``_release_save`` /
``_acquire_restore`` / ``_is_owned`` protocol, which the RLock witness
forwards), so ``cond.wait()`` correctly drops and re-takes the witness
bookkeeping along with the lock.
"""

from __future__ import annotations

import _thread
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["LockOrderInversion", "Witness", "WitnessLock", "WitnessRLock",
           "WITNESS", "install", "uninstall", "installed", "witness_lock",
           "report"]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

#: bounded inversion evidence ring
_MAX_INVERSIONS = 64

#: (on_acquire(name), on_release(name)) observer pairs — the racedep
#: happens-before witness (``common/racedep.py``) registers here so one
#: set of wrapped factories feeds both witnesses. Hooks fire on EVERY
#: acquire/release call (including reentrant ones) so observers see a
#: balanced event stream; they must never raise.
RACE_HOOKS: List[Tuple] = []


class LockOrderInversion(RuntimeError):
    """Observed acquisition closes a cycle in the lock-order graph."""


class _Hold:
    __slots__ = ("lock_id", "name", "t0", "count")

    def __init__(self, lock_id: int, name: str, t0: float):
        self.lock_id = lock_id
        self.name = name
        self.t0 = t0
        self.count = 1


class Witness:
    """Process-wide observed lock-order graph + evidence stats."""

    def __init__(self, raise_on_inversion: Optional[bool] = None):
        if raise_on_inversion is None:
            raise_on_inversion = os.environ.get(
                "ES_TPU_LOCKDEP_MODE", "raise").lower() != "record"
        self.raise_on_inversion = raise_on_inversion
        # the witness's own mutex must be the REAL primitive — it is
        # consulted from inside every wrapped acquire
        self._mutex = _thread.allocate_lock()
        self._tls = threading.local()
        #: (held_name, acquired_name) -> (file, line-ish site info)
        self.edges: Dict[Tuple[str, str], str] = {}
        self._adj: Dict[str, Set[str]] = {}
        #: distinct inverting (acquired, held) pairs → evidence doc
        #: (bounded); re-occurrences bump counts, never duplicate docs
        self.inversions: List[dict] = []
        self._inversion_pairs: Set[Tuple[str, str]] = set()
        #: monotonic total across ALL detections (the telemetry counter
        #: — keeps counting past the evidence ring's cap)
        self.inversion_count = 0
        # evidence stats (GIL-atomic best-effort updates; they feed
        # gauges, not invariants)
        self.locks_witnessed = 0
        self.acquisitions = 0
        self.max_held_depth = 0
        self.longest_hold_ms = 0.0

    # -- per-thread hold stack ----------------------------------------------

    def _stack(self) -> List[_Hold]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def on_acquire(self, lock: "_WitnessBase") -> None:
        for acq, _rel in RACE_HOOKS:
            try:
                acq(lock.name)
            except Exception:   # noqa: BLE001 — observers are evidence,
                pass            # never control flow
        st = self._stack()
        lid = id(lock)
        for h in st:
            if h.lock_id == lid:
                h.count += 1          # reentrant re-acquire: no edges
                return
        held_names = []
        for h in st:
            if h.name != lock.name and h.name not in held_names:
                held_names.append(h.name)
        for h in held_names:
            self._edge(h, lock.name)
        st.append(_Hold(lid, lock.name, time.perf_counter()))
        self.acquisitions += 1
        if len(st) > self.max_held_depth:
            self.max_held_depth = len(st)

    def on_release(self, lock: "_WitnessBase") -> None:
        for _acq, rel in RACE_HOOKS:
            try:
                rel(lock.name)
            except Exception:   # noqa: BLE001
                pass
        st = self._stack()
        lid = id(lock)
        for i in range(len(st) - 1, -1, -1):
            if st[i].lock_id == lid:
                st[i].count -= 1
                if st[i].count <= 0:
                    hold_ms = (time.perf_counter() - st[i].t0) * 1e3
                    if hold_ms > self.longest_hold_ms:
                        self.longest_hold_ms = hold_ms
                    del st[i]
                return
        # release of a lock acquired before witnessing began: ignore

    # -- order graph ---------------------------------------------------------

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """A path src → … → dst in the current edge set (caller holds
        the witness mutex)."""
        todo = [(src, [src])]
        seen = {src}
        while todo:
            cur, path = todo.pop()
            for nxt in self._adj.get(cur, ()):
                if nxt == dst:
                    return path + [dst]
                if nxt not in seen:
                    seen.add(nxt)
                    todo.append((nxt, path + [nxt]))
        return None

    def _edge(self, held: str, acquired: str) -> None:
        key = (held, acquired)
        if key in self.edges:       # lock-free fast path (dict read)
            return
        site = _caller_site()
        with self._mutex:
            if key in self.edges:
                return
            back = self._path(acquired, held)
            if back is not None:
                self.inversion_count += 1
                doc = {
                    "acquiring": acquired, "while_holding": held,
                    "established_order": " -> ".join(back),
                    "site": site,
                    "reverse_sites": [
                        self.edges.get((back[i], back[i + 1]))
                        for i in range(len(back) - 1)],
                    "thread": threading.current_thread().name,
                    "count": 1,
                }
                pair = (acquired, held)
                if pair in self._inversion_pairs:
                    # recurring pair: bump its doc, don't fill the ring
                    for d in self.inversions:
                        if (d["acquiring"], d["while_holding"]) == pair:
                            d["count"] += 1
                            break
                elif len(self.inversions) < _MAX_INVERSIONS:
                    self._inversion_pairs.add(pair)
                    self.inversions.append(doc)
                if self.raise_on_inversion:
                    raise LockOrderInversion(
                        f"lock-order inversion: acquiring [{acquired}] "
                        f"while holding [{held}] at {site}, but the "
                        f"opposite order {' -> '.join(back)} was "
                        f"already witnessed at "
                        f"{doc['reverse_sites']}")
                return
            self.edges[key] = site
            self._adj.setdefault(held, set()).add(acquired)

    # -- evidence ------------------------------------------------------------

    def report(self) -> dict:
        with self._mutex:
            edges = {f"{a} => {b}": s for (a, b), s in self.edges.items()}
            inversions = list(self.inversions)
        return {
            "locks_witnessed": self.locks_witnessed,
            "acquisitions": self.acquisitions,
            "max_held_depth": self.max_held_depth,
            "longest_hold_ms": round(self.longest_hold_ms, 3),
            "edges": edges,
            "inversions": inversions,
            "inversion_count": self.inversion_count,
        }

    def telemetry_doc(self) -> dict:
        return {
            "es_lockdep_locks_witnessed": {
                "type": "gauge",
                "help": "locks created under the lockdep witness",
                "samples": [({}, self.locks_witnessed)]},
            "es_lockdep_acquisitions_total": {
                "type": "counter",
                "help": "witnessed lock acquisitions",
                "samples": [({}, self.acquisitions)]},
            "es_lockdep_max_held_depth": {
                "type": "gauge",
                "help": "max locks held simultaneously by one thread",
                "samples": [({}, self.max_held_depth)]},
            "es_lockdep_longest_hold_millis": {
                "type": "gauge",
                "help": "longest single witnessed lock hold",
                "samples": [({}, round(self.longest_hold_ms, 3))]},
            "es_lockdep_inversions_total": {
                "type": "counter",
                "help": "observed lock-order inversions (must stay 0)",
                "samples": [({}, self.inversion_count)]},
        }


#: process-wide witness (the installed factories and the telemetry
#: collector both read it)
WITNESS = Witness()


class _WitnessBase:
    """Shared acquire/release bookkeeping over an underlying primitive."""

    def __init__(self, witness: Witness, name: str, underlying):
        self._w = witness
        self.name = name
        self._lk = underlying
        witness.locks_witnessed += 1

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            try:
                self._w.on_acquire(self)
            except BaseException:
                # never leave the underlying lock held behind a raise
                # (the with-statement would skip __exit__)
                self._lk.release()
                raise
        return ok

    def release(self) -> None:
        self._w.on_release(self)
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name} of {self._lk!r}>"


class WitnessLock(_WitnessBase):
    def __init__(self, witness: Optional[Witness] = None,
                 name: Optional[str] = None):
        super().__init__(witness or WITNESS, name or _caller_site(),
                         _REAL_LOCK())


class WitnessRLock(_WitnessBase):
    def __init__(self, witness: Optional[Witness] = None,
                 name: Optional[str] = None):
        super().__init__(witness or WITNESS, name or _caller_site(),
                         _REAL_RLOCK())

    # threading.Condition's saved-state protocol (cond.wait on an RLock)
    def _release_save(self):
        self._w.on_release(self)
        return self._lk._release_save()

    def _acquire_restore(self, state) -> None:
        self._lk._acquire_restore(state)
        self._w.on_acquire(self)

    def _is_owned(self) -> bool:
        return self._lk._is_owned()

    def locked(self) -> bool:
        locked = getattr(self._lk, "locked", None)
        return locked() if locked is not None else False


# ---------------------------------------------------------------------------
# Factory installation
# ---------------------------------------------------------------------------

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SKIP_FILES = (os.path.abspath(threading.__file__),
               os.path.abspath(__file__))
_INSTALLED = False


def _caller_site() -> str:
    """file:line of the nearest frame outside threading/lockdep — the
    creation (or acquisition) site that names a lock class."""
    f = sys._getframe(1)
    while f is not None:
        fname = f.f_code.co_filename
        if os.path.abspath(fname) not in _SKIP_FILES:
            try:
                rel = os.path.relpath(fname, os.path.dirname(_PACKAGE_DIR))
            except ValueError:   # different drive (windows)
                rel = fname
            if not rel.startswith(".."):
                return f"{rel}:{f.f_lineno}"
            return f"{os.path.basename(fname)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>:0"


def _package_caller() -> bool:
    f = sys._getframe(1)
    while f is not None:
        fname = os.path.abspath(f.f_code.co_filename)
        if fname not in _SKIP_FILES:
            return fname.startswith(_PACKAGE_DIR + os.sep)
        f = f.f_back
    return False


def _lock_factory():
    if _package_caller():
        _ensure_collector()
        return WitnessLock(WITNESS)
    return _REAL_LOCK()


def _rlock_factory():
    if _package_caller():
        _ensure_collector()
        return WitnessRLock(WITNESS)
    return _REAL_RLOCK()


def enabled_by_env() -> bool:
    return os.environ.get("ES_TPU_LOCKDEP", "0").lower() in ("1", "true")


def install(force: bool = False) -> bool:
    """Patch the threading lock factories (package callers only). Returns
    True when installed. Call EARLY (before package modules create their
    module-level locks) — ``tests/conftest.py`` does this under
    ``ES_TPU_LOCKDEP=1``."""
    global _INSTALLED
    if not force and not enabled_by_env():
        return False
    if _INSTALLED:
        return True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _INSTALLED = True
    _ensure_collector()
    return True


def uninstall() -> None:
    global _INSTALLED
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _INSTALLED = False


def installed() -> bool:
    return _INSTALLED


def witness_lock(name: Optional[str] = None,
                 witness: Optional[Witness] = None) -> WitnessLock:
    """An explicitly-witnessed lock (tests, the telemetry-lint workload)
    — works without installing the global factories."""
    _ensure_collector()
    return WitnessLock(witness or WITNESS, name or _caller_site())


def report() -> dict:
    return WITNESS.report()


_COLLECTOR_REGISTERED = False


def _ensure_collector() -> None:
    """Register the es_lockdep_* telemetry collector once (lazily — the
    lock factories fire DURING the telemetry module's own import when
    its registry/metric locks are created, so this must tolerate a
    partially-initialized telemetry module and retry later)."""
    global _COLLECTOR_REGISTERED
    if _COLLECTOR_REGISTERED:
        return
    try:
        from . import telemetry
        reg = getattr(telemetry, "DEFAULT", None)
        if reg is None:
            return            # telemetry mid-import: retry on next call
        reg.register_collector("lockdep", lambda: WITNESS.telemetry_doc())
        _COLLECTOR_REGISTERED = True
    except Exception:   # noqa: BLE001 — witnessing must never break
        pass            # lock creation; the collector is best-effort
