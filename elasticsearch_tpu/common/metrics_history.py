"""Windowed metrics history: a bounded in-memory downsampling ring.

The SLO burn engine (``common/flightrec.py``) hand-rolls one per-second
bucket ring for exactly two signals; every other ``es_*`` family is a
point-in-time read with no past. This module generalizes those buckets
into the time-series input every future controller decision (ROADMAP
item 4 — rebalance by cost, not count) needs:

- :class:`MetricsHistory` records a SELECTED list of counter/gauge
  families once per watchdog tick via
  :meth:`TelemetryRegistry.family_values` — the cheap point read; a
  tick never snapshot-sorts histogram rings (histogram families record
  their monotonic counts).
- Samples land in three downsampling tiers per series:
  **raw** (one point per tick, default 256 points), **10s** (last
  value per 10-second bucket, default 360 points ≈ 1 h), and **1m**
  (last value per minute, default 1440 points ≈ 24 h). Memory is
  bounded by ``families x series x tier caps``.
- :meth:`doc` serves ``GET /_telemetry/history?family=&window=`` with
  ``rate=true`` support: per-second derivatives between consecutive
  retained points, clamped at zero so counter resets (process restart)
  read as silence, not negative rates.

The clock is injectable (the SLO-parity test drives a fake clock
through the engine and the history side by side); recording never
raises and takes only this module's own lock — no serving lock is ever
held here (ESTP-L02 lists this module with ``common/telemetry``).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import telemetry
from .settings import CLUSTER_SETTINGS, Setting

__all__ = ["MetricsHistory", "DEFAULT", "record_tick",
           "default_families", "TIERS"]

#: (window name, bucket seconds, default retained points). ``raw``
#: keeps one point per tick (bucket 0 = no alignment).
TIERS = (("raw", 0.0, 256), ("10s", 10.0, 360), ("1m", 60.0, 1440))

SETTING_RAW_POINTS = CLUSTER_SETTINGS.register(
    Setting.int_setting("history.raw_points", 256,
                        scope="cluster", dynamic=False, min_value=16))
SETTING_10S_POINTS = CLUSTER_SETTINGS.register(
    Setting.int_setting("history.10s_points", 360,
                        scope="cluster", dynamic=False, min_value=16))
SETTING_1M_POINTS = CLUSTER_SETTINGS.register(
    Setting.int_setting("history.1m_points", 1440,
                        scope="cluster", dynamic=False, min_value=16))

#: families recorded when no explicit selection is configured — the
#: SLO inputs plus the cost/backlog signals the controller loop reads
DEFAULT_FAMILIES = (
    "es_search_retries_total",
    "es_shard_failovers_total",
    "es_slo_burn_rate",
    "es_query_latency_ms",
    "es_tasks_running",
    "es_tenant_requests_total",
    "es_tenant_device_millis_total",
    "es_plane_serving_queries_total",
    "es_batcher_queue_depth",
    "es_insight_observations_total",
)


def default_families() -> Tuple[str, ...]:
    """The recorded family selection: ``ES_TPU_HISTORY_FAMILIES`` (CSV)
    overrides the built-in list."""
    raw = os.environ.get("ES_TPU_HISTORY_FAMILIES")
    if raw:
        fams = tuple(f.strip() for f in raw.split(",") if f.strip())
        if fams:
            return fams
    return DEFAULT_FAMILIES


def _tier_caps() -> Dict[str, int]:
    caps = {}
    for (name, _bucket, dflt), env, setting in zip(
            TIERS,
            ("ES_TPU_HISTORY_RAW_POINTS", "ES_TPU_HISTORY_10S_POINTS",
             "ES_TPU_HISTORY_1M_POINTS"),
            (SETTING_RAW_POINTS, SETTING_10S_POINTS,
             SETTING_1M_POINTS)):
        raw = os.environ.get(env)
        cap = None
        if raw is not None:
            try:
                cap = max(16, int(raw))
            except ValueError:
                cap = None
        caps[name] = cap if cap is not None else int(setting.default)
    return caps


class _Series:
    """One (family, labels) time-series: a deque per tier of
    ``(ts, value)`` points. 10s/1m tiers keep the LAST value seen in
    each aligned bucket — right for gauges, and for monotonic counters
    rate computation between bucket-end points is exact."""

    __slots__ = ("labels", "tiers")

    def __init__(self, labels: dict, caps: Dict[str, int]):
        self.labels = labels
        self.tiers: Dict[str, deque] = {
            name: deque(maxlen=caps[name]) for name, _b, _c in TIERS}

    def append(self, ts: float, value: float) -> None:
        for name, bucket, _cap in TIERS:
            ring = self.tiers[name]
            if bucket <= 0:
                ring.append((ts, value))
                continue
            aligned = int(ts // bucket) * bucket
            if ring and ring[-1][0] == aligned:
                ring[-1] = (aligned, value)
            else:
                ring.append((aligned, value))


class MetricsHistory:
    """Bounded multi-tier history over selected registry families."""

    #: distinct (family, labels) series cap — overflow drops NEW series
    #: (the registry's own MAX_SERIES bounds labels per family already)
    MAX_SERIES = 1024

    def __init__(self,
                 registry: Optional[telemetry.TelemetryRegistry] = None,
                 families: Optional[Tuple[str, ...]] = None,
                 clock=time.time,
                 caps: Optional[Dict[str, int]] = None):
        self._registry = registry
        self.families = tuple(families) if families is not None \
            else default_families()
        self._clock = clock
        self._caps = dict(caps) if caps is not None else _tier_caps()
        self._lock = threading.Lock()
        # family -> labels_key -> _Series
        self._series: Dict[str, Dict[tuple, _Series]] = {}
        self._ticks = 0
        self._dropped_series = 0

    def _reg(self) -> telemetry.TelemetryRegistry:
        return self._registry or telemetry.DEFAULT

    # -- write path ---------------------------------------------------------

    def record(self, now: Optional[float] = None) -> int:
        """One sampling round over the selected families; returns the
        number of points appended. Rides the watchdog tick; never
        raises."""
        try:
            ts = float(now) if now is not None else self._clock()
            reg = self._reg()
            appended = 0
            n_series = 0
            for family in self.families:
                try:
                    values = reg.family_values(family)
                except Exception:   # noqa: BLE001 — one bad family
                    continue        # must not starve the rest
                if not values:
                    continue
                with self._lock:
                    fam_series = self._series.setdefault(family, {})
                    for labels, value in values:
                        key = tuple(sorted(labels.items()))
                        series = fam_series.get(key)
                        if series is None:
                            if self._n_series_locked() >= \
                                    self.MAX_SERIES:
                                self._dropped_series += 1
                                continue
                            series = fam_series[key] = _Series(
                                dict(labels), self._caps)
                        series.append(ts, float(value))
                        appended += 1
            with self._lock:
                self._ticks += 1
                n_series = self._n_series_locked()
            reg.counter("es_history_samples_total",
                        help="points appended to the metrics-history "
                             "ring").inc(appended)
            reg.gauge("es_history_series",
                      help="distinct (family, labels) series retained "
                           "in the metrics-history ring").set(n_series)
            return appended
        except Exception:   # noqa: BLE001 — history must not fail the tick
            return 0

    def _n_series_locked(self) -> int:
        return sum(len(s) for s in self._series.values())

    # -- read path ----------------------------------------------------------

    def doc(self, family: str, window: str = "raw",
            since: Optional[float] = None, rate: bool = False,
            labels: Optional[dict] = None) -> dict:
        """The ``GET /_telemetry/history`` payload for ONE family:
        every retained series (optionally filtered to label subsets
        containing ``labels``) in the requested tier, newest-last
        ``[ts, value]`` points; ``rate=True`` replaces points with
        per-second derivatives between consecutive retained points
        (clamped >= 0 so counter resets read as gaps, not negatives)."""
        if window not in {t[0] for t in TIERS}:
            window = "raw"
        with self._lock:
            fam_series = self._series.get(family, {})
            snap = [(s.labels, list(s.tiers[window]))
                    for s in fam_series.values()]
        out_series = []
        for lbls, points in snap:
            if labels and any(lbls.get(k) != v
                              for k, v in labels.items()):
                continue
            if since is not None:
                points = [p for p in points if p[0] >= since]
            if rate:
                points = _rate_points(points)
            out_series.append(
                {"labels": lbls,
                 "points": [[round(ts, 3), round(v, 6)]
                            for ts, v in points]})
        return {"family": family, "window": window, "rate": bool(rate),
                "series": out_series}

    def windowed_delta(self, family: str, span_s: float,
                       now: Optional[float] = None,
                       window: str = "raw",
                       label_filter: Optional[dict] = None) -> float:
        """Sum over matching series of (last value - value at/just
        before ``now - span_s``) — the windowed counter delta a burn-
        rate style consumer needs. Series with no point old enough use
        their oldest retained point (the delta is then a floor)."""
        t = float(now) if now is not None else self._clock()
        doc = self.doc(family, window=window, labels=label_filter)
        total = 0.0
        floor_ts = t - float(span_s)
        for series in doc["series"]:
            points = series["points"]
            if not points:
                continue
            base = points[0][1]
            for ts, v in points:
                if ts > floor_ts:
                    break
                base = v
            total += max(points[-1][1] - base, 0.0)
        return total

    def stats_doc(self) -> dict:
        with self._lock:
            return {"families": list(self.families),
                    "ticks": self._ticks,
                    "series": self._n_series_locked(),
                    "dropped_series": self._dropped_series,
                    "tiers": {name: {"bucket_seconds": bucket,
                                     "points": self._caps[name]}
                              for name, bucket, _cap in TIERS}}


def _rate_points(points: List[tuple]) -> List[tuple]:
    out = []
    for (t0, v0), (t1, v1) in zip(points, points[1:]):
        dt = t1 - t0
        if dt <= 0:
            continue
        out.append((t1, max(v1 - v0, 0.0) / dt))
    return out


#: PROCESS-scoped history (the flightrec.DEFAULT singleton pattern) —
#: fed by the watchdog tick; in-process multi-node clusters share it
DEFAULT = MetricsHistory()


def record_tick(now: Optional[float] = None) -> int:
    """Module entry the watchdog tick uses."""
    return DEFAULT.record(now)
