"""Continuous profiling: an always-on, low-overhead flamegraph sampler.

The observability stack sees every *device* dispatch (timeline profiler,
roofline auditor) but host CPU — REST parse, micro-batch host prep, CSR
eager scoring, pack/repack, tier streaming — was only visible through
``_nodes/hot_threads``, an on-demand blocking snapshot. This module runs
ONE daemon sampler thread that walks :func:`sys._current_frames` on a
bounded cadence and folds every busy stack into a per-window bounded
flamegraph trie, attributed along the dimensions the stack already
carries:

- **thread pool** — every package-created thread is named with a stable
  ``es-<role>`` prefix at creation (dispatcher/repack/warmup/recovery/
  watchdog/monitoring/sampler/...); the sampler derives the pool from
  the name, with an explicit per-thread override registry on top
  (:func:`register_thread`, and the REST edge binding request threads
  to the ``rest`` pool for the request's lifetime).
- **tenant + query shape** — request threads bind their X-Opaque-Id at
  the REST edge (:func:`bind_request_thread`) and publish a reference
  to flightrec's MUTABLE shape holder (:func:`note_shape_holder`), so
  mid-request shape upgrades (``flightrec.set_shape``) are visible to
  the sampler live, with zero per-sample request-side work. Dispatcher
  threads carry no request context, so ``microbatch._dispatch_loop``
  stamps the active batch's dominant (tenant, shape) around each
  dispatch (:func:`bind_dispatch`) — the slots captured both on the
  request thread at enqueue.
- **idle/busy** — one classifier (:func:`classify_idle`) shared with
  ``utils/hot_threads`` (which re-exports it), so the two samplers can
  never disagree about what "parked" means.

Windows rotate current→previous on the insights cadence
(``contprof.window_seconds``); the trie is node-capped
(``contprof.max_nodes``) with truncation counted, never unbounded.
``GET /_profiler/flamegraph`` serves collapsed-stack text and
d3-flamegraph JSON with ``?window=&pool=&tenant=`` filters; the cluster
front fans it in over ``rest:exec`` and merges rows per full path, then
re-applies the limit AFTER the merge (the insights limit-after-merge
lesson). Every watchdog capture embeds a profile slice
(:func:`capture_doc`), so SLO-red post-mortems answer "where was the
CPU going".

The sampler self-meters: ``es_contprof_samples_total`` (thread-stack
samples observed), ``es_contprof_stacks_retained_total`` (busy stacks
folded fully into a window), ``es_contprof_dropped_total`` (stacks
truncated by the node cap) and an ``es_contprof_duty_cycle`` gauge
(EWMA fraction of wall time spent sampling); ``bench.py`` gates the
ABBA on-vs-off overhead at <=2% like the insights gate.

Attribution writes here are O(1) dict updates under this module's own
lock — never under a serving lock (ESTP-L02 lists this module with
``common/telemetry``). The sampler thread has a real ``close()`` that
signals and joins (ESTP-T01).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

from .settings import CLUSTER_SETTINGS, Setting

__all__ = [
    "classify_idle", "sample_stacks", "register_thread",
    "bind_request_thread", "unbind_request_thread", "note_shape_holder",
    "bind_dispatch", "unbind_dispatch", "thread_role",
    "ContinuousProfiler", "ensure_profiler", "get_profiler",
    "close_profiler", "profile_doc", "capture_doc", "merge_docs",
    "collapsed_text", "flame_json", "contprof_enabled", "interval_ms",
    "window_seconds", "max_nodes", "IDLE_HINTS",
]

SETTING_INTERVAL_MS = CLUSTER_SETTINGS.register(
    Setting.float_setting("contprof.interval_ms", 50.0,
                          scope="cluster", dynamic=False))
SETTING_WINDOW_S = CLUSTER_SETTINGS.register(
    Setting.float_setting("contprof.window_seconds", 60.0,
                          scope="cluster", dynamic=False))
SETTING_MAX_NODES = CLUSTER_SETTINGS.register(
    Setting.int_setting("contprof.max_nodes", 8192,
                        scope="cluster", dynamic=False, min_value=64))

#: frames kept per sampled stack (innermost) — bounds both the
#: per-sample extract cost and the trie depth
STACK_DEPTH = 24

#: default row cap for the REST endpoint / capture slice
DEFAULT_LIMIT = 256


def contprof_enabled() -> bool:
    """Master on/off gate (``ES_TPU_CONTPROF`` env; default on). The
    bench's profiler-off arm uses this to measure the overhead."""
    return os.environ.get("ES_TPU_CONTPROF", "1").lower() \
        not in ("0", "false")


def interval_ms() -> float:
    raw = os.environ.get("ES_TPU_CONTPROF_INTERVAL_MS")
    if raw is not None:
        try:
            return max(1.0, float(raw))
        except ValueError:
            pass
    return float(SETTING_INTERVAL_MS.default)


def window_seconds() -> float:
    raw = os.environ.get("ES_TPU_CONTPROF_WINDOW_S")
    if raw is not None:
        try:
            return max(1.0, float(raw))
        except ValueError:
            pass
    return float(SETTING_WINDOW_S.default)


def max_nodes() -> int:
    raw = os.environ.get("ES_TPU_CONTPROF_MAX_NODES")
    if raw is not None:
        try:
            return max(64, int(raw))
        except ValueError:
            pass
    return int(SETTING_MAX_NODES.default)


# -- idle/busy classifier (shared with utils/hot_threads) -------------------

#: frames that mean "parked, not burning cpu" — probed against
#: ``filename:funcname`` of the innermost frame
IDLE_HINTS = ("threading.py", "queue.py", "selectors.py",
              "socket.py", "ssl.py", "concurrent/futures",
              "asyncio/base_events.py", "wait", "select", "epoll",
              "utils/hot_threads.py", "common/contprof.py")

#: runtime-infrastructure files a waiter frame can live in
_RUNTIME_FILES = ("threading.py", "queue.py", "selectors.py",
                  "socket.py", "ssl.py", "concurrent/futures",
                  "asyncio/")

#: function names that park a thread when executing in a runtime file —
#: STRICT (no ``run``/``_bootstrap``), so a busy application frame under
#: ``Thread.run`` never misreads as idle
_WAITER_NAMES = ("wait", "acquire", "select", "poll", "join", "sleep",
                 "get", "recv", "accept", "epoll")


def _is_waiter_frame(fs: traceback.FrameSummary) -> bool:
    return any(r in fs.filename for r in _RUNTIME_FILES) and \
        any(w in fs.name for w in _WAITER_NAMES)


def classify_idle(stack: List[traceback.FrameSummary]) -> bool:
    """True when the sampled thread is parked rather than burning CPU.

    The old ``hot_threads._is_idle`` probed ONLY the top frame, so a
    thread parked in ``cond.wait()`` could misread as busy whenever the
    extracted listing put a package frame innermost (wrapper/extract
    ordering quirks). Classification here is on the deepest frame that
    decides anything: an idle-hint innermost frame is parked; an
    application innermost frame is busy — UNLESS the frame immediately
    outward of it is a strict runtime waiter (``threading.py:wait`` and
    friends), which means the listing inverted the order around a park.
    """
    if not stack:
        return True
    top = stack[-1]
    probe = f"{top.filename}:{top.name}"
    if any(h in probe for h in IDLE_HINTS):
        return True
    if len(stack) >= 2 and _is_waiter_frame(stack[-2]):
        return True
    return False


def sample_stacks(limit: Optional[int] = None) \
        -> Dict[int, List[traceback.FrameSummary]]:
    """One pass over every live Python thread: ``{ident: stack}`` with
    frames outermost-first (innermost ``limit`` frames kept). The ONE
    sampling core shared by the continuous sampler and hot_threads."""
    out: Dict[int, List[traceback.FrameSummary]] = {}
    for tid, frame in sys._current_frames().items():
        try:
            out[tid] = traceback.extract_stack(frame, limit=limit)
        except Exception:   # noqa: BLE001 — a frame torn down mid-walk
            continue        # contributes nothing this pass
    return out


# -- thread -> attribution registries ---------------------------------------

#: guards the three maps below; every hold is O(1) (ESTP-L02: this
#: module is telemetry-side, never under a serving lock)
_ATTR_LOCK = threading.Lock()
#: ident -> explicit role override (register_thread)
_ROLES: Dict[int, str] = {}
#: ident -> [tenant, shape_holder] for request threads (REST edge);
#: shape_holder is flightrec's MUTABLE single-slot list, so mid-request
#: ``set_shape`` upgrades are visible to the sampler live
_REQUESTS: Dict[int, list] = {}
#: ident -> (tenant, shape) stamped by dispatcher threads around the
#: active batch (the batch's dominant pair, captured at enqueue)
_DISPATCH: Dict[int, Tuple[Optional[str], Optional[str]]] = {}


def register_thread(role: str, thread: Optional[threading.Thread] = None
                    ) -> None:
    """Explicitly stamp ``role`` for a thread (defaults to the caller)
    — for threads whose name a foreign layer controls."""
    t = thread if thread is not None else threading.current_thread()
    if t.ident is None:
        return
    with _ATTR_LOCK:
        _ROLES[t.ident] = str(role)


def thread_role(ident: int, name: str) -> str:
    """The pool a thread samples into: explicit override, else the
    ``es-<role>[-...]`` name prefix, else main/other."""
    with _ATTR_LOCK:
        role = _ROLES.get(ident)
    if role is not None:
        return role
    if name.startswith("es-"):
        rest = name[3:]
        return rest.split("-", 1)[0] or "other"
    if name == "MainThread":
        return "main"
    return "other"


def bind_request_thread(tenant: Optional[str]) -> tuple:
    """Bind the calling (request) thread's tenant for its lifetime;
    returns a token for :func:`unbind_request_thread`. Nest-safe:
    internal re-dispatches on the same thread restore the outer
    binding."""
    ident = threading.get_ident()
    with _ATTR_LOCK:
        prev = _REQUESTS.get(ident)
        _REQUESTS[ident] = [tenant or None, None]
    return ident, prev


def unbind_request_thread(token: tuple) -> None:
    ident, prev = token
    with _ATTR_LOCK:
        if prev is None:
            _REQUESTS.pop(ident, None)
        else:
            _REQUESTS[ident] = prev


def note_shape_holder(holder: list) -> None:
    """Publish flightrec's mutable shape holder for the calling request
    thread (called by ``flightrec.bind_shape``); no-op off-request."""
    ident = threading.get_ident()
    with _ATTR_LOCK:
        ent = _REQUESTS.get(ident)
        if ent is not None:
            ent[1] = holder


#: shape-id upgrades (structural fingerprint -> plan id) noted by
#: ``flightrec.set_shape``: samples folded BEFORE the planner lowered
#: the body carry the early id; render-time resolution converges every
#: window onto the final id query-insights reports
_SHAPE_ALIASES: Dict[str, str] = {}
_ALIAS_CAP = 4096


def note_shape_alias(old: Optional[str], new: Optional[str]) -> None:
    """Record that samples attributed to shape ``old`` belong to ``new``
    (a mid-request in-place upgrade). Bounded; self-maps are dropped."""
    if not old or not new or old == new:
        return
    with _ATTR_LOCK:
        if len(_SHAPE_ALIASES) < _ALIAS_CAP or old in _SHAPE_ALIASES:
            _SHAPE_ALIASES[old] = new


def resolve_shape(shape: str) -> str:
    """Chase the alias chain (bounded — upgrade chains are short and a
    stale cycle must not hang the renderer)."""
    with _ATTR_LOCK:
        for _hop in range(8):
            nxt = _SHAPE_ALIASES.get(shape)
            if nxt is None or nxt == shape:
                break
            shape = nxt
    return shape


def bind_dispatch(tenant: Optional[str], shape: Optional[str]) -> tuple:
    """Stamp the calling (dispatcher) thread with the active batch's
    dominant (tenant, shape); returns a token for
    :func:`unbind_dispatch`."""
    ident = threading.get_ident()
    with _ATTR_LOCK:
        prev = _DISPATCH.get(ident)
        _DISPATCH[ident] = (tenant, shape)
    return ident, prev


def unbind_dispatch(token: tuple) -> None:
    ident, prev = token
    with _ATTR_LOCK:
        if prev is None:
            _DISPATCH.pop(ident, None)
        else:
            _DISPATCH[ident] = prev


def _attribution_snapshot(live_idents) -> tuple:
    """Copy the three maps under the lock and prune dead idents (threads
    exit without unregistering; the sampler is the natural GC point)."""
    with _ATTR_LOCK:
        for m in (_ROLES, _REQUESTS, _DISPATCH):
            for ident in [i for i in m if i not in live_idents]:
                del m[ident]
        return (dict(_ROLES),
                {i: (v[0], v[1]) for i, v in _REQUESTS.items()},
                dict(_DISPATCH))


# -- bounded flamegraph trie windows ----------------------------------------

class _Window:
    """One rotation window: a node-capped trie of attributed stacks.

    Trie nodes are ``[count, children_dict]``; a node's count is the
    samples passing THROUGH it, so self-samples (the flamegraph leaf
    value) fall out as ``count - sum(children)`` at render time and the
    whole structure merges across nodes by summing per-path."""

    __slots__ = ("started", "root", "n_nodes", "busy", "idle",
                 "truncated")

    def __init__(self, started: float):
        self.started = started
        self.root: list = [0, {}]
        self.n_nodes = 0
        self.busy = 0
        self.idle = 0
        self.truncated = 0

    def fold(self, path: Tuple[str, ...], cap: int) -> bool:
        """Add one busy stack; returns False when the node cap truncated
        it (the sample still counts into every node it reached)."""
        cur = self.root
        cur[0] += 1
        full = True
        for part in path:
            nxt = cur[1].get(part)
            if nxt is None:
                if self.n_nodes >= cap:
                    full = False
                    break
                nxt = cur[1][part] = [0, {}]
                self.n_nodes += 1
            cur = nxt
            cur[0] += 1
        self.busy += 1
        if not full:
            self.truncated += 1
        return full

    def rows(self) -> List[Tuple[Tuple[str, ...], int]]:
        """``(path, self_samples)`` per terminating node — the collapsed
        form the endpoint, the merge and the renderers all share."""
        out: List[Tuple[Tuple[str, ...], int]] = []

        def walk(node, parts):
            cnt, children = node
            self_n = cnt - sum(c[0] for c in children.values())
            if self_n > 0 and parts:
                out.append((tuple(parts), self_n))
            for name, child in children.items():
                parts.append(name)
                walk(child, parts)
                parts.pop()

        walk(self.root, [])
        return out


def _row_doc(path: Tuple[str, ...], samples: int) -> dict:
    pad = tuple(path) + ("-",) * max(0, 3 - len(path))
    return {"pool": pad[0], "tenant": pad[1], "shape": pad[2],
            "stack": list(path[3:]), "samples": int(samples)}


def _doc_from_rows(rows: List[dict], limit: int) -> dict:
    """Rank, truncate, and attach the attribution rollup + dominant
    triple (computed BEFORE the row truncation, so a long tail cannot
    hide the dominant pool)."""
    rows = sorted(rows, key=lambda r: (-r["samples"], r["pool"],
                                       r["tenant"], r["shape"],
                                       tuple(r["stack"])))
    attrib: Dict[Tuple[str, str, str], int] = {}
    for r in rows:
        key = (r["pool"], r["tenant"], r["shape"])
        attrib[key] = attrib.get(key, 0) + r["samples"]
    attribution = [{"pool": p, "tenant": t, "shape": s,
                    "samples": n}
                   for (p, t, s), n in sorted(attrib.items(),
                                              key=lambda kv: -kv[1])]
    kept = rows[:max(limit, 0)]
    return {"rows": kept, "rows_dropped": len(rows) - len(kept),
            "attribution": attribution,
            "dominant": attribution[0] if attribution else None,
            "flamegraph": flame_json(kept)}


class ContinuousProfiler:
    """The always-on sampler: one daemon thread, bounded cadence,
    current/previous trie windows. Constructible thread-less for burst
    sampling (watchdog captures, the lint workload, tests) — only
    :meth:`start` spawns the thread; :meth:`close` signals and joins."""

    def __init__(self, registry=None, clock=time.time,
                 interval_ms_: Optional[float] = None,
                 window_s: Optional[float] = None,
                 cap: Optional[int] = None):
        self.clock = clock
        self.interval_s = (interval_ms_ if interval_ms_ is not None
                           else interval_ms()) / 1e3
        self.window_s = window_s if window_s is not None \
            else window_seconds()
        self.cap = cap if cap is not None else max_nodes()
        self._lock = threading.Lock()
        now = clock()
        self._current = _Window(now)
        self._previous: Optional[_Window] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._duty = 0.0
        if registry is None:
            from . import telemetry as _tm
            registry = _tm.DEFAULT
        # pre-create the families so the telemetry lint sees them
        # deterministically, like the watchdog's capture counters
        self._c_samples = registry.counter(
            "es_contprof_samples_total",
            help="thread-stack samples observed by the continuous "
                 "profiler (busy + idle)")
        self._c_retained = registry.counter(
            "es_contprof_stacks_retained_total",
            help="busy stacks folded fully into a profile window trie")
        self._c_dropped = registry.counter(
            "es_contprof_dropped_total",
            help="busy stacks truncated by the profile window's "
                 "contprof.max_nodes cap")
        self._g_duty = registry.gauge(
            "es_contprof_duty_cycle",
            help="EWMA fraction of wall time the sampler spends "
                 "walking stacks (self-metered overhead)")
        for c in (self._c_samples, self._c_retained, self._c_dropped):
            c.inc(0)
        self._g_duty.set(0.0)

    # -- lifecycle ----------------------------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "ContinuousProfiler":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                t = threading.Thread(target=self._run,
                                     name="es-sampler-contprof",
                                     daemon=True)
                self._thread = t
                t.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        """Signal and JOIN the sampler thread (orderly teardown)."""
        self._stop.set()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            t.join(timeout)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:   # noqa: BLE001 — the sampler must
                pass            # survive any torn frame it walks

    # -- sampling -----------------------------------------------------------

    def _rotate_locked(self, now: float) -> None:
        if now - self._current.started >= self.window_s:
            self._previous = self._current
            self._current = _Window(now)

    def sample_once(self, now: Optional[float] = None) -> int:
        """One pass: walk every thread, classify, attribute, fold.
        Returns the number of busy stacks folded (tests/burst mode)."""
        t = now if now is not None else self.clock()
        t0 = time.perf_counter()
        me = threading.get_ident()
        frames = sys._current_frames()
        names = {th.ident: th.name for th in threading.enumerate()}
        roles, reqs, disp = _attribution_snapshot(frames)
        n_seen = n_busy = n_dropped = 0
        with self._lock:
            self._rotate_locked(t)
            win = self._current
            for tid, frame in frames.items():
                if tid == me:
                    continue
                try:
                    stack = traceback.extract_stack(frame,
                                                    limit=STACK_DEPTH)
                except Exception:   # noqa: BLE001 — torn frame
                    continue
                n_seen += 1
                if classify_idle(stack):
                    win.idle += 1
                    continue
                tenant = shape = None
                d = disp.get(tid)
                if d is not None:
                    tenant, shape = d
                ent = reqs.get(tid)
                role = roles.get(tid)
                if ent is not None:
                    tenant = ent[0]
                    holder = ent[1]
                    if holder:
                        shape = holder[0]
                    if role is None:
                        role = "rest"
                if role is None:
                    role = thread_role(tid, names.get(tid, ""))
                path = (role, tenant or "-", shape or "-") + tuple(
                    f"{fs.filename.rsplit('/', 1)[-1]}:{fs.name}"
                    for fs in stack)
                if win.fold(path, self.cap):
                    n_busy += 1
                else:
                    n_dropped += 1
        self._c_samples.inc(n_seen)
        if n_busy:
            self._c_retained.inc(n_busy)
        if n_dropped:
            self._c_dropped.inc(n_dropped)
        dur = time.perf_counter() - t0
        with self._lock:
            self._duty += 0.2 * (min(dur / max(self.interval_s, 1e-3),
                                     1.0) - self._duty)
            duty = self._duty
        self._g_duty.set(round(duty, 6))
        return n_busy + n_dropped

    # -- reads --------------------------------------------------------------

    def _windows(self, which: str) -> List[_Window]:
        if which == "current":
            return [self._current]
        if which == "previous":
            return [self._previous] if self._previous else []
        return [w for w in (self._current, self._previous) if w]

    def top_doc(self, window: str = "current",
                pool: Optional[str] = None,
                tenant: Optional[str] = None,
                limit: int = DEFAULT_LIMIT) -> dict:
        """The node-local profile doc the endpoint, the cluster merge
        and the watchdog capture all share."""
        with self._lock:
            self._rotate_locked(self.clock())
            wins = self._windows(window)
            merged: Dict[Tuple[str, ...], int] = {}
            stats = {"samples": 0, "idle_samples": 0, "truncated": 0,
                     "trie_nodes": 0}
            for w in wins:
                stats["samples"] += w.busy + w.idle
                stats["idle_samples"] += w.idle
                stats["truncated"] += w.truncated
                stats["trie_nodes"] += w.n_nodes
                for path, n in w.rows():
                    if len(path) >= 3 and path[2] != "-":
                        rs = resolve_shape(path[2])
                        if rs != path[2]:
                            path = path[:2] + (rs,) + path[3:]
                    merged[path] = merged.get(path, 0) + n
            duty = self._duty
        rows = [_row_doc(p, n) for p, n in merged.items()]
        if pool is not None:
            rows = [r for r in rows if r["pool"] == pool]
        if tenant is not None:
            rows = [r for r in rows if r["tenant"] == tenant]
        doc = _doc_from_rows(rows, limit)
        doc.update(stats)
        doc["enabled"] = True
        doc["window"] = window
        doc["interval_ms"] = round(self.interval_s * 1e3, 3)
        doc["duty_cycle"] = round(duty, 6)
        return doc


# -- renderers / merge ------------------------------------------------------

def collapsed_text(rows: List[dict]) -> str:
    """Brendan-Gregg collapsed stacks, one attributed path per line,
    sorted by weight: ``pool;tenant;shape;frame;... N``."""
    lines = []
    for r in sorted(rows, key=lambda r: (-r["samples"], r["pool"],
                                         r["tenant"], r["shape"],
                                         tuple(r["stack"]))):
        parts = [r["pool"], r["tenant"], r["shape"]] + list(r["stack"])
        lines.append(";".join(parts) + f" {r['samples']}")
    return "\n".join(lines) + ("\n" if lines else "")


def flame_json(rows: List[dict]) -> dict:
    """Re-fold rows into the nested ``{name, value, children}`` tree
    d3-flamegraph loads directly."""
    root = {"name": "all", "value": 0, "children": {}}
    for r in rows:
        parts = [r["pool"], r["tenant"], r["shape"]] + list(r["stack"])
        node = root
        node["value"] += r["samples"]
        for part in parts:
            child = node["children"].get(part)
            if child is None:
                child = node["children"][part] = {
                    "name": part, "value": 0, "children": {}}
            node = child
            node["value"] += r["samples"]

    def finish(node):
        kids = [finish(c) for c in node["children"].values()]
        kids.sort(key=lambda c: -c["value"])
        out = {"name": node["name"], "value": node["value"]}
        if kids:
            out["children"] = kids
        return out

    return finish(root)


def merge_docs(docs: List[dict], limit: int = DEFAULT_LIMIT) -> dict:
    """Cluster fan-in merge: per-path SUM of self-samples across nodes,
    re-rank, then re-apply ``limit`` AFTER the merge — never concatenate
    per-node top-N lists."""
    merged: Dict[tuple, int] = {}
    stats = {"samples": 0, "idle_samples": 0, "truncated": 0,
             "trie_nodes": 0}
    for d in docs:
        if not isinstance(d, dict):
            continue
        for k in stats:
            stats[k] += int(d.get(k) or 0)
        for r in d.get("rows") or []:
            key = (r.get("pool", "-"), r.get("tenant", "-"),
                   r.get("shape", "-"), tuple(r.get("stack") or ()))
            merged[key] = merged.get(key, 0) + int(r.get("samples", 0))
    rows = [{"pool": p, "tenant": t, "shape": s, "stack": list(st),
             "samples": n} for (p, t, s, st), n in merged.items()]
    doc = _doc_from_rows(rows, limit)
    doc.update(stats)
    return doc


# -- process singleton ------------------------------------------------------

_SINGLETON_LOCK = threading.Lock()
_PROFILER: Optional[ContinuousProfiler] = None


def ensure_profiler() -> Optional[ContinuousProfiler]:
    """Start the process sampler when enabled; TEAR IT DOWN (close +
    join) when ``ES_TPU_CONTPROF=0`` — the bench's off arm flips the
    env and calls this to actually stop the sampling."""
    global _PROFILER
    with _SINGLETON_LOCK:
        if not contprof_enabled():
            p, _PROFILER = _PROFILER, None
        else:
            if _PROFILER is None:
                _PROFILER = ContinuousProfiler()
            _PROFILER.start()
            return _PROFILER
    if p is not None:
        p.close()
    return None


def get_profiler() -> Optional[ContinuousProfiler]:
    with _SINGLETON_LOCK:
        return _PROFILER


def close_profiler() -> None:
    """Tear down the process sampler (tests / orderly shutdown)."""
    global _PROFILER
    with _SINGLETON_LOCK:
        p, _PROFILER = _PROFILER, None
    if p is not None:
        p.close()


def profile_doc(window: str = "current", pool: Optional[str] = None,
                tenant: Optional[str] = None,
                limit: int = DEFAULT_LIMIT) -> dict:
    """The endpoint's doc: the live singleton's windows, or an explicit
    empty-but-shaped doc when the sampler is off."""
    p = get_profiler()
    if p is None:
        doc = _doc_from_rows([], limit)
        doc.update({"samples": 0, "idle_samples": 0, "truncated": 0,
                    "trie_nodes": 0, "enabled": False, "window": window,
                    "interval_ms": interval_ms(), "duty_cycle": 0.0})
        return doc
    return p.top_doc(window=window, pool=pool, tenant=tenant,
                     limit=limit)


def capture_doc(limit: int = 64, bursts: int = 20,
                burst_sleep_s: float = 0.003) -> dict:
    """The watchdog-capture profile slice: the live sampler's windows
    when it is running, else a short synchronous burst sample (the
    hot_threads-style blocking walk) so captures carry CPU evidence
    even with the always-on thread gated off."""
    p = get_profiler()
    if p is not None and p.running:
        return p.top_doc(window="both", limit=limit)
    burst = ContinuousProfiler(interval_ms_=max(burst_sleep_s * 1e3,
                                                1.0))
    for i in range(max(bursts, 1)):
        burst.sample_once()
        if i + 1 < bursts:
            time.sleep(burst_sleep_s)
    doc = burst.top_doc(window="both", limit=limit)
    doc["burst"] = True
    return doc
