"""Exception hierarchy mirroring the reference's ElasticsearchException tree.

Reference: ``server/src/main/java/org/elasticsearch/ElasticsearchException.java``
and the REST status mapping in ``rest/RestStatus``-carrying exceptions. Each
exception carries an HTTP status so the REST layer can render ES-compatible
error bodies ``{"error": {"type": ..., "reason": ...}, "status": N}``.
"""

from __future__ import annotations


class ElasticsearchError(Exception):
    """Base error. ``status`` is the HTTP status the REST layer returns."""

    status = 500
    error_type = "exception"

    def __init__(self, reason: str = "", **metadata):
        super().__init__(reason)
        self.reason = reason
        self.metadata = metadata

    def to_dict(self) -> dict:
        err = {"type": self.error_type, "reason": self.reason or str(self)}
        err.update(self.metadata)
        return {"error": err, "status": self.status}


class IndexNotFoundError(ElasticsearchError):
    status = 404
    error_type = "index_not_found_exception"

    def __init__(self, index: str):
        super().__init__(f"no such index [{index}]", index=index)
        self.index = index


class ResourceAlreadyExistsError(ElasticsearchError):
    status = 400
    error_type = "resource_already_exists_exception"


class DocumentMissingError(ElasticsearchError):
    status = 404
    error_type = "document_missing_exception"


class VersionConflictError(ElasticsearchError):
    """Reference: ``index/engine/VersionConflictEngineException.java``."""

    status = 409
    error_type = "version_conflict_engine_exception"


class MapperParsingError(ElasticsearchError):
    status = 400
    error_type = "mapper_parsing_exception"


class IllegalArgumentError(ElasticsearchError):
    status = 400
    error_type = "illegal_argument_exception"


class IllegalStateError(ElasticsearchError):
    """Reference: ``java.lang.IllegalStateException`` surfaced through
    ``ElasticsearchException`` (e.g. resize validation in
    ``cluster/metadata/MetadataCreateIndexService.java:1068``)."""

    status = 500
    error_type = "illegal_state_exception"


class ElasticsearchParseError(ElasticsearchError):
    """``ElasticsearchParseException`` — type "parse_exception", distinct
    from ParsingError's "parsing_exception"."""

    status = 400
    error_type = "parse_exception"


class ParsingError(ElasticsearchError):
    """Query DSL / body parse failure (``common/ParsingException.java``)."""

    status = 400
    error_type = "parsing_exception"


class QueryShardError(ElasticsearchError):
    """Reference: ``index/query/QueryShardException.java`` — a query that
    cannot execute against this shard's mapping."""

    status = 400
    error_type = "query_shard_exception"


class SearchPhaseExecutionError(ElasticsearchError):
    status = 500
    error_type = "search_phase_execution_exception"


class ShardNotFoundError(ElasticsearchError):
    status = 404
    error_type = "shard_not_found_exception"


class NodeNotFoundError(ElasticsearchError):
    status = 404
    error_type = "node_not_found_exception"


class CircuitBreakingError(ElasticsearchError):
    """Reference: ``common/breaker/CircuitBreakingException.java`` (429)."""

    status = 429
    error_type = "circuit_breaking_exception"


class ClusterBlockError(ElasticsearchError):
    status = 503
    error_type = "cluster_block_exception"


class InvalidIndexNameError(ElasticsearchError):
    status = 400
    error_type = "invalid_index_name_exception"


class InvalidAliasNameError(ElasticsearchError):
    status = 400
    error_type = "invalid_alias_name_exception"


class SnapshotError(ElasticsearchError):
    status = 500
    error_type = "snapshot_exception"


class SnapshotMissingError(ElasticsearchError):
    status = 404
    error_type = "snapshot_missing_exception"


class PipelineError(ElasticsearchError):
    status = 400
    error_type = "pipeline_processing_exception"


class ResourceNotFoundError(ElasticsearchError):
    status = 404
    error_type = "resource_not_found_exception"


class IndexClosedError(ElasticsearchError):
    status = 400
    error_type = "index_closed_exception"


class XContentParseError(ElasticsearchError):
    """Agg/body parse failures surfaced as x_content_parse_exception."""
    status = 400
    error_type = "x_content_parse_exception"


class ActionRequestValidationError(ElasticsearchError):
    """Request validation failures (action_request_validation_exception)."""
    status = 400
    error_type = "action_request_validation_exception"


def remote_status(e) -> int:
    """HTTP status of any exception, including remote-wrapped ones whose
    class crossed the transport by NAME (RemoteTransportError carries
    ``remote_type``); 0 when unknown."""
    st = getattr(e, "status", None)
    if st is None and hasattr(e, "remote_type"):
        cls = globals().get(getattr(e, "remote_type", "") or "")
        st = getattr(cls, "status", None)
    return int(st or 0)
