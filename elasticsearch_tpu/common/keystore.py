"""Secure-settings keystore: encrypted at rest, loaded into node settings.

Reference: ``server/.../common/settings/KeyStoreWrapper.java:83`` — an
optionally password-protected container for secure settings (repository
credentials, TLS passphrases, remote-cluster secrets) stored beside the
config, plus the ``elasticsearch-keystore`` CLI
(``distribution/tools/keystore-cli/``).

Format (versioned, all big-endian):
  magic ``ESTPUKS1`` | salt(16) | nonce(16) | ciphertext | hmac-tag(32)

Crypto is stdlib-only by necessity (no ``cryptography`` wheel in the
image): PBKDF2-HMAC-SHA256 key derivation, then an HMAC-SHA256 counter
keystream (CTR construction over a PRF) for confidentiality and an
encrypt-then-MAC tag over header+ciphertext for integrity. An empty
password (the reference's default since 7.x) still encrypts — obfuscation
at rest, real protection when a password is set.
"""
from __future__ import annotations

import hashlib
import hmac
import json
import os
import struct
from typing import Dict, Optional

from .errors import ElasticsearchError, IllegalArgumentError

MAGIC = b"ESTPUKS1"
PBKDF2_ITERS = 120_000


class KeystoreError(ElasticsearchError):
    status = 500
    error_type = "security_exception"


def _derive(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt,
                               PBKDF2_ITERS, dklen=64)


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = bytearray()
    counter = 0
    while len(out) < n:
        block = hmac.new(key, nonce + struct.pack(">Q", counter),
                         hashlib.sha256).digest()
        out.extend(block)
        counter += 1
    return bytes(out[:n])


class Keystore:
    """In-memory view of the secure settings + load/save."""

    FILENAME = "estpu.keystore"

    def __init__(self, path: str, password: str = ""):
        self.path = path
        self.password = password
        self.entries: Dict[str, str] = {}

    # -- persistence ----------------------------------------------------
    def save(self) -> None:
        salt = os.urandom(16)
        nonce = os.urandom(16)
        keys = _derive(self.password, salt)
        enc_key, mac_key = keys[:32], keys[32:]
        plain = json.dumps(self.entries, sort_keys=True).encode()
        cipher = bytes(a ^ b for a, b in
                       zip(plain, _keystream(enc_key, nonce, len(plain))))
        header = MAGIC + salt + nonce
        tag = hmac.new(mac_key, header + cipher, hashlib.sha256).digest()
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "wb") as fh:
            fh.write(header + cipher + tag)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)

    @classmethod
    def load(cls, path: str, password: str = "") -> "Keystore":
        ks = cls(path, password)
        with open(path, "rb") as fh:
            blob = fh.read()
        if len(blob) < len(MAGIC) + 16 + 16 + 32 or \
                not blob.startswith(MAGIC):
            raise KeystoreError(f"[{path}] is not a keystore file")
        salt = blob[len(MAGIC): len(MAGIC) + 16]
        nonce = blob[len(MAGIC) + 16: len(MAGIC) + 32]
        cipher = blob[len(MAGIC) + 32: -32]
        tag = blob[-32:]
        keys = _derive(password, salt)
        enc_key, mac_key = keys[:32], keys[32:]
        header = MAGIC + salt + nonce
        expect = hmac.new(mac_key, header + cipher,
                          hashlib.sha256).digest()
        if not hmac.compare_digest(tag, expect):
            raise KeystoreError(
                "Provided keystore password was incorrect")
        plain = bytes(a ^ b for a, b in
                      zip(cipher, _keystream(enc_key, nonce,
                                             len(cipher))))
        ks.entries = json.loads(plain.decode())
        return ks

    @classmethod
    def load_or_create(cls, path: str,
                       password: str = "") -> "Keystore":
        if os.path.exists(path):
            return cls.load(path, password)
        return cls(path, password)

    # -- entry API ------------------------------------------------------
    def set(self, key: str, value: str) -> None:
        if not key or key != key.lower():
            raise IllegalArgumentError(
                f"Setting name [{key}] does not match the allowed "
                f"setting name pattern [[a-z0-9_\\-.]+]")
        self.entries[key] = value

    def get(self, key: str) -> Optional[str]:
        return self.entries.get(key)

    def remove(self, key: str) -> None:
        if key not in self.entries:
            raise IllegalArgumentError(
                f"Setting [{key}] does not exist in the keystore.")
        del self.entries[key]

    def list_keys(self):
        return sorted(self.entries)
