"""Native users, roles, and role-based authorization.

Reference: ``x-pack/plugin/security/`` — the native realm
(``authc/esnative/NativeUsersStore.java``) stores PBKDF2-hashed users in
a system index; the role store (``authz/store/NativeRolesStore.java``)
holds role descriptors with cluster privileges, index privileges,
document-level security queries, and field-level security grants; the
authorization service (``authz/AuthorizationService.java``) resolves the
union of a user's roles and checks every transport action against them.

Same model here, sized to this build: users/roles live in the service
(persisted beside the API keys when a path is configured), Basic auth
rides the same ``authenticate`` entry the API keys use, and every REST
dispatch classifies into (scope, privilege-kind) — the observable
granularity of the reference's action matrix: index read / write /
admin / monitor, cluster monitor / admin — plus DLS/FLS effects that
the search path applies.
"""
from __future__ import annotations

import base64
import hashlib
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from ..common.errors import (ElasticsearchError, IllegalArgumentError,
                             ResourceNotFoundError)


class AuthorizationError(ElasticsearchError):
    error_type = "security_exception"
    status = 403


def _hash_pw(password: str, salt: bytes) -> str:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt,
                               10_000).hex()


#: index privilege → granted kinds (IndexPrivilege.java's named sets)
_INDEX_PRIVS: Dict[str, frozenset] = {
    "all": frozenset({"read", "write", "admin", "monitor"}),
    "read": frozenset({"read"}),
    "write": frozenset({"write"}),
    "index": frozenset({"write"}),
    "create": frozenset({"write"}),
    "create_doc": frozenset({"write"}),
    "delete": frozenset({"write"}),
    "create_index": frozenset({"admin"}),
    "delete_index": frozenset({"admin"}),
    "manage": frozenset({"admin", "monitor"}),
    "monitor": frozenset({"monitor"}),
    "view_index_metadata": frozenset({"monitor"}),
}

#: cluster privilege → granted kinds (ClusterPrivilegeResolver.java)
_CLUSTER_PRIVS: Dict[str, frozenset] = {
    "all": frozenset({"monitor", "admin"}),
    "monitor": frozenset({"monitor"}),
    "manage": frozenset({"monitor", "admin"}),
    "manage_security": frozenset({"monitor", "admin"}),
    "manage_index_templates": frozenset({"monitor", "admin"}),
    "manage_ml": frozenset({"monitor", "admin"}),
    "manage_ilm": frozenset({"monitor", "admin"}),
    "manage_slm": frozenset({"monitor", "admin"}),
}

#: built-in reserved roles (subset of ReservedRolesStore.java)
BUILTIN_ROLES: Dict[str, dict] = {
    "superuser": {
        "cluster": ["all"],
        "indices": [{"names": ["*"], "privileges": ["all"],
                     "allow_restricted_indices": True}],
        "metadata": {"_reserved": True}},
    "monitoring_user": {
        "cluster": ["monitor"],
        "indices": [{"names": [".monitoring-*"],
                     "privileges": ["read"]}],
        "metadata": {"_reserved": True}},
    "viewer": {
        "cluster": [],
        "indices": [{"names": ["*"], "privileges": ["read",
                                                    "view_index_metadata"]}],
        "metadata": {"_reserved": True}},
    "editor": {
        "cluster": [],
        "indices": [{"names": ["*"],
                     "privileges": ["read", "write", "create_index",
                                    "view_index_metadata"]}],
        "metadata": {"_reserved": True}},
}


#: top-level (indexless) endpoints that are DATA operations over all
#: indices, not cluster admin — they authorize as index ops on "*"
_TOP_LEVEL_READ = {"_search", "_msearch", "_count", "_mget",
                   "_field_caps", "_rank_eval", "_async_search",
                   "_knn_search", "_sql", "_render", "_search_shards",
                   "_mtermvectors", "_pit"}
_TOP_LEVEL_WRITE = {"_bulk", "_reindex"}


def classify_request(method: str, path: str) -> Tuple[str, str, str]:
    """(scope, kind, index_expr) for one REST request — the authz
    checkpoint granularity.  scope: "index"|"cluster".  kind for index:
    read|write|admin|monitor; for cluster: monitor|admin."""
    p = path.rstrip("/") or "/"
    if p == "/" or p in ("/_xpack", "/_license"):
        return "cluster", "monitor", ""
    seg = p.split("/")[1]
    if seg.startswith("_"):
        base = seg.split("?")[0]
        if base in _TOP_LEVEL_READ:
            return "index", "read", "*"
        if base in _TOP_LEVEL_WRITE:
            return "index", "write", "*"
        if base == "_security":
            # user/role/key management is privileged regardless of verb
            # (manage_security); self-service paths are exempted at the
            # dispatch layer before this runs
            return "cluster", "admin", ""
        if method == "GET":
            return "cluster", "monitor", ""
        return "cluster", "admin", ""
    index = seg
    rest = "/" + "/".join(p.split("/")[2:]) if "/" in p[1:] else ""
    read_eps = ("_search", "_msearch", "_count", "_doc", "_source",
                "_mget", "_explain", "_termvectors", "_mtermvectors",
                "_field_caps", "_rank_eval", "_validate", "_graph",
                "_knn_search", "_eql", "_async_search", "_pit",
                "_searchable_snapshots")
    write_eps = ("_bulk", "_create", "_update", "_delete_by_query",
                 "_update_by_query", "_rollover")
    monitor_eps = ("_stats", "_segments", "_recovery", "_shard_stores",
                   "_settings", "_mapping", "_alias", "_ilm")
    first = rest.split("/")[1] if len(rest) > 1 else ""
    if first in read_eps:
        if first == "_doc" and method in ("PUT", "POST", "DELETE"):
            return "index", "write", index
        return "index", "read", index
    if first in write_eps:
        return "index", "write", index
    if first in monitor_eps and method in ("GET", "HEAD"):
        return "index", "monitor", index
    if not first and method in ("GET", "HEAD"):
        return "index", "monitor", index
    return "index", "admin", index


class RbacService:
    """Users + roles + the authorize() checkpoint."""

    def __init__(self):
        self.users: Dict[str, dict] = {}
        self.roles: Dict[str, dict] = {}
        #: owner's persistence hook (SecurityService wires its own)
        self._on_change = lambda: None

    # -- users -----------------------------------------------------------
    def put_user(self, username: str, body: dict) -> dict:
        if not re.fullmatch(r"[a-zA-Z0-9_@.+-]+", username or ""):
            raise IllegalArgumentError(
                f"invalid user name [{username}]")
        existing = self.users.get(username)
        password = body.get("password")
        if password is None and existing is None:
            raise IllegalArgumentError(
                "password must be specified unless you are updating an "
                "existing user")
        if password is not None and len(str(password)) < 6:
            raise IllegalArgumentError(
                "passwords must be at least [6] characters long")
        rec = dict(existing or {})
        if password is not None:
            salt = os.urandom(16)
            rec["salt"] = salt.hex()
            rec["hash"] = _hash_pw(str(password), salt)
        rec["roles"] = list(body.get("roles",
                                     rec.get("roles") or []))
        for k in ("full_name", "email", "metadata"):
            if k in body:
                rec[k] = body[k]
        rec.setdefault("enabled", True)
        created = existing is None
        self.users[username] = rec
        self._on_change()
        return {"created": created}

    def get_users(self, username: Optional[str]) -> dict:
        if username:
            missing = [u for u in username.split(",")
                       if u not in self.users]
            if missing:
                raise ResourceNotFoundError(
                    f"user [{missing[0]}] not found")
            names = username.split(",")
        else:
            names = sorted(self.users)
        return {u: self._user_view(u) for u in names}

    def _user_view(self, username: str) -> dict:
        r = self.users[username]
        return {"username": username, "roles": r.get("roles") or [],
                "full_name": r.get("full_name"),
                "email": r.get("email"),
                "metadata": r.get("metadata") or {},
                "enabled": r.get("enabled", True)}

    def delete_user(self, username: str) -> dict:
        if username not in self.users:
            return {"found": False}
        del self.users[username]
        self._on_change()
        return {"found": True}

    def change_password(self, username: str, body: dict) -> dict:
        if username not in self.users:
            raise ResourceNotFoundError(f"user [{username}] not found")
        password = body.get("password")
        if not password or len(str(password)) < 6:
            raise IllegalArgumentError(
                "passwords must be at least [6] characters long")
        salt = os.urandom(16)
        self.users[username]["salt"] = salt.hex()
        self.users[username]["hash"] = _hash_pw(str(password), salt)
        self._on_change()
        return {}

    def set_enabled(self, username: str, enabled: bool) -> dict:
        if username not in self.users:
            raise ResourceNotFoundError(f"user [{username}] not found")
        self.users[username]["enabled"] = enabled
        self._on_change()
        return {}

    def verify_password(self, username: str,
                        password: str) -> Optional[dict]:
        rec = self.users.get(username)
        if rec is None or not rec.get("enabled", True):
            return None
        if _hash_pw(password, bytes.fromhex(rec["salt"])) != rec["hash"]:
            return None
        return self._user_view(username)

    # -- roles -----------------------------------------------------------
    def put_role(self, name: str, body: dict) -> dict:
        if name in BUILTIN_ROLES:
            raise IllegalArgumentError(
                f"role [{name}] is reserved and cannot be modified")
        for priv in body.get("cluster") or []:
            if priv not in _CLUSTER_PRIVS:
                raise IllegalArgumentError(
                    f"unknown cluster privilege [{priv}]")
        for entry in body.get("indices") or []:
            if not entry.get("names"):
                raise IllegalArgumentError(
                    "indices privileges must refer to at least one "
                    "index name")
            for priv in entry.get("privileges") or []:
                if priv not in _INDEX_PRIVS:
                    raise IllegalArgumentError(
                        f"unknown index privilege [{priv}]")
            if not entry.get("privileges"):
                raise IllegalArgumentError(
                    "indices privileges must define at least one "
                    "privilege")
        created = name not in self.roles
        self.roles[name] = {
            "cluster": list(body.get("cluster") or []),
            "indices": [dict(e) for e in body.get("indices") or []],
            "run_as": list(body.get("run_as") or []),
            "metadata": body.get("metadata") or {},
            "transient_metadata": {"enabled": True}}
        self._on_change()
        return {"role": {"created": created}}

    def get_roles(self, name: Optional[str]) -> dict:
        all_roles = {**BUILTIN_ROLES, **self.roles}
        if name:
            missing = [n for n in name.split(",")
                       if n not in all_roles]
            if missing:
                raise ResourceNotFoundError(
                    f"role [{missing[0]}] not found")
            names = name.split(",")
        else:
            names = sorted(self.roles)     # GET all lists custom only
        return {n: self._role_view(all_roles[n]) for n in names}

    @staticmethod
    def _role_view(r: dict) -> dict:
        return {"cluster": r.get("cluster") or [],
                "indices": r.get("indices") or [],
                "run_as": r.get("run_as") or [],
                "metadata": {k: v for k, v in
                             (r.get("metadata") or {}).items()
                             if not k.startswith("_")},
                "transient_metadata": {"enabled": True}}

    def delete_role(self, name: str) -> dict:
        if name in BUILTIN_ROLES:
            raise IllegalArgumentError(
                f"role [{name}] is reserved and cannot be deleted")
        if name not in self.roles:
            return {"found": False}
        del self.roles[name]
        self._on_change()
        return {"found": True}

    # -- authorization ---------------------------------------------------
    def _resolve(self, role_names: List[str],
                 inline: Optional[List[dict]] = None) -> List[dict]:
        out = []
        for n in role_names or []:
            r = self.roles.get(n) or BUILTIN_ROLES.get(n)
            if r is not None:
                out.append(r)
        out.extend(inline or [])
        return out

    @staticmethod
    def _index_matches(patterns: List[str], index: str) -> bool:
        import fnmatch
        return any(fnmatch.fnmatchcase(index, p) for p in patterns)

    def authorize(self, principal: dict, method: str,
                  path: str) -> None:
        """403 unless some resolved role grants the classified
        (scope, kind) on the target (AuthorizationService.authorize)."""
        roles = self._resolve(principal.get("roles") or [],
                              principal.get("_inline_roles"))
        scope, kind, index_expr = classify_request(method, path)
        username = principal.get("username", "_unknown")
        if scope == "cluster":
            # the root ping needs authentication only, like the
            # reference's main action
            if path.rstrip("/") in ("", "/"):
                return
            for r in roles:
                for priv in r.get("cluster") or []:
                    if kind in _CLUSTER_PRIVS.get(priv, ()):
                        return
            raise AuthorizationError(
                f"action [cluster:{kind}] is unauthorized for user "
                f"[{username}]")
        # index scope: EVERY named index must be granted
        targets = [i for i in (index_expr or "").split(",") if i] \
            or ["*"]
        for target in targets:
            ok = False
            for r in roles:
                for e in r.get("indices") or []:
                    if not self._index_matches(e.get("names") or [],
                                               target):
                        continue
                    granted = set()
                    for priv in e.get("privileges") or []:
                        granted |= _INDEX_PRIVS.get(priv, frozenset())
                    if kind in granted:
                        ok = True
                        break
                if ok:
                    break
            if not ok:
                raise AuthorizationError(
                    f"action [indices:{kind}] is unauthorized for "
                    f"user [{username}] on indices [{target}]")

    def dls_fls(self, principal: dict,
                index: str) -> Tuple[List[Any], Optional[List[str]]]:
        """(dls_queries, fls_grant) effective for one index read.

        Reference semantics (``authz/accesscontrol/``): DLS queries
        from multiple roles OR together; FLS grants union.  A role
        entry granting read WITHOUT restrictions lifts both."""
        roles = self._resolve(principal.get("roles") or [],
                              principal.get("_inline_roles"))
        queries: List[Any] = []
        fields: List[str] = []
        unrestricted = False
        for r in roles:
            for e in r.get("indices") or []:
                if not self._index_matches(e.get("names") or [], index):
                    continue
                granted = set()
                for priv in e.get("privileges") or []:
                    granted |= _INDEX_PRIVS.get(priv, frozenset())
                if "read" not in granted:
                    continue
                q = e.get("query")
                fs = (e.get("field_security") or {}).get("grant")
                if q is None and fs is None:
                    unrestricted = True
                if q is not None:
                    import json as _json
                    queries.append(_json.loads(q)
                                   if isinstance(q, str) else q)
                if fs is not None:
                    fields.extend(fs)
        if unrestricted:
            return [], None
        return queries, (fields if fields else None)

    def has_privileges(self, principal: dict, body: dict) -> dict:
        roles = self._resolve(principal.get("roles") or [],
                              principal.get("_inline_roles"))
        cluster_have = set()
        for r in roles:
            for p in r.get("cluster") or []:
                cluster_have |= _CLUSTER_PRIVS.get(p, frozenset())
        cluster_res = {}
        for priv in body.get("cluster") or []:
            want = _CLUSTER_PRIVS.get(priv, frozenset({priv}))
            cluster_res[priv] = bool(want) and want <= cluster_have
        index_res: Dict[str, dict] = {}
        for entry in body.get("index") or []:
            for name in entry.get("names") or []:
                per = index_res.setdefault(name, {})
                have = set()
                for r in roles:
                    for e in r.get("indices") or []:
                        if self._index_matches(
                                e.get("names") or [], name):
                            for p in e.get("privileges") or []:
                                have |= _INDEX_PRIVS.get(
                                    p, frozenset())
                for priv in entry.get("privileges") or []:
                    want = _INDEX_PRIVS.get(priv, frozenset())
                    per[priv] = bool(want) and want <= have
        all_ok = all(cluster_res.values()) and all(
            v for per in index_res.values() for v in per.values())
        return {"username": principal.get("username"),
                "has_all_requested": all_ok,
                "cluster": cluster_res,
                "index": index_res,
                "application": {}}
