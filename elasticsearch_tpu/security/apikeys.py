"""API keys with hashed storage + TLS material helper.

Reference: ``x-pack/plugin/security/.../authc/ApiKeyService.java`` — keys
are (id, secret) pairs; the secret is stored only as a salted PBKDF2 hash
(the reference default hasher is PBKDF2 as well); clients authenticate
with ``Authorization: ApiKey base64(id:secret)``. Invalidation is a
tombstone, not a delete, so audit surfaces can still list the key.

Design notes (TPU-era simplifications, documented not hidden):
- principals are key names; there is no realm chain or RBAC — any valid
  key is a full-access user (the reference's role resolution,
  ``authz/RBACEngine.java``, is out of scope this round);
- the key store is in-memory with an optional JSON file behind it
  (hashes only — never secrets);
- PBKDF2 iteration count is 10_000 (reference default for api keys).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import secrets
import time
from typing import Dict, Optional

from ..common.errors import ElasticsearchError

_PBKDF2_ITERS = 10_000


class AuthenticationError(ElasticsearchError):
    """401 security_exception (reference:
    ``ElasticsearchSecurityException`` with RestStatus.UNAUTHORIZED)."""

    status = 401
    error_type = "security_exception"

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["error"]["header"] = {
            "WWW-Authenticate": ['Basic realm="security" charset="UTF-8"',
                                 "ApiKey"]}
        return d


def _hash_secret(secret: str, salt: bytes) -> str:
    dk = hashlib.pbkdf2_hmac("sha256", secret.encode(), salt,
                             _PBKDF2_ITERS)
    return dk.hex()


class SecurityService:
    """API-key issue/verify/invalidate + request authentication."""

    def __init__(self, enabled: bool = False,
                 persist_path: Optional[str] = None):
        self.enabled = enabled
        self.persist_path = persist_path
        # native users + roles + the authorize() checkpoint (rbac.py)
        from .rbac import RbacService
        self.rbac = RbacService()
        self.rbac._on_change = self._persist
        #: key id -> record (secret_hash/salt, name, creation, invalidated)
        self._keys: Dict[str, dict] = {}
        if persist_path and os.path.exists(persist_path):
            try:
                with open(persist_path) as f:
                    blob = json.load(f)
                if "keys" in blob or "users" in blob:
                    self._keys = blob.get("keys") or {}
                    self.rbac.users = blob.get("users") or {}
                    self.rbac.roles = blob.get("roles") or {}
                else:           # pre-RBAC file layout: keys only
                    self._keys = blob
            except (OSError, ValueError):
                self._keys = {}

    # -- key lifecycle ---------------------------------------------------

    def create_key(self, name: str,
                   expiration_ms: Optional[int] = None,
                   role_descriptors: Optional[dict] = None) -> dict:
        """Returns {id, name, api_key, encoded} — the cleartext secret
        appears ONLY in this response (the store keeps the hash)."""
        key_id = secrets.token_urlsafe(15)
        secret = secrets.token_urlsafe(24)
        salt = secrets.token_bytes(16)
        self._keys[key_id] = {
            "name": name,
            "salt": salt.hex(),
            "hash": _hash_secret(secret, salt),
            "creation": int(time.time() * 1000),
            "expiration": (int(time.time() * 1000) + expiration_ms)
            if expiration_ms else None,
            "invalidated": False,
            "role_descriptors": role_descriptors or None,
        }
        self._persist()
        return {"id": key_id, "name": name, "api_key": secret,
                "encoded": base64.b64encode(
                    f"{key_id}:{secret}".encode()).decode()}

    def invalidate(self, ids=None, name: Optional[str] = None) -> dict:
        hit = []
        for kid, rec in self._keys.items():
            if rec["invalidated"]:
                continue
            if (ids and kid in ids) or (name and rec["name"] == name):
                rec["invalidated"] = True
                rec["invalidation"] = int(time.time() * 1000)
                hit.append(kid)
        self._persist()
        return {"invalidated_api_keys": hit,
                "previously_invalidated_api_keys": [],
                "error_count": 0}

    def list_keys(self) -> dict:
        return {"api_keys": [
            {"id": kid, "name": rec["name"], "creation": rec["creation"],
             "invalidated": rec["invalidated"],
             "expiration": rec.get("expiration")}
            for kid, rec in sorted(self._keys.items())]}

    def _persist(self) -> None:
        if not self.persist_path:
            return
        tmp = self.persist_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"keys": self._keys, "users": self.rbac.users,
                       "roles": self.rbac.roles}, f)
        os.replace(tmp, self.persist_path)

    # -- authentication --------------------------------------------------

    def verify(self, key_id: str, secret: str) -> Optional[str]:
        """Key name when (id, secret) is valid and live, else None.
        Constant-time hash compare."""
        rec = self._keys.get(key_id)
        if rec is None or rec["invalidated"]:
            return None
        exp = rec.get("expiration")
        if exp is not None and exp < time.time() * 1000:
            return None
        want = rec["hash"]
        got = _hash_secret(secret, bytes.fromhex(rec["salt"]))
        return rec["name"] if hmac.compare_digest(want, got) else None

    def authenticate(self, headers: Optional[dict]) -> dict:
        """Authenticate one REST request from its headers. Returns the
        principal doc; raises :class:`AuthenticationError` (401) when
        credentials are missing or invalid."""
        auth = (headers or {}).get("authorization") or \
            (headers or {}).get("Authorization")
        if not auth:
            raise AuthenticationError(
                "missing authentication credentials for REST request")
        scheme, _, value = auth.partition(" ")
        if scheme.lower() == "apikey":
            try:
                decoded = base64.b64decode(value.strip()).decode()
                key_id, _, secret = decoded.partition(":")
            except Exception:   # noqa: BLE001 — malformed header
                raise AuthenticationError(
                    "unable to authenticate with provided credentials")
            name = self.verify(key_id, secret)
            if name is None:
                raise AuthenticationError(
                    "unable to authenticate api key "
                    f"[{key_id}]")
            rec = self._keys.get(key_id) or {}
            principal = {"username": name,
                         "authentication_type": "api_key",
                         "api_key": {"id": key_id, "name": name}}
            rds = rec.get("role_descriptors")
            if rds:
                # API keys with role_descriptors are LIMITED to them;
                # without, they act as the superuser-equivalent owner
                # (the observable shape of the reference's owner-scoped
                # keys under the default operator setup)
                principal["_inline_roles"] = list(rds.values()) \
                    if isinstance(rds, dict) else list(rds)
            else:
                principal["roles"] = ["superuser"]
            return principal
        if scheme.lower() == "basic":
            try:
                decoded = base64.b64decode(value.strip()).decode()
                username, _, password = decoded.partition(":")
            except Exception:   # noqa: BLE001 — malformed header
                raise AuthenticationError(
                    "unable to authenticate with provided credentials")
            view = self.rbac.verify_password(username, password)
            if view is None:
                raise AuthenticationError(
                    f"unable to authenticate user [{username}] for "
                    f"REST request")
            return dict(view, authentication_type="realm")
        raise AuthenticationError(
            f"unsupported authentication scheme [{scheme}]")


def make_self_signed_tls(cert_dir: str, common_name: str = "localhost"):
    """Generate a self-signed cert/key pair and return
    (server_ssl_context, client_ssl_context) — the client context trusts
    exactly this cert (the reference ships ``elasticsearch-certutil``;
    this is its minimum in-process equivalent for tests and dev)."""
    import ssl
    from datetime import datetime, timedelta, timezone

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(cert_dir, exist_ok=True)
    cert_path = os.path.join(cert_dir, "node.crt")
    key_path = os.path.join(cert_dir, "node.key")
    if not (os.path.exists(cert_path) and os.path.exists(key_path)):
        key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
        now = datetime.now(timezone.utc)
        cert = (x509.CertificateBuilder()
                .subject_name(name).issuer_name(name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - timedelta(minutes=5))
                .not_valid_after(now + timedelta(days=365))
                .add_extension(x509.SubjectAlternativeName(
                    [x509.DNSName(common_name),
                     x509.DNSName("127.0.0.1")]), critical=False)
                .sign(key, hashes.SHA256()))
        with open(key_path, "wb") as f:
            f.write(key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption()))
        with open(cert_path, "wb") as f:
            f.write(cert.public_bytes(serialization.Encoding.PEM))
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(cert_path, key_path)
    client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client_ctx.load_verify_locations(cert_path)
    client_ctx.check_hostname = False
    return server_ctx, client_ctx
