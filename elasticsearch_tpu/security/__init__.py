"""Security layer: API-key authentication on REST, shared-secret
challenge-response on the node-to-node transport, optional TLS on both
planes (reference: ``x-pack/plugin/security/`` — ``ApiKeyService.java``,
``authc/``, transport interceptors). Off by default so the open
conformance corpus runs unchanged; enabling flips every REST request to
require credentials and every transport connection to complete the
handshake."""

from .apikeys import (AuthenticationError, SecurityService,
                      make_self_signed_tls)

__all__ = ["AuthenticationError", "SecurityService",
           "make_self_signed_tls"]
