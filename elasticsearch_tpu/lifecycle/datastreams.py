"""Data streams: generational backing indices behind one write surface.

Reference: ``cluster/metadata/MetadataCreateDataStreamService.java:54``,
``cluster/metadata/DataStream.java`` — a stream requires a matching
composable template carrying ``data_stream: {}``; documents land in the
current write index (the highest generation); rollover mints
``.ds-<name>-<generation+1>``; reads resolve to every backing index.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..common.errors import (IllegalArgumentError, IndexNotFoundError,
                             ResourceAlreadyExistsError,
                             ResourceNotFoundError)


def backing_name(stream: str, generation: int) -> str:
    return f".ds-{stream}-{generation:06d}"


class DataStreamService:
    """Stream registry + lifecycle, operating through the owning
    RestAPI's indices service (creation runs the full index machinery:
    templates, mappings, allocation on the cluster tier)."""

    def __init__(self, api):
        self.api = api
        #: name -> {"generation": int, "indices": [names], "template": str}
        self.streams: Dict[str, dict] = {}

    # -- template matching ----------------------------------------------

    def matching_template(self, name: str) -> Optional[str]:
        """Highest-priority composable template with ``data_stream`` whose
        patterns match ``name`` (reference: the stream's defining
        template)."""
        import fnmatch
        best = None
        for tname, t in self.api.templates.items():
            if "data_stream" not in t:
                continue
            pats = t.get("index_patterns") or []
            if any(fnmatch.fnmatchcase(name, p) for p in pats):
                pr = int(t.get("priority", 0))
                if best is None or pr > best[0]:
                    best = (pr, tname)
        return best[1] if best else None

    # -- CRUD ------------------------------------------------------------

    def create(self, name: str) -> dict:
        if name in self.streams:
            raise ResourceAlreadyExistsError(
                f"data_stream [{name}] already exists")
        tpl = self.matching_template(name)
        if tpl is None:
            raise IllegalArgumentError(
                f"no matching index template found for data stream "
                f"[{name}]")
        self.streams[name] = {"generation": 0, "indices": [],
                              "template": tpl}
        self._roll(name)
        return {"acknowledged": True}

    def _roll(self, name: str) -> str:
        """Mint the next backing index and make it the write index."""
        st = self.streams[name]
        st["generation"] += 1
        backing = backing_name(name, st["generation"])
        # the template's mappings/settings apply through the normal
        # create path (templates match .ds-* only via the stream's own
        # patterns, so merge the defining template explicitly)
        t = self.api.templates.get(st["template"]) or {}
        body_tpl = t.get("template") or {}
        mappings = dict(body_tpl.get("mappings") or {})
        props = dict((mappings.get("properties") or {}))
        props.setdefault("@timestamp", {"type": "date"})
        mappings["properties"] = props
        self.api.indices.create_index(
            backing, body_tpl.get("settings") or {}, mappings)
        st["indices"].append(backing)
        self._after_meta_change()
        return backing

    def delete(self, pattern: str) -> dict:
        import fnmatch
        hit = [n for n in self.streams
               if fnmatch.fnmatchcase(n, pattern)] if any(
                   c in pattern for c in "*?") else (
                       [pattern] if pattern in self.streams else [])
        if not hit and not any(c in pattern for c in "*?"):
            raise ResourceNotFoundError(
                f"data_stream matching [{pattern}] not found")
        for n in hit:
            st = self.streams.pop(n)
            for idx in st["indices"]:
                try:
                    self.api.indices.delete_index(idx)
                except IndexNotFoundError:
                    pass
        self._after_meta_change()
        return {"acknowledged": True}

    def get(self, pattern: Optional[str]) -> dict:
        import fnmatch
        names = sorted(self.streams) if not pattern or pattern in (
            "*", "_all") else [
            n for n in sorted(self.streams)
            if fnmatch.fnmatchcase(n, pattern)] if any(
                c in pattern for c in "*?") else (
                    [pattern] if pattern in self.streams else None)
        if names is None:
            raise ResourceNotFoundError(
                f"data_stream matching [{pattern}] not found")
        out = []
        for n in names:
            st = self.streams[n]
            out.append({
                "name": n,
                "timestamp_field": {"name": "@timestamp"},
                "indices": [
                    {"index_name": idx,
                     "index_uuid": getattr(
                         self.api.indices.indices.get(idx), "uuid", "")}
                    for idx in st["indices"]],
                "generation": st["generation"],
                "status": "GREEN",
                "template": st["template"],
            })
        return {"data_streams": out}

    # -- write/read routing ---------------------------------------------

    def write_index(self, name: str) -> Optional[str]:
        st = self.streams.get(name)
        return st["indices"][-1] if st and st["indices"] else None

    def backing_indices(self, name: str) -> Optional[List[str]]:
        st = self.streams.get(name)
        return list(st["indices"]) if st else None

    def rollover(self, name: str) -> dict:
        if name not in self.streams:
            raise ResourceNotFoundError(
                f"data_stream [{name}] not found")
        old = self.write_index(name)
        new = self._roll(name)
        return {"acknowledged": True, "rolled_over": True,
                "old_index": old, "new_index": new,
                "dry_run": False, "shards_acknowledged": True,
                "conditions": {}}

    def auto_create(self, name: str) -> Optional[str]:
        """First write to an unknown name whose matching template is a
        data-stream template: create the stream, return its write index
        (reference: auto-create flows through the same metadata
        service)."""
        if name in self.streams:
            return self.write_index(name)
        if self.matching_template(name) is None:
            return None
        self.create(name)
        return self.write_index(name)

    def _after_meta_change(self) -> None:
        """Expression resolution consults the registry through the
        indices service (streams resolve like aliases)."""
        self.api.indices.data_streams_provider = self.backing_indices
