"""ILM-lite: a policy state machine over hot-rollover and delete phases.

Reference: ``x-pack/plugin/ilm/.../IndexLifecycleService.java:52`` +
``TimeseriesLifecycleType`` (phase ordering). The two load-bearing phases
are implemented — hot (rollover on max_age/max_docs) and delete
(min_age) — driven by an injectable clock through ``tick(now_ms)`` so
tests step time instead of sleeping; the reference runs the identical
evaluation from a scheduler every ``indices.lifecycle.poll_interval``.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..common.errors import ResourceNotFoundError
from ..common.settings import parse_time_millis


class IlmService:
    def __init__(self, api):
        self.api = api
        self.policies: Dict[str, dict] = {}

    # -- policy CRUD -----------------------------------------------------

    def put_policy(self, name: str, policy: dict) -> dict:
        self.policies[name] = policy or {}
        return {"acknowledged": True}

    def get_policy(self, name: Optional[str]) -> dict:
        if name and name not in self.policies:
            raise ResourceNotFoundError(
                f"Lifecycle policy not found: [{name}]")
        names = [name] if name else sorted(self.policies)
        return {n: {"policy": self.policies[n], "version": 1}
                for n in names}

    def delete_policy(self, name: str) -> dict:
        if name not in self.policies:
            raise ResourceNotFoundError(
                f"Lifecycle policy not found: [{name}]")
        del self.policies[name]
        return {"acknowledged": True}

    # -- evaluation ------------------------------------------------------

    def _policy_of(self, svc) -> Optional[dict]:
        pname = svc.settings.get("index.lifecycle.name")
        return self.policies.get(pname) if pname else None

    def tick(self, now_ms: Optional[int] = None) -> dict:
        """One evaluation round: apply hot-phase rollover conditions and
        delete-phase expiry to every policy-managed index. Returns what
        happened (for observability and tests)."""
        now_ms = int(time.time() * 1000) if now_ms is None else int(now_ms)
        rolled, deleted = [], []
        for name, svc in list(self.api.indices.indices.items()):
            policy = self._policy_of(svc)
            if policy is None:
                continue
            phases = (policy.get("policy") or policy).get("phases") or {}
            age_ms = now_ms - svc.creation_date
            dl = phases.get("delete") or {}
            if "delete" in (dl.get("actions") or {}):
                min_age = parse_time_millis(dl.get("min_age", "0ms"))
                if age_ms >= min_age:
                    # a data stream's non-write backing index deletes;
                    # its write index waits for the next rollover first
                    ds = self._owning_stream(name)
                    if ds is None or \
                            self.api.datastreams.write_index(ds) != name:
                        self.api.indices.delete_index(name)
                        if ds is not None:
                            st = self.api.datastreams.streams[ds]
                            st["indices"].remove(name)
                        deleted.append(name)
                        continue
            hot = phases.get("hot") or {}
            ro = (hot.get("actions") or {}).get("rollover")
            if ro:
                ds = self._owning_stream(name)
                if ds is not None and \
                        self.api.datastreams.write_index(ds) == name and \
                        self._rollover_due(svc, ro, age_ms):
                    self.api.datastreams.rollover(ds)
                    rolled.append(ds)
        return {"rolled_over": rolled, "deleted": deleted}

    def _owning_stream(self, index: str) -> Optional[str]:
        for ds, st in self.api.datastreams.streams.items():
            if index in st["indices"]:
                return ds
        return None

    @staticmethod
    def _rollover_due(svc, conditions: dict, age_ms: int) -> bool:
        if "max_age" in conditions and age_ms >= parse_time_millis(
                conditions["max_age"]):
            return True
        if "max_docs" in conditions:
            docs = sum(s.doc_count for s in svc.shards)
            if docs >= int(conditions["max_docs"]):
                return True
        return False

    def explain(self, index: str) -> dict:
        svc = self.api.indices.get(index)
        pname = svc.settings.get("index.lifecycle.name")
        out = {"index": index, "managed": pname is not None}
        if pname:
            out.update({"policy": pname,
                        "age": f"{max(0, int(time.time() * 1000) - svc.creation_date) // 1000}s",
                        "phase": "hot"})
        return out
