"""Data streams + index lifecycle management (ILM-lite).

Reference: ``cluster/metadata/MetadataCreateDataStreamService.java:54``
(streams over generational backing indices with an @timestamp contract)
and ``x-pack/plugin/ilm/.../IndexLifecycleService.java:52`` (policy state
machine driving rollover/delete). Re-design notes:

- a data stream is registry state on the IndicesService: name →
  {generation, indices:[backing names], template}; backing indices are
  ordinary indices named ``.ds-<stream>-<NNNNNN>`` whose resolution rides
  the existing expression resolver (stream name → its backing list, like
  an alias with a write index = the latest generation);
- ILM policies evaluate on an injectable clock (``tick(now)``), so tests
  drive phase transitions deterministically instead of sleeping — the
  reference runs the same logic off a scheduler thread.
"""

from .datastreams import DataStreamService
from .ilm import IlmService

__all__ = ["DataStreamService", "IlmService"]
