"""Low-level client transport: load balancing, dead-node marking,
retries, sniffing.

Reference: ``client/rest/.../RestClient.java`` — round-robin over
configured hosts, failed hosts quarantined with exponentially growing
dead-times and revived after timeout (or when all are dead), retries on
connection errors against the next host; ``client/sniffer/
ElasticsearchNodesSniffer.java`` refreshes the host list from
``GET /_nodes``.
"""
from __future__ import annotations

import http.client
import json
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class TransportError(Exception):
    """Non-2xx response with the parsed error body attached."""

    def __init__(self, status: int, info: Any):
        self.status_code = status
        self.info = info
        reason = info
        if isinstance(info, dict):
            err = info.get("error")
            if isinstance(err, dict):
                reason = err.get("reason", err.get("type"))
            elif err is not None:
                reason = err
        super().__init__(f"TransportError({status}, {reason!r})")


class ConnectionError(TransportError):           # noqa: A001
    def __init__(self, info: Any):
        Exception.__init__(self, f"ConnectionError: {info}")
        self.status_code = None
        self.info = info


class _Host:
    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.failed_attempts = 0
        self.dead_until = 0.0

    def mark_dead(self) -> None:
        self.failed_attempts += 1
        # 1min base doubling per failure, capped at 30min (RestClient's
        # DEFAULT_DEAD_TIMEOUT schedule)
        timeout = min(60.0 * (2 ** (self.failed_attempts - 1)), 1800.0)
        self.dead_until = time.monotonic() + timeout

    def mark_alive(self) -> None:
        self.failed_attempts = 0
        self.dead_until = 0.0

    @property
    def alive(self) -> bool:
        return time.monotonic() >= self.dead_until

    def __repr__(self):
        return f"{self.host}:{self.port}"


class ClientTransport:
    def __init__(self, hosts: List[str], timeout: float = 30.0,
                 max_retries: int = 3,
                 headers: Optional[Dict[str, str]] = None):
        self._hosts: List[_Host] = []
        for h in hosts:
            if "://" in h:
                h = h.split("://", 1)[1]
            name, _, port = h.partition(":")
            self._hosts.append(_Host(name, int(port or 9200)))
        if not self._hosts:
            raise ValueError("at least one host is required")
        self.timeout = timeout
        self.max_retries = max_retries
        self.headers = dict(headers or {})
        self._rr = 0
        self._lock = threading.Lock()

    # -- host selection -------------------------------------------------
    def _next_host(self) -> _Host:
        with self._lock:
            n = len(self._hosts)
            for _ in range(n):
                h = self._hosts[self._rr % n]
                self._rr += 1
                if h.alive:
                    return h
            # all dead: revive the least-recently-failed (RestClient
            # retries the host that has been dead the longest)
            return min(self._hosts, key=lambda x: x.dead_until)

    def sniff(self) -> None:
        """Refresh hosts from GET /_nodes (ElasticsearchNodesSniffer)."""
        status, body = self.perform_request("GET", "/_nodes")
        found: List[_Host] = []
        for node in (body.get("nodes") or {}).values():
            addr = (node.get("http") or {}).get("publish_address") \
                or node.get("transport_address")
            if not addr:
                continue
            host, _, port = str(addr).rpartition(":")
            try:
                found.append(_Host(host or "127.0.0.1", int(port)))
            except ValueError:
                continue
        if found:
            with self._lock:
                self._hosts = found
                self._rr = 0

    # -- request path ---------------------------------------------------
    def perform_request(self, method: str, path: str,
                        params: Optional[dict] = None,
                        body: Any = None,
                        headers: Optional[dict] = None
                        ) -> Tuple[int, Any]:
        query = ""
        if params:
            from urllib.parse import urlencode
            query = "?" + urlencode(
                {k: (str(v).lower() if isinstance(v, bool) else v)
                 for k, v in params.items() if v is not None})
        if isinstance(body, (dict, list)):
            payload: Optional[bytes] = json.dumps(body).encode()
            ctype = "application/json"
        elif isinstance(body, str):
            payload = body.encode()
            ctype = "application/x-ndjson"
        else:
            payload = body
            ctype = "application/json"
        last_err: Optional[Exception] = None
        for _ in range(self.max_retries + 1):
            host = self._next_host()
            try:
                conn = http.client.HTTPConnection(
                    host.host, host.port, timeout=self.timeout)
                try:
                    send_headers = {"Content-Type": ctype,
                                    **self.headers, **(headers or {})}
                    conn.request(method, path + query, payload,
                                 send_headers)
                    resp = conn.getresponse()
                    raw = resp.read()
                finally:
                    conn.close()
            except (OSError, socket.timeout,
                    http.client.HTTPException) as e:
                host.mark_dead()
                last_err = e
                continue
            host.mark_alive()
            ct = resp.getheader("content-type", "")
            if ct.startswith("application/json"):
                parsed: Any = json.loads(raw) if raw else None
            else:
                parsed = raw.decode(errors="replace")
            if resp.status >= 400:
                raise TransportError(resp.status, parsed)
            return resp.status, parsed
        raise ConnectionError(last_err)
