"""Client library (L15): low-level HTTP transport + typed API surface +
bulk/scan helpers.

Reference: ``client/rest`` (``RestClient.java`` — load balancing, dead-
node marking, retries, sniffing hook), ``client/rest-high-level``
(``RestHighLevelClient.java`` — typed request/response mirror), and
``client/sniffer``. The typed surface here is namespace objects over one
``perform_request`` seam rather than 93k LoC of request classes — the
dict-in/dict-out style is the Pythonic shape of the same API.
"""

from .transport import ClientTransport, TransportError, ConnectionError  # noqa: F401
from .api import EsTpuClient  # noqa: F401
from .helpers import bulk, scan  # noqa: F401
