"""Client helpers: chunked bulk + scroll-driven scan.

Reference: ``client/rest-high-level`` ``BulkProcessor`` (chunking/flush)
and the high-level client's scroll helper idiom.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, Optional, Tuple


def bulk(client, actions: Iterable[dict], index: Optional[str] = None,
         chunk_size: int = 500, refresh: bool = False,
         raise_on_error: bool = True) -> Tuple[int, list]:
    """Index an iterable of actions in chunks.

    An action is either a full ``{"_op_type", "_index", "_id", ...doc}``
    dict (op type defaults to ``index``) or a bare source dict when
    ``index`` is given. Returns ``(successes, errors)``.
    """
    import json as _json
    ok = 0
    errors: list = []
    buf: list = []

    def flush():
        nonlocal ok
        if not buf:
            return
        payload = "".join(_json.dumps(x) + "\n" for x in buf)
        params = {"refresh": "true"} if refresh else {}
        resp = client._req("POST",
                           f"/{index}/_bulk" if index else "/_bulk",
                           params, payload)
        for item in resp.get("items", []):
            (_op, detail), = item.items()
            if detail.get("error"):
                errors.append(item)
            else:
                ok += 1
        buf.clear()

    pending_items = 0
    for action in actions:
        a = dict(action)
        op = a.pop("_op_type", "index")
        meta: Dict[str, Any] = {}
        for k in ("_index", "_id", "_routing", "routing"):
            if k in a:
                meta[k if k.startswith("_") else "_" + k] = a.pop(k)
        if index and "_index" not in meta:
            meta["_index"] = index
        buf.append({op: meta})
        if op != "delete":
            buf.append(a.get("_source", a))
        pending_items += 1
        if pending_items >= chunk_size:
            flush()
            pending_items = 0
    flush()
    if errors and raise_on_error:
        raise RuntimeError(f"{len(errors)} document(s) failed to index: "
                           f"{errors[:3]}")
    return ok, errors


def scan(client, index: Optional[str] = None,
         query: Optional[dict] = None, scroll: str = "5m",
         size: int = 1000, clear_scroll: bool = True) -> Iterator[dict]:
    """Iterate every hit of a query via scroll."""
    body = dict(query or {"query": {"match_all": {}}})
    body["size"] = size
    resp = client.search(index=index, body=body,
                         scroll=scroll)
    sid = resp.get("_scroll_id")
    try:
        while True:
            hits = resp["hits"]["hits"]
            if not hits:
                return
            for h in hits:
                yield h
            if sid is None:
                return
            resp = client.scroll(sid, scroll=scroll)
            sid = resp.get("_scroll_id", sid)
    finally:
        if sid and clear_scroll:
            try:
                client.clear_scroll(sid)
            except Exception:   # noqa: BLE001 — best-effort cleanup
                pass
