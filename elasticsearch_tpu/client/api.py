"""Typed client surface: namespaced methods over the transport.

Reference: ``client/rest-high-level/.../RestHighLevelClient.java`` and
its per-feature sub-clients (IndicesClient, ClusterClient, …). Methods
take/return plain dicts — the request classes of the reference collapse
into keyword arguments, the response classes into the parsed JSON.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .transport import ClientTransport


def _esc(name: str) -> str:
    from urllib.parse import quote
    return quote(str(name), safe="*,")


class _Namespace:
    def __init__(self, client: "EsTpuClient"):
        self._c = client


class IndicesClient(_Namespace):
    def create(self, index: str, body: Optional[dict] = None, **params):
        return self._c._req("PUT", f"/{_esc(index)}", params, body)

    def delete(self, index: str, **params):
        return self._c._req("DELETE", f"/{_esc(index)}", params)

    def get(self, index: str, **params):
        return self._c._req("GET", f"/{_esc(index)}", params)

    def exists(self, index: str, **params) -> bool:
        from .transport import TransportError
        try:
            self._c._req("HEAD", f"/{_esc(index)}", params)
            return True
        except TransportError as e:
            if e.status_code == 404:
                return False
            raise

    def refresh(self, index: Optional[str] = None, **params):
        path = f"/{_esc(index)}/_refresh" if index else "/_refresh"
        return self._c._req("POST", path, params)

    def flush(self, index: Optional[str] = None, **params):
        path = f"/{_esc(index)}/_flush" if index else "/_flush"
        return self._c._req("POST", path, params)

    def forcemerge(self, index: str, **params):
        return self._c._req("POST", f"/{_esc(index)}/_forcemerge",
                            params)

    def get_mapping(self, index: str, **params):
        return self._c._req("GET", f"/{_esc(index)}/_mapping", params)

    def put_mapping(self, index: str, body: dict, **params):
        return self._c._req("PUT", f"/{_esc(index)}/_mapping", params,
                            body)

    def get_settings(self, index: str, **params):
        return self._c._req("GET", f"/{_esc(index)}/_settings", params)

    def put_settings(self, index: str, body: dict, **params):
        return self._c._req("PUT", f"/{_esc(index)}/_settings", params,
                            body)

    def put_alias(self, index: str, name: str, **params):
        return self._c._req(
            "PUT", f"/{_esc(index)}/_alias/{_esc(name)}", params)

    def get_alias(self, index: Optional[str] = None, **params):
        path = f"/{_esc(index)}/_alias" if index else "/_alias"
        return self._c._req("GET", path, params)

    def update_aliases(self, body: dict, **params):
        return self._c._req("POST", "/_aliases", params, body)

    def put_index_template(self, name: str, body: dict, **params):
        return self._c._req("PUT", f"/_index_template/{_esc(name)}",
                            params, body)

    def rollover(self, alias: str, body: Optional[dict] = None,
                 **params):
        return self._c._req("POST", f"/{_esc(alias)}/_rollover", params,
                            body)

    def shrink(self, index: str, target: str,
               body: Optional[dict] = None, **params):
        return self._c._req(
            "PUT", f"/{_esc(index)}/_shrink/{_esc(target)}", params,
            body)

    def split(self, index: str, target: str,
              body: Optional[dict] = None, **params):
        return self._c._req(
            "PUT", f"/{_esc(index)}/_split/{_esc(target)}", params, body)

    def stats(self, index: Optional[str] = None, **params):
        path = f"/{_esc(index)}/_stats" if index else "/_stats"
        return self._c._req("GET", path, params)

    def analyze(self, body: dict, index: Optional[str] = None, **params):
        path = f"/{_esc(index)}/_analyze" if index else "/_analyze"
        return self._c._req("GET", path, params, body)

    def open(self, index: str, **params):
        return self._c._req("POST", f"/{_esc(index)}/_open", params)

    def close(self, index: str, **params):
        return self._c._req("POST", f"/{_esc(index)}/_close", params)


class ClusterClient(_Namespace):
    def health(self, index: Optional[str] = None, **params):
        path = f"/_cluster/health/{_esc(index)}" if index \
            else "/_cluster/health"
        return self._c._req("GET", path, params)

    def state(self, metric: Optional[str] = None, **params):
        path = f"/_cluster/state/{metric}" if metric \
            else "/_cluster/state"
        return self._c._req("GET", path, params)

    def stats(self, **params):
        return self._c._req("GET", "/_cluster/stats", params)

    def get_settings(self, **params):
        return self._c._req("GET", "/_cluster/settings", params)

    def put_settings(self, body: dict, **params):
        return self._c._req("PUT", "/_cluster/settings", params, body)

    def reroute(self, body: Optional[dict] = None, **params):
        return self._c._req("POST", "/_cluster/reroute", params, body)

    def allocation_explain(self, body: Optional[dict] = None, **params):
        return self._c._req("GET", "/_cluster/allocation/explain",
                            params, body)


class CatClient(_Namespace):
    def _cat(self, path: str, **params):
        params.setdefault("format", "json")
        return self._c._req("GET", path, params)

    def indices(self, **params):
        return self._cat("/_cat/indices", **params)

    def shards(self, **params):
        return self._cat("/_cat/shards", **params)

    def nodes(self, **params):
        return self._cat("/_cat/nodes", **params)

    def health(self, **params):
        return self._cat("/_cat/health", **params)

    def count(self, **params):
        return self._cat("/_cat/count", **params)

    def aliases(self, **params):
        return self._cat("/_cat/aliases", **params)

    def segments(self, **params):
        return self._cat("/_cat/segments", **params)


class NodesClient(_Namespace):
    def info(self, **params):
        return self._c._req("GET", "/_nodes", params)

    def stats(self, **params):
        return self._c._req("GET", "/_nodes/stats", params)

    def hot_threads(self, **params):
        return self._c._req("GET", "/_nodes/hot_threads", params)


class SnapshotClient(_Namespace):
    def create_repository(self, repository: str, body: dict, **params):
        return self._c._req("PUT", f"/_snapshot/{_esc(repository)}",
                            params, body)

    def create(self, repository: str, snapshot: str,
               body: Optional[dict] = None, **params):
        return self._c._req(
            "PUT", f"/_snapshot/{_esc(repository)}/{_esc(snapshot)}",
            params, body)

    def get(self, repository: str, snapshot: str, **params):
        return self._c._req(
            "GET", f"/_snapshot/{_esc(repository)}/{_esc(snapshot)}",
            params)

    def restore(self, repository: str, snapshot: str,
                body: Optional[dict] = None, **params):
        return self._c._req(
            "POST",
            f"/_snapshot/{_esc(repository)}/{_esc(snapshot)}/_restore",
            params, body)

    def delete(self, repository: str, snapshot: str, **params):
        return self._c._req(
            "DELETE", f"/_snapshot/{_esc(repository)}/{_esc(snapshot)}",
            params)


class SqlClient(_Namespace):
    def query(self, body: dict, **params):
        return self._c._req("POST", "/_sql", params, body)

    def translate(self, body: dict, **params):
        return self._c._req("POST", "/_sql/translate", params, body)

    def clear_cursor(self, body: dict, **params):
        return self._c._req("POST", "/_sql/close", params, body)


class EqlClient(_Namespace):
    def search(self, index: str, body: dict, **params):
        return self._c._req("POST", f"/{_esc(index)}/_eql/search",
                            params, body)


class TasksClient(_Namespace):
    def list(self, **params):
        return self._c._req("GET", "/_tasks", params)

    def get(self, task_id: str, **params):
        return self._c._req("GET", f"/_tasks/{_esc(task_id)}", params)

    def cancel(self, task_id: str, **params):
        return self._c._req("POST", f"/_tasks/{_esc(task_id)}/_cancel",
                            params)


class SecurityClient(_Namespace):
    def create_api_key(self, body: dict, **params):
        return self._c._req("PUT", "/_security/api_key", params, body)

    def invalidate_api_key(self, body: dict, **params):
        return self._c._req("DELETE", "/_security/api_key", params, body)

    def authenticate(self, **params):
        return self._c._req("GET", "/_security/_authenticate", params)

    def put_user(self, username: str, body: dict, **params):
        return self._c._req("PUT", f"/_security/user/{_esc(username)}",
                            params, body)

    def get_user(self, username: Optional[str] = None, **params):
        path = f"/_security/user/{_esc(username)}" if username \
            else "/_security/user"
        return self._c._req("GET", path, params)

    def delete_user(self, username: str, **params):
        return self._c._req("DELETE",
                            f"/_security/user/{_esc(username)}", params)

    def put_role(self, name: str, body: dict, **params):
        return self._c._req("PUT", f"/_security/role/{_esc(name)}",
                            params, body)

    def get_role(self, name: Optional[str] = None, **params):
        path = f"/_security/role/{_esc(name)}" if name \
            else "/_security/role"
        return self._c._req("GET", path, params)

    def delete_role(self, name: str, **params):
        return self._c._req("DELETE", f"/_security/role/{_esc(name)}",
                            params)

    def has_privileges(self, body: dict, **params):
        return self._c._req("POST", "/_security/user/_has_privileges",
                            params, body)


class MlClient(_Namespace):
    def put_job(self, job_id: str, body: dict, **params):
        return self._c._req(
            "PUT", f"/_ml/anomaly_detectors/{_esc(job_id)}", params,
            body)

    def open_job(self, job_id: str, **params):
        return self._c._req(
            "POST", f"/_ml/anomaly_detectors/{_esc(job_id)}/_open",
            params)

    def close_job(self, job_id: str, **params):
        return self._c._req(
            "POST", f"/_ml/anomaly_detectors/{_esc(job_id)}/_close",
            params)

    def get_jobs(self, job_id: Optional[str] = None, **params):
        path = f"/_ml/anomaly_detectors/{_esc(job_id)}" if job_id \
            else "/_ml/anomaly_detectors"
        return self._c._req("GET", path, params)

    def get_buckets(self, job_id: str, body: Optional[dict] = None,
                    **params):
        return self._c._req(
            "POST",
            f"/_ml/anomaly_detectors/{_esc(job_id)}/results/buckets",
            params, body or {})

    def get_records(self, job_id: str, body: Optional[dict] = None,
                    **params):
        return self._c._req(
            "POST",
            f"/_ml/anomaly_detectors/{_esc(job_id)}/results/records",
            params, body or {})

    def put_datafeed(self, feed_id: str, body: dict, **params):
        return self._c._req("PUT", f"/_ml/datafeeds/{_esc(feed_id)}",
                            params, body)

    def start_datafeed(self, feed_id: str, **params):
        return self._c._req(
            "POST", f"/_ml/datafeeds/{_esc(feed_id)}/_start", params)

    def put_trained_model(self, model_id: str, body: dict, **params):
        return self._c._req(
            "PUT", f"/_ml/trained_models/{_esc(model_id)}", params,
            body)

    def infer_trained_model(self, model_id: str, body: dict, **params):
        return self._c._req(
            "POST", f"/_ml/trained_models/{_esc(model_id)}/_infer",
            params, body)

    def put_data_frame_analytics(self, aid: str, body: dict, **params):
        return self._c._req(
            "PUT", f"/_ml/data_frame/analytics/{_esc(aid)}", params,
            body)

    def start_data_frame_analytics(self, aid: str, **params):
        return self._c._req(
            "POST", f"/_ml/data_frame/analytics/{_esc(aid)}/_start",
            params)


class SlmClient(_Namespace):
    def put_lifecycle(self, policy_id: str, body: dict, **params):
        return self._c._req("PUT", f"/_slm/policy/{_esc(policy_id)}",
                            params, body)

    def get_lifecycle(self, policy_id: Optional[str] = None, **params):
        path = f"/_slm/policy/{_esc(policy_id)}" if policy_id \
            else "/_slm/policy"
        return self._c._req("GET", path, params)

    def execute_lifecycle(self, policy_id: str, **params):
        return self._c._req(
            "POST", f"/_slm/policy/{_esc(policy_id)}/_execute", params)

    def execute_retention(self, **params):
        return self._c._req("POST", "/_slm/_execute_retention", params)

    def get_stats(self, **params):
        return self._c._req("GET", "/_slm/stats", params)


class LicenseClient(_Namespace):
    def get(self, **params):
        return self._c._req("GET", "/_license", params)

    def post_start_trial(self, **params):
        return self._c._req("POST", "/_license/start_trial", params)

    def post_start_basic(self, **params):
        return self._c._req("POST", "/_license/start_basic", params)


class AutoscalingClient(_Namespace):
    def put_autoscaling_policy(self, name: str, body: dict, **params):
        return self._c._req("PUT",
                            f"/_autoscaling/policy/{_esc(name)}",
                            params, body)

    def get_autoscaling_capacity(self, **params):
        return self._c._req("GET", "/_autoscaling/capacity", params)

    def delete_autoscaling_policy(self, name: str, **params):
        return self._c._req("DELETE",
                            f"/_autoscaling/policy/{_esc(name)}",
                            params)


class EsTpuClient:
    """The entry point: ``EsTpuClient(["localhost:9200"])``."""

    def __init__(self, hosts: List[str], timeout: float = 30.0,
                 max_retries: int = 3, api_key: Optional[str] = None,
                 sniff_on_start: bool = False):
        headers = {}
        if api_key:
            headers["Authorization"] = f"ApiKey {api_key}"
        self.transport = ClientTransport(hosts, timeout=timeout,
                                         max_retries=max_retries,
                                         headers=headers)
        if sniff_on_start:
            self.transport.sniff()
        self.indices = IndicesClient(self)
        self.cluster = ClusterClient(self)
        self.cat = CatClient(self)
        self.nodes = NodesClient(self)
        self.snapshot = SnapshotClient(self)
        self.sql = SqlClient(self)
        self.eql = EqlClient(self)
        self.tasks = TasksClient(self)
        self.security = SecurityClient(self)
        self.ml = MlClient(self)
        self.slm = SlmClient(self)
        self.license = LicenseClient(self)
        self.autoscaling = AutoscalingClient(self)

    def _req(self, method: str, path: str,
             params: Optional[dict] = None, body: Any = None) -> Any:
        _status, parsed = self.transport.perform_request(
            method, path, params, body)
        return parsed

    # -- document + search core ----------------------------------------
    def info(self, **params):
        return self._req("GET", "/", params)

    def ping(self) -> bool:
        from .transport import TransportError
        try:
            self._req("GET", "/")
            return True
        except TransportError:
            return False

    def index(self, index: str, body: dict, id: Optional[str] = None,
              **params):
        if id is None:
            return self._req("POST", f"/{_esc(index)}/_doc", params,
                             body)
        return self._req("PUT", f"/{_esc(index)}/_doc/{_esc(id)}",
                         params, body)

    def create(self, index: str, id: str, body: dict, **params):
        return self._req("PUT", f"/{_esc(index)}/_create/{_esc(id)}",
                         params, body)

    def get(self, index: str, id: str, **params):
        return self._req("GET", f"/{_esc(index)}/_doc/{_esc(id)}",
                         params)

    def get_source(self, index: str, id: str, **params):
        return self._req("GET", f"/{_esc(index)}/_source/{_esc(id)}",
                         params)

    def exists(self, index: str, id: str, **params) -> bool:
        from .transport import TransportError
        try:
            self._req("HEAD", f"/{_esc(index)}/_doc/{_esc(id)}", params)
            return True
        except TransportError as e:
            if e.status_code == 404:
                return False
            raise

    def delete(self, index: str, id: str, **params):
        return self._req("DELETE", f"/{_esc(index)}/_doc/{_esc(id)}",
                         params)

    def update(self, index: str, id: str, body: dict, **params):
        return self._req("POST", f"/{_esc(index)}/_update/{_esc(id)}",
                         params, body)

    def mget(self, body: dict, index: Optional[str] = None, **params):
        path = f"/{_esc(index)}/_mget" if index else "/_mget"
        return self._req("POST", path, params, body)

    def bulk(self, body, index: Optional[str] = None, **params):
        """``body`` is NDJSON text or a list of action/source dicts."""
        if isinstance(body, list):
            import json as _json
            body = "".join(_json.dumps(x) + "\n" for x in body)
        path = f"/{_esc(index)}/_bulk" if index else "/_bulk"
        return self._req("POST", path, params, body)

    def search(self, index: Optional[str] = None,
               body: Optional[dict] = None, **params):
        path = f"/{_esc(index)}/_search" if index else "/_search"
        return self._req("POST", path, params, body or {})

    def msearch(self, body, index: Optional[str] = None, **params):
        if isinstance(body, list):
            import json as _json
            body = "".join(_json.dumps(x) + "\n" for x in body)
        path = f"/{_esc(index)}/_msearch" if index else "/_msearch"
        return self._req("POST", path, params, body)

    def count(self, index: Optional[str] = None,
              body: Optional[dict] = None, **params):
        path = f"/{_esc(index)}/_count" if index else "/_count"
        return self._req("POST", path, params, body)

    def scroll(self, scroll_id: str, scroll: str = "1m", **params):
        return self._req("POST", "/_search/scroll", params,
                         {"scroll_id": scroll_id, "scroll": scroll})

    def clear_scroll(self, scroll_id: str, **params):
        return self._req("DELETE", "/_search/scroll", params,
                         {"scroll_id": [scroll_id]})

    def delete_by_query(self, index: str, body: dict, **params):
        return self._req("POST", f"/{_esc(index)}/_delete_by_query",
                         params, body)

    def update_by_query(self, index: str,
                        body: Optional[dict] = None, **params):
        return self._req("POST", f"/{_esc(index)}/_update_by_query",
                         params, body)

    def reindex(self, body: dict, **params):
        return self._req("POST", "/_reindex", params, body)

    def explain(self, index: str, id: str, body: dict, **params):
        return self._req("POST", f"/{_esc(index)}/_explain/{_esc(id)}",
                         params, body)

    def field_caps(self, index: Optional[str] = None,
                   fields: str = "*", **params):
        params = dict(params, fields=fields)
        path = f"/{_esc(index)}/_field_caps" if index else "/_field_caps"
        return self._req("GET", path, params)
