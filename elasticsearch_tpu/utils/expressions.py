"""Restricted arithmetic expression evaluation.

The reference embeds full scripting languages (Painless —
``modules/lang-painless``, 41k LoC compiling to JVM bytecode; and
``lang-expression`` for numeric-only scripts). The TPU-native equivalent
keeps scripts *compilable*: a small arithmetic grammar parsed with Python's
``ast`` in eval mode and walked against a whitelist — no attribute access,
no calls except a math whitelist, no subscripts beyond variables — so the
same expression tree can later be traced into an XLA program for on-device
score scripts.
"""

from __future__ import annotations

import ast
import math
from typing import Any, Dict

from ..common.errors import ElasticsearchError


class ScriptException(ElasticsearchError):
    status = 400
    error_type = "script_exception"


_ALLOWED_FUNCS = {
    "abs": abs, "min": min, "max": max, "round": round,
    "floor": math.floor, "ceil": math.ceil, "sqrt": math.sqrt,
    "log": math.log, "log10": math.log10, "exp": math.exp,
    "pow": math.pow, "sin": math.sin, "cos": math.cos, "tan": math.tan,
}

_ALLOWED_NODES = (
    ast.Expression, ast.BinOp, ast.UnaryOp, ast.Constant, ast.Name,
    ast.Call, ast.Load, ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Mod,
    ast.Pow, ast.FloorDiv, ast.USub, ast.UAdd, ast.Compare, ast.Lt,
    ast.LtE, ast.Gt, ast.GtE, ast.Eq, ast.NotEq, ast.IfExp, ast.BoolOp,
    ast.And, ast.Or, ast.Not,
)


def compile_expression(source: str):
    """Parse + validate; returns the ast, raising ScriptException on any
    disallowed construct."""
    # Painless-style param refs: params.x -> variable x
    cleaned = source.replace("params.", "")
    try:
        tree = ast.parse(cleaned, mode="eval")
    except SyntaxError as e:
        raise ScriptException(f"compile error in script [{source}]: {e}")
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ScriptException(
                f"disallowed construct [{type(node).__name__}] in script "
                f"[{source}]")
        if isinstance(node, ast.Call):
            if not isinstance(node.func, ast.Name) or \
                    node.func.id not in _ALLOWED_FUNCS:
                raise ScriptException(
                    f"disallowed function call in script [{source}]")
    return tree


def evaluate_expression_vec(source: str, params: Dict[str, Any]):
    """Evaluate the same restricted grammar over *arrays* (jnp or numpy):
    operators broadcast elementwise, ``a if c else b`` lowers to ``where``,
    comparisons return boolean arrays. This is how score scripts run on
    device — the whole expression traces into one XLA program (the
    reference compiles Painless to bytecode per doc; here one fused kernel
    for the whole segment)."""
    import jax.numpy as jnp
    tree = compile_expression(source)

    vec_funcs = {
        "abs": jnp.abs, "min": jnp.minimum, "max": jnp.maximum,
        "round": jnp.round, "floor": jnp.floor, "ceil": jnp.ceil,
        "sqrt": jnp.sqrt, "log": jnp.log, "log10": jnp.log10,
        "exp": jnp.exp, "pow": jnp.power, "sin": jnp.sin, "cos": jnp.cos,
        "tan": jnp.tan,
    }

    def ev(node):
        if isinstance(node, ast.Expression):
            return ev(node.body)
        if isinstance(node, ast.Constant):
            if not isinstance(node.value, (int, float, bool)):
                raise ScriptException(f"non-numeric constant [{node.value}]")
            return node.value
        if isinstance(node, ast.Name):
            if node.id in params:
                return params[node.id]
            raise ScriptException(f"unknown variable [{node.id}]")
        if isinstance(node, ast.BinOp):
            a, b = ev(node.left), ev(node.right)
            op = type(node.op)
            if op is ast.Add:
                return a + b
            if op is ast.Sub:
                return a - b
            if op is ast.Mult:
                return a * b
            if op is ast.Div:
                return a / b
            if op is ast.Mod:
                return a % b
            if op is ast.Pow:
                return a ** b
            if op is ast.FloorDiv:
                return a // b
        if isinstance(node, ast.UnaryOp):
            v = ev(node.operand)
            if isinstance(node.op, ast.USub):
                return -v
            if isinstance(node.op, ast.UAdd):
                return +v
            if isinstance(node.op, ast.Not):
                return jnp.logical_not(v)
        if isinstance(node, ast.Compare):
            if len(node.ops) != 1:
                raise ScriptException("chained comparisons not supported "
                                      "in vector scripts")
            left, right = ev(node.left), ev(node.comparators[0])
            op = type(node.ops[0])
            return {ast.Lt: left < right, ast.LtE: left <= right,
                    ast.Gt: left > right, ast.GtE: left >= right,
                    ast.Eq: left == right, ast.NotEq: left != right}[op]
        if isinstance(node, ast.BoolOp):
            vals = [ev(v) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                out = (jnp.logical_and(out, v)
                       if isinstance(node.op, ast.And)
                       else jnp.logical_or(out, v))
            return out
        if isinstance(node, ast.IfExp):
            return jnp.where(ev(node.test), ev(node.body), ev(node.orelse))
        if isinstance(node, ast.Call):
            fn = vec_funcs[node.func.id]
            return fn(*[ev(a) for a in node.args])
        raise ScriptException(
            f"unsupported node [{type(node).__name__}]")  # pragma: no cover

    return ev(tree)


def evaluate_expression(source: str, params: Dict[str, float],
                        allow_strings: bool = False) -> float:
    tree = compile_expression(source)

    def ev(node):
        if isinstance(node, ast.Expression):
            return ev(node.body)
        if isinstance(node, ast.Constant):
            ok_types = (int, float, bool, str) if allow_strings \
                else (int, float, bool)
            if not isinstance(node.value, ok_types):
                raise ScriptException(f"non-numeric constant [{node.value}]")
            return node.value
        if isinstance(node, ast.Name):
            if node.id in params:
                return params[node.id]
            raise ScriptException(f"unknown variable [{node.id}]")
        if isinstance(node, ast.BinOp):
            a, b = ev(node.left), ev(node.right)
            op = type(node.op)
            try:
                if op is ast.Add:
                    return a + b
                if op is ast.Sub:
                    return a - b
                if op is ast.Mult:
                    return a * b
                if op is ast.Div:
                    return a / b
                if op is ast.Mod:
                    return a % b
                if op is ast.Pow:
                    return a ** b
                if op is ast.FloorDiv:
                    return a // b
            except ZeroDivisionError:
                raise ScriptException("division by zero in script")
        if isinstance(node, ast.UnaryOp):
            v = ev(node.operand)
            if isinstance(node.op, ast.USub):
                return -v
            if isinstance(node.op, ast.UAdd):
                return +v
            if isinstance(node.op, ast.Not):
                return not v
        if isinstance(node, ast.Compare):
            left = ev(node.left)
            for op, comp in zip(node.ops, node.comparators):
                right = ev(comp)
                ok = {ast.Lt: left < right, ast.LtE: left <= right,
                      ast.Gt: left > right, ast.GtE: left >= right,
                      ast.Eq: left == right, ast.NotEq: left != right}[type(op)]
                if not ok:
                    return False
                left = right
            return True
        if isinstance(node, ast.BoolOp):
            vals = [ev(v) for v in node.values]
            return all(vals) if isinstance(node.op, ast.And) else any(vals)
        if isinstance(node, ast.IfExp):
            return ev(node.body) if ev(node.test) else ev(node.orelse)
        if isinstance(node, ast.Call):
            fn = _ALLOWED_FUNCS[node.func.id]
            return fn(*[ev(a) for a in node.args])
        raise ScriptException(
            f"unsupported node [{type(node).__name__}]")  # pragma: no cover

    return ev(tree)


# ---------------------------------------------------------------------------
# per-doc scripts: doc['field'].value access (script_fields, script sort,
# scripted_metric map scripts — reference: Painless doc-values API)
# ---------------------------------------------------------------------------

import re as _re

_DOC_RE = _re.compile(r"doc\[['\"]([^'\"]+)['\"]\]\.(value|size\(\))")


def compile_doc_expression(source: str):
    """Rewrite ``doc['f'].value`` / ``doc['f'].size()`` into synthetic
    variables; returns (cleaned_source, ordered field list). The cleaned
    source must pass :func:`compile_expression`."""
    fields: list = []

    def sub(m):
        f, attr = m.group(1), m.group(2)
        if f not in fields:
            fields.append(f)
        i = fields.index(f)
        return f"__doc{i}" if attr == "value" else f"__size{i}"

    cleaned = _DOC_RE.sub(sub, source)
    compile_expression(cleaned)
    return cleaned, fields


def evaluate_doc_expression(cleaned: str, fields, params: Dict[str, Any],
                            field_values: Dict[str, Any]):
    """Evaluate a compiled doc expression for ONE document.

    ``field_values``: field -> first value (None when absent; strings
    allowed — equality/comparison work, arithmetic on strings raises a
    ScriptException like Painless's class-cast errors)."""
    env = dict(params)
    for i, f in enumerate(fields):
        v = field_values.get(f)
        env[f"__doc{i}"] = 0 if v is None else v
        env[f"__size{i}"] = 0 if v is None else 1
    try:
        return evaluate_expression(cleaned, env, allow_strings=True)
    except TypeError as e:
        raise ScriptException(f"runtime error in script: {e}")
