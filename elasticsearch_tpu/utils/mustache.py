"""Mustache-lite renderer for search templates.

The reference embeds full Mustache via ``modules/lang-mustache``
(``MustacheScriptEngine.java:53``) for ``_search/template`` /
``_render/template``. Search templates overwhelmingly use a small core,
implemented here without a dependency:

- ``{{var}}`` / ``{{a.b}}`` — variable substitution (dotted paths);
  strings insert raw (the template supplies its own quotes), other JSON
  values insert as JSON.
- ``{{#toJson}}var{{/toJson}}`` — JSON-encode a parameter.
- ``{{#join}}var{{/join}}`` — comma-join a list parameter.
- ``{{#section}}...{{/section}}`` — truthy gate; lists iterate with
  ``{{.}}`` bound to the item and dotted lookups falling through to the
  item when it is an object.
- ``{{^section}}...{{/section}}`` — inverted (renders when falsy/absent).
"""

from __future__ import annotations

import json
import re
from typing import Any

_TAG = re.compile(r"\{\{\s*([#^/]?)\s*([^}]*?)\s*\}\}")


def _lookup(params, path: str):
    if path == ".":
        # inside a list section the current item travels under the "."
        # key of the iteration scope; at top level "." is the whole map
        if isinstance(params, dict) and "." in params:
            return params["."]
        return params
    cur = params
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None
    return cur


def _stringify(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return v
    if isinstance(v, (int, float)):
        return json.dumps(v)
    return json.dumps(v)


def render_mustache(template: str, params: dict) -> str:
    out, _ = _render(template, 0, params, None)
    return out


def _render(src: str, pos: int, params, stop_tag):
    out = []
    while pos < len(src):
        m = _TAG.search(src, pos)
        if m is None:
            out.append(src[pos:])
            return "".join(out), len(src)
        out.append(src[pos:m.start()])
        sigil, name = m.group(1), m.group(2)
        pos = m.end()
        if sigil == "/":
            if stop_tag is not None and name == stop_tag:
                return "".join(out), pos
            continue                      # stray close: drop
        if sigil in ("#", "^"):
            body_start = pos
            # find the matching close (nesting-aware)
            depth = 1
            scan = pos
            close_at = len(src)
            pos = len(src)
            while True:
                m2 = _TAG.search(src, scan)
                if m2 is None:
                    break
                if m2.group(1) in ("#", "^"):
                    depth += 1
                elif m2.group(1) == "/":
                    depth -= 1
                    if depth == 0:
                        close_at = m2.start()
                        pos = m2.end()
                        break
                scan = m2.end()
            body = src[body_start:close_at]
            if sigil == "#" and name == "toJson":
                v = _lookup(params, body.strip())
                out.append(json.dumps(v))
                continue
            if sigil == "#" and name == "join":
                v = _lookup(params, body.strip())
                out.append(",".join(_stringify(x)
                                    for x in (v or [])))
                continue
            v = _lookup(params, name)
            truthy = bool(v) and v != []
            if sigil == "^":
                if not truthy:
                    rendered, _ = _render(body, 0, params, None)
                    out.append(rendered)
                continue
            if not truthy:
                continue
            if isinstance(v, list):
                for item in v:
                    # "." always rebinds to the CURRENT item — without
                    # this, a nested section's items would see a stale
                    # "." inherited from an outer iteration scope
                    if isinstance(item, dict):
                        scope = {**params, **item, ".": item}
                    else:
                        scope = {**params, ".": item}
                    rendered, _ = _render(body, 0, scope, None)
                    out.append(rendered)
            else:
                # a truthy section value becomes the current context:
                # dicts merge their keys in, scalars bind only "."
                scope = {**params, **v, ".": v} if isinstance(v, dict) \
                    else {**params, ".": v}
                rendered, _ = _render(body, 0, scope, None)
                out.append(rendered)
            continue
        # plain variable
        out.append(_stringify(_lookup(params, name)))
    return "".join(out), pos
