"""Synthetic Zipf CSR corpora for benchmarks and dry runs (stand-in for
real datasets in a zero-egress image; shapes mirror what SegmentBuilder
emits — see index/segment.py)."""

from __future__ import annotations

import numpy as np


def synthetic_csr_corpus(rng: np.random.RandomState, n_docs: int, vocab: int,
                         avg_dl: int, zipf_s: float = 1.2) -> dict:
    """Zipf-distributed postings for one shard: dict with ``docs`` i32[P]
    (CSR doc ids, doc-ascending per term run), ``tf`` f32[P], ``offsets``
    i64[V+1], ``df`` i32[V], ``doc_len`` f32[N]."""
    lens = np.maximum(1, rng.poisson(avg_dl, n_docs))
    ranks = rng.zipf(zipf_s, size=int(lens.sum()))
    terms = np.minimum(ranks - 1, vocab - 1).astype(np.int64)
    doc_of = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
    order = np.lexsort((doc_of, terms))
    terms, doc_of = terms[order], doc_of[order]
    key = terms * n_docs + doc_of
    uniq, counts = np.unique(key, return_counts=True)
    p_terms = (uniq // n_docs).astype(np.int64)
    p_docs = (uniq % n_docs).astype(np.int32)
    p_tf = counts.astype(np.float32)
    offsets = np.zeros(vocab + 1, np.int64)
    np.add.at(offsets, p_terms + 1, 1)
    offsets = np.cumsum(offsets)
    df = (offsets[1:] - offsets[:-1]).astype(np.int32)
    return dict(docs=p_docs, tf=p_tf, offsets=offsets, df=df,
                doc_len=lens.astype(np.float32))


def split_csr_shards(corpus: dict, n_shards: int) -> list:
    """Split one CSR corpus into ``n_shards`` contiguous doc-range shards
    (vectorized — no per-term Python loop; the bench's stand-in for the
    doc→shard routing an indexing pipeline would do with murmur3)."""
    n_docs = corpus["doc_len"].shape[0]
    vocab = corpus["df"].shape[0]
    per = -(-n_docs // n_shards)
    docs, tf, offsets = corpus["docs"], corpus["tf"], corpus["offsets"]
    term_of = np.repeat(np.arange(vocab, dtype=np.int32),
                        np.diff(offsets).astype(np.int64))
    shard_of = docs // per
    out = []
    for si in range(n_shards):
        keep = shard_of == si
        sterm = term_of[keep]
        ndf = np.bincount(sterm, minlength=vocab).astype(np.int32)
        noff = np.zeros(vocab + 1, np.int64)
        np.cumsum(ndf, out=noff[1:])
        out.append(dict(
            docs=(docs[keep] - si * per).astype(np.int32),
            tf=tf[keep], offsets=noff, df=ndf,
            doc_len=corpus["doc_len"][si * per: (si + 1) * per]))
    return out


def synthetic_csr_corpus_fast(rng: np.random.RandomState, n_docs: int,
                              vocab: int, avg_dl: int,
                              zipf_s: float = 1.2) -> dict:
    """O(P) sort-free Zipf CSR corpus for large benchmarks.

    ``synthetic_csr_corpus`` materializes every token and lexsorts (term,
    doc) — O(P log P) single-threaded, minutes at 2^23 docs. Here the CSR is
    constructed directly in term-major order: per-term document frequencies
    follow the Zipf pmf analytically, and each term's doc-ascending run is a
    sorted uniform sample drawn with the exponential-gap trick (normalized
    per-run cumulative sums of exponentials are order statistics of
    uniforms). Adjacent duplicate docs within a run are dropped and ``df``
    recomputed, so runs stay strictly doc-ascending like SegmentBuilder's.
    """
    ranks = np.arange(1, vocab + 1, dtype=np.float64)
    pmf = ranks ** (-zipf_s)
    pmf /= pmf.sum()
    df = np.minimum(n_docs, np.maximum(
        1, np.round(pmf * n_docs * avg_dl))).astype(np.int64)
    p_total = int(df.sum())

    # sorted uniform doc ids per run via normalized exponential-gap cumsums.
    # Memory discipline: everything length-(P+V) is computed IN PLACE on one
    # float64 buffer (peak ≈ 2 such arrays + the int64 docs, not 6 — at the
    # 268M-posting bench config that is the difference between ~7 GB and an
    # OOM-killed bench host)
    gaps = rng.exponential(1.0, p_total + vocab)
    run_ends = np.cumsum(df + 1)
    run_starts = run_ends - (df + 1)
    first_gap = gaps[run_starts].copy()          # small: [V]
    g = np.cumsum(gaps, out=gaps)                # g aliases gaps
    seg_base = g[run_starts] - first_gap         # small: [V]
    g -= np.repeat(seg_base, df + 1)             # per-run cumulative sums
    seg_total = g[run_ends - 1].copy()           # small: [V]
    g /= np.repeat(seg_total, df + 1)            # sorted uniforms per run
    # drop each run's last slot (u == 1, the normalizer)
    keep = np.ones(p_total + vocab, bool)
    keep[run_ends - 1] = False
    docs = np.minimum((g[keep] * n_docs).astype(np.int64), n_docs - 1)
    del gaps, g, keep

    # dedup *within runs*: doc-ascending, so dup iff same as predecessor
    # and not at a run start
    starts0 = np.cumsum(df) - df
    is_start = np.zeros(p_total, bool)
    is_start[starts0] = True
    dup = np.zeros(p_total, bool)
    dup[1:] = docs[1:] == docs[:-1]
    dup &= ~is_start
    docs = docs[~dup]
    term_of = np.repeat(np.arange(vocab, dtype=np.int32), df)[~dup]
    new_df = np.bincount(term_of, minlength=vocab).astype(np.int32)
    offsets = np.zeros(vocab + 1, np.int64)
    np.cumsum(new_df, out=offsets[1:])

    tf = (1.0 + rng.poisson(0.35, docs.shape[0])).astype(np.float32)
    doc_len = np.maximum(1, rng.poisson(avg_dl, n_docs)).astype(np.float32)
    return dict(docs=docs.astype(np.int32), tf=tf, offsets=offsets,
                df=new_df, doc_len=doc_len)
