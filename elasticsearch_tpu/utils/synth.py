"""Synthetic Zipf CSR corpora for benchmarks and dry runs (stand-in for
real datasets in a zero-egress image; shapes mirror what SegmentBuilder
emits — see index/segment.py)."""

from __future__ import annotations

import numpy as np


def synthetic_csr_corpus(rng: np.random.RandomState, n_docs: int, vocab: int,
                         avg_dl: int, zipf_s: float = 1.2) -> dict:
    """Zipf-distributed postings for one shard: dict with ``docs`` i32[P]
    (CSR doc ids, doc-ascending per term run), ``tf`` f32[P], ``offsets``
    i64[V+1], ``df`` i32[V], ``doc_len`` f32[N]."""
    lens = np.maximum(1, rng.poisson(avg_dl, n_docs))
    ranks = rng.zipf(zipf_s, size=int(lens.sum()))
    terms = np.minimum(ranks - 1, vocab - 1).astype(np.int64)
    doc_of = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
    order = np.lexsort((doc_of, terms))
    terms, doc_of = terms[order], doc_of[order]
    key = terms * n_docs + doc_of
    uniq, counts = np.unique(key, return_counts=True)
    p_terms = (uniq // n_docs).astype(np.int64)
    p_docs = (uniq % n_docs).astype(np.int32)
    p_tf = counts.astype(np.float32)
    offsets = np.zeros(vocab + 1, np.int64)
    np.add.at(offsets, p_terms + 1, 1)
    offsets = np.cumsum(offsets)
    df = (offsets[1:] - offsets[:-1]).astype(np.int32)
    return dict(docs=p_docs, tf=p_tf, offsets=offsets, df=df,
                doc_len=lens.astype(np.float32))
