"""Hot-threads sampling: periodic stack snapshots aggregated per thread.

Reference: ``monitor/jvm/HotThreads.java:41`` — N snapshots at a fixed
interval, threads ranked by CPU time between first and last snapshot,
common stack suffixes grouped ("M/N snapshots sharing following K
elements"). The JVM's per-thread CPU counters have no exact CPython
analog, so busyness here is the fraction of snapshots in which a thread
was runnable outside known-idle frames (waiter/selector/sleep) — the same
ranking signal, sampled rather than counted. The output text follows the
reference's format so ``_nodes/hot_threads`` consumers parse unchanged.

The stack walk and the idle/busy classifier are shared with the
continuous profiler (``common/contprof.py``) — one sampling core, so
the on-demand snapshot and the always-on flamegraph can never disagree
about what "parked" means.
"""
from __future__ import annotations

import threading
import time
import traceback
from typing import Dict, List, Tuple

from ..common.contprof import IDLE_HINTS, classify_idle, sample_stacks

#: frames that mean "parked, not burning cpu" (re-exported from the
#: shared classifier for backward compatibility)
_IDLE_HINTS = IDLE_HINTS


def _is_idle(stack: List[traceback.FrameSummary]) -> bool:
    return classify_idle(stack)


def hot_threads(threads: int = 3, interval_ms: float = 500.0,
                snapshots: int = 10, ignore_idle: bool = True,
                node_name: str = "node", node_id: str = "") -> str:
    """Sample and render the reference's text format."""
    names = {t.ident: t.name for t in threading.enumerate()}
    #: tid -> list of sampled stacks (only busy samples kept)
    samples: Dict[int, List[Tuple[str, ...]]] = {}
    seen: Dict[int, int] = {}
    step = max(interval_ms / 1e3 / max(snapshots, 1), 0.001)
    for _ in range(snapshots):
        for tid, stack in sample_stacks().items():
            seen[tid] = seen.get(tid, 0) + 1
            if ignore_idle and _is_idle(stack):
                continue
            sig = tuple(f"{fs.filename.rsplit('/', 1)[-1]}:{fs.lineno} "
                        f"{fs.name}" for fs in stack[-10:])
            samples.setdefault(tid, []).append(sig)
        time.sleep(step)
    rows = []
    for tid, sigs in samples.items():
        busy_frac = len(sigs) / max(seen.get(tid, snapshots), 1)
        # most common stack for the "sharing following elements" block
        counts: Dict[Tuple[str, ...], int] = {}
        for s in sigs:
            counts[s] = counts.get(s, 0) + 1
        common, n_common = max(counts.items(), key=lambda kv: kv[1])
        rows.append((busy_frac, tid, len(sigs), n_common, common))
    rows.sort(key=lambda r: -r[0])
    ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime())
    out = [f"::: {{{node_name}}}{{{node_id}}}",
           f"   Hot threads at {ts}Z, interval={interval_ms:.0f}ms, "
           f"busiestThreads={threads}, ignoreIdleThreads="
           f"{str(ignore_idle).lower()}:"]
    for busy_frac, tid, n_busy, n_common, common in rows[:threads]:
        ms = busy_frac * interval_ms
        name = names.get(tid, f"thread-{tid}")
        out.append("")
        out.append(f"   {busy_frac * 100:.1f}% ({ms:.1f}ms out of "
                   f"{interval_ms:.0f}ms) cpu usage by thread "
                   f"'{name}'")
        out.append(f"     {n_common}/{n_busy} snapshots sharing "
                   f"following {len(common)} elements")
        for line in common:
            out.append(f"       {line}")
    return "\n".join(out) + "\n"
