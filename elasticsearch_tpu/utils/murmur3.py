"""Murmur3 x86 32-bit hash, used for doc routing.

The reference routes ``doc_id → shard`` with Murmur3 over the routing key
(``cluster/routing/OperationRouting.java:242-256``, backed by
``Murmur3HashFunction``). Implemented from the public MurmurHash3 spec
(Austin Appleby, public domain). ``shard_for`` hashes the routing key's
UTF-16LE code units with seed 0 and takes the signed floorMod — BIT-EXACT
with the reference, because shard-coupled features (scroll slicing,
shard-partition terms) assert specific doc→shard placements. Changing the
hash invalidates on-disk shard assignments of previously written indexes.
"""

from __future__ import annotations

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_MASK = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK


_NATIVE = None


def _native_handle():
    """The C++ implementation when built (bit-exact, parity-tested in
    tests/test_native.py — routing must never move when it appears)."""
    global _NATIVE
    if _NATIVE is None:
        try:
            from ..native import _LIB_HANDLE
            _NATIVE = _LIB_HANDLE if _LIB_HANDLE is not None else False
        except Exception:   # noqa: BLE001
            _NATIVE = False
    return _NATIVE


def murmur3_32(data: bytes, seed: int = 0) -> int:
    lib = _native_handle()
    if lib:
        return int(lib.murmur3_32(data, len(data), seed & 0xFFFFFFFF))
    return _murmur3_32_py(data, seed)


def _murmur3_32_py(data: bytes, seed: int = 0) -> int:
    h = seed & _MASK
    n = len(data)
    rounded = n - (n % 4)
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _MASK
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h


def shard_for(routing: str, num_shards: int, routing_partition_size: int = 1,
              partition_offset: int = 0) -> int:
    """doc → shard, BIT-EXACT with the reference
    (``OperationRouting.generateShardId`` + ``Murmur3HashFunction``: the
    hash runs over the routing key's UTF-16LE code units and the shard is
    the signed floorMod — shard-coupled behaviors like scroll slicing
    depend on landing on the same shards)."""
    h = murmur3_32(routing.encode("utf-16-le"))
    if routing_partition_size > 1:
        h = (h + partition_offset) % (1 << 32)
    if h >= 1 << 31:
        h -= 1 << 32            # java int; python % IS floorMod
    return h % num_shards
