"""Static-shape bucketing helpers.

XLA traces/compiles once per shape; ragged search-time shapes (query term
count, postings lengths, segment doc counts) are rounded up to power-of-two
buckets so the compile cache stays small and kernels are reused. This replaces
the reference's dynamically-sized Java hot loops with a bounded family of
fixed-shape XLA programs (see SURVEY.md §7 "hard parts" #1).
"""

from __future__ import annotations


def round_up_pow2(n: int, minimum: int = 8) -> int:
    """Smallest power of two >= max(n, minimum)."""
    n = max(int(n), minimum)
    return 1 << (n - 1).bit_length()


def round_up_multiple(n: int, multiple: int) -> int:
    return ((int(n) + multiple - 1) // multiple) * multiple


def bucket_length(n: int, minimum: int = 8, maximum: int | None = None) -> int:
    b = round_up_pow2(n, minimum)
    if maximum is not None:
        b = min(b, maximum)
    return b
