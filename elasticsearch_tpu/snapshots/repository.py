"""Snapshot/restore: content-addressed incremental backups to a blob store.

Re-design of the reference's snapshot stack
(``snapshots/SnapshotsService.java`` orchestrates, ``repositories/blobstore/
BlobStoreRepository.java`` owns the blob layout, ``IndexShardSnapshot*``
describe per-shard file manifests). The reference's layout is
``indices/<uuid>/<shard>/__<blob>`` with per-shard generation files; here
the same incrementality comes from **content addressing**: every shard file
is stored once under its sha256, and a snapshot is metadata (shard file
manifests + index settings/mappings) pointing at hashes. Unchanged segments
between snapshots — the common case, segments are immutable — cost zero new
bytes.

Layout under the repository root::

    blobs/<hh>/<sha256>          # deduplicated file contents
    snap-<name>.json             # snapshot metadata + shard manifests
    index.json                   # repository index: snapshot list

Restore writes a shard's files back into a fresh store directory and lets
the engine's normal recovery path open the commit point — restore *is*
recovery, the same way the reference's restore is a recovery source
(``RecoverySource.SnapshotRecoverySource``).

Concurrency model: one snapshot/restore at a time per repository,
synchronous (the reference queues these through the cluster state; the
single-node control plane here runs them inline — the multi-node path goes
through the coordinator once Phase-3 lands).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import uuid as uuid_mod
from typing import Dict, List, Optional, Tuple

from ..common.errors import (IllegalArgumentError, ResourceAlreadyExistsError,
                             SnapshotError, SnapshotMissingError)


class FsRepository:
    """Filesystem blob store with content-addressed deduplication."""

    def __init__(self, name: str, location: str, compress: bool = False):
        self.name = name
        self.location = location
        self.compress = compress
        #: repositories-metering-api counters (x-pack
        #: repositories-metering: RepositoryStatsSnapshot) — blob-level
        #: operation + byte counts per repository instance
        self.metering = {"PutObject": 0, "GetObject": 0,
                         "bytes_written": 0, "bytes_read": 0}
        os.makedirs(os.path.join(location, "blobs"), exist_ok=True)

    # -- blob primitives ----------------------------------------------------

    def _blob_path(self, digest: str) -> str:
        return os.path.join(self.location, "blobs", digest[:2], digest)

    def put_file(self, path: str) -> Dict[str, object]:
        """Store one file; returns its manifest entry. Dedup by sha256 —
        an existing blob is never rewritten (segments are immutable)."""
        h = hashlib.sha256()
        size = 0
        with open(path, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
                size += len(chunk)
        digest = h.hexdigest()
        blob = self._blob_path(digest)
        if not os.path.exists(blob):
            os.makedirs(os.path.dirname(blob), exist_ok=True)
            tmp = blob + f".tmp.{os.getpid()}"
            shutil.copyfile(path, tmp)
            with open(tmp, "rb") as f:
                os.fsync(f.fileno())
            os.replace(tmp, blob)
            # deduped blobs issue no write: count only real uploads
            self.metering["PutObject"] += 1
            self.metering["bytes_written"] += size
        return {"name": os.path.basename(path), "hash": digest,
                "size": size}

    def get_file(self, entry: dict, dest_dir: str) -> None:
        blob = self._blob_path(entry["hash"])
        if not os.path.exists(blob):
            raise SnapshotError(
                f"repository [{self.name}] is missing blob "
                f"[{entry['hash']}] for file [{entry['name']}]")
        shutil.copyfile(blob, os.path.join(dest_dir, entry["name"]))
        self.metering["GetObject"] += 1
        self.metering["bytes_read"] += int(entry.get("size", 0))

    # -- snapshot metadata --------------------------------------------------

    def _index_path(self) -> str:
        return os.path.join(self.location, "index.json")

    def _snap_path(self, snapshot: str) -> str:
        return os.path.join(self.location, f"snap-{snapshot}.json")

    def read_index(self) -> dict:
        try:
            with open(self._index_path()) as f:
                return json.load(f)
        except FileNotFoundError:
            return {"snapshots": []}

    def write_index(self, idx: dict) -> None:
        tmp = self._index_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(idx, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._index_path())

    def read_snapshot(self, snapshot: str) -> dict:
        try:
            with open(self._snap_path(snapshot)) as f:
                return json.load(f)
        except FileNotFoundError:
            raise SnapshotMissingError(
                f"[{self.name}:{snapshot}] is missing")

    def write_snapshot(self, snapshot: str, meta: dict) -> None:
        tmp = self._snap_path(snapshot) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path(snapshot))

    def delete_snapshot_meta(self, snapshot: str) -> None:
        try:
            os.remove(self._snap_path(snapshot))
        except FileNotFoundError:
            pass

    def gc_blobs(self) -> int:
        """Drop blobs referenced by no snapshot (the reference's
        cleanup-after-delete in ``BlobStoreRepository.deleteSnapshots``)."""
        referenced = set()
        for s in self.read_index()["snapshots"]:
            meta = self.read_snapshot(s["snapshot"])
            for idx_meta in meta["indices"].values():
                for manifest in idx_meta["shards"].values():
                    for entry in manifest:
                        referenced.add(entry["hash"])
        removed = 0
        blob_root = os.path.join(self.location, "blobs")
        for sub in os.listdir(blob_root):
            subdir = os.path.join(blob_root, sub)
            for fname in os.listdir(subdir):
                if fname not in referenced:
                    os.remove(os.path.join(subdir, fname))
                    removed += 1
        return removed


class SnapshotsService:
    """Repository registry + snapshot/restore orchestration."""

    def __init__(self, indices_service):
        self.indices = indices_service
        self.repositories: Dict[str, FsRepository] = {}
        #: base for RELATIVE repo locations (the reference's path.repo).
        #: The cluster tier points every node's service at one shared
        #: directory so owners upload shards into the same blob store.
        self.path_repo: Optional[str] = None

    # -- repositories -------------------------------------------------------

    def put_repository(self, name: str, body: dict) -> None:
        if body.get("type") != "fs":
            raise IllegalArgumentError(
                f"repository type [{body.get('type')}] unknown — only [fs] "
                f"is supported")
        settings = body.get("settings") or {}
        location = settings.get("location")
        if not location:
            raise IllegalArgumentError(
                "missing location setting for fs repository")
        if not os.path.isabs(location):
            # relative locations resolve under path.repo (shared across
            # the cluster) or the node's own repo root on a single node
            base = self.path_repo or os.path.join(
                self.indices.data_path, "repos")
            location = os.path.join(base, location)
        prev = self.repositories.get(name)
        self.repositories[name] = FsRepository(
            name, location, compress=bool(settings.get("compress", False)))
        if prev is not None:
            # metering survives repository setting updates (the
            # reference archives RepositoryStatsSnapshot across them)
            self.repositories[name].metering = prev.metering

    def get_repository(self, name: str) -> FsRepository:
        repo = self.repositories.get(name)
        if repo is None:
            raise SnapshotMissingError(f"[{name}] missing repository")
        return repo

    def delete_repository(self, name: str) -> None:
        if name not in self.repositories:
            raise SnapshotMissingError(f"[{name}] missing repository")
        del self.repositories[name]

    # -- snapshot -----------------------------------------------------------

    def create(self, repo_name: str, snapshot: str,
               indices_expr: Optional[str] = None,
               include_global_state: bool = True,
               ignore_unavailable: bool = False,
               metadata: Optional[dict] = None) -> dict:
        repo = self.get_repository(repo_name)
        idx = repo.read_index()
        if any(s["snapshot"] == snapshot for s in idx["snapshots"]):
            raise ResourceAlreadyExistsError(
                f"[{repo_name}:{snapshot}] snapshot with the same name "
                f"already exists")
        if isinstance(indices_expr, list):   # ES accepts array or CSV string
            indices_expr = ",".join(indices_expr)
        try:
            names = self.indices.resolve(indices_expr)
        except Exception:   # noqa: BLE001 — missing named index
            if not ignore_unavailable:
                raise
            names = []
        start = time.time()
        indices_meta: Dict[str, dict] = {}
        total_files = 0
        total_bytes = 0
        for name in names:
            svc = self.indices.get(name)
            shards: Dict[str, List[dict]] = {}
            for shard_id, engine in enumerate(svc.shards):
                manifest, nfiles, nbytes = self.upload_shard(
                    repo_name, name, shard_id, engine)
                total_files += nfiles
                total_bytes += nbytes
                shards[str(shard_id)] = manifest
            indices_meta[name] = dict(self.index_snapshot_meta(name),
                                      shards=shards)
        return self.create_from_manifests(
            repo_name, snapshot, indices_meta, total_files, total_bytes,
            include_global_state=include_global_state, metadata=metadata,
            start=start)

    def index_snapshot_meta(self, name: str) -> dict:
        svc = self.indices.get(name)
        return {"settings": dict(svc.settings),
                "mappings": svc.mapper.mapping_dict(),
                "aliases": dict(svc.aliases),
                "num_shards": svc.num_shards}

    def upload_shard(self, repo_name: str, index_name: str, shard_id: int,
                     engine) -> Tuple[List[dict], int, int]:
        """Upload ONE shard's committed files into the repo (the data-
        node side of the reference's ``SnapshotShardsService``): in the
        cluster tier each shard's owner runs this against the SHARED fs
        repo, and only the coordinating master writes metadata."""
        repo = self.get_repository(repo_name)
        engine.flush()                  # durable commit point to copy
        manifest: List[dict] = []
        store = engine.store_dir
        commit = json.load(open(os.path.join(store, "commit_point.json")))
        files = ["commit_point.json"]
        for fname in commit["segments"]:
            # the commit entry itself (npz, or a legacy round-1
            # .json.gz) plus its liveness sidecar if present
            files.append(fname)
            seg_base = fname
            for suffix in (".npz", ".json.gz"):
                if seg_base.endswith(suffix):
                    seg_base = seg_base[: -len(suffix)]
                    break
            sidecar = seg_base + ".live.npy"
            if os.path.exists(os.path.join(store, sidecar)):
                files.append(sidecar)
        missing = [f for f in files
                   if not os.path.exists(os.path.join(store, f))]
        if missing:
            raise SnapshotError(
                f"shard [{index_name}][{shard_id}] store is missing "
                f"committed files {missing}")
        nbytes = 0
        for fname in files:
            entry = repo.put_file(os.path.join(store, fname))
            manifest.append(entry)
            nbytes += int(entry.get("size", 0))
        return manifest, len(files), nbytes

    def create_from_manifests(self, repo_name: str, snapshot: str,
                              indices_meta: Dict[str, dict],
                              total_files: int, total_bytes: int, *,
                              include_global_state: bool = True,
                              metadata: Optional[dict] = None,
                              start: Optional[float] = None) -> dict:
        """Finalize a snapshot from per-shard manifests (master side)."""
        repo = self.get_repository(repo_name)
        idx = repo.read_index()
        if any(s["snapshot"] == snapshot for s in idx["snapshots"]):
            raise ResourceAlreadyExistsError(
                f"[{repo_name}:{snapshot}] snapshot with the same name "
                f"already exists")
        shards_total = sum(m["num_shards"] for m in indices_meta.values())
        meta = {
            "snapshot": snapshot,
            "uuid": uuid_mod.uuid4().hex[:20],
            "repository": repo_name,
            "state": "SUCCESS",
            "indices": indices_meta,
            "include_global_state": include_global_state,
            "metadata": metadata,
            "start_time_in_millis": int((start or time.time()) * 1000),
            "end_time_in_millis": int(time.time() * 1000),
            "total_files": total_files,
            "total_size_in_bytes": total_bytes,
            "shards": {"total": shards_total, "failed": 0,
                       "successful": shards_total},
            "failures": [],
            "version": "8.0.0-tpu",
        }
        repo.write_snapshot(snapshot, meta)
        idx["snapshots"].append({"snapshot": snapshot,
                                 "uuid": meta["uuid"],
                                 "state": "SUCCESS",
                                 "indices": sorted(indices_meta)})
        repo.write_index(idx)
        return meta

    def get(self, repo_name: str, expr: str) -> List[dict]:
        repo = self.get_repository(repo_name)
        listed = repo.read_index()["snapshots"]
        if expr in ("_all", "*", None, ""):
            names = [s["snapshot"] for s in listed]
        else:
            import fnmatch
            names = []
            for part in expr.split(","):
                if "*" in part:
                    names.extend(s["snapshot"] for s in listed
                                 if fnmatch.fnmatchcase(s["snapshot"], part))
                else:
                    if not any(s["snapshot"] == part for s in listed):
                        raise SnapshotMissingError(
                            f"[{repo_name}:{part}] is missing")
                    names.append(part)
        return [repo.read_snapshot(n) for n in names]

    def clone(self, repo_name: str, snapshot: str, target: str,
              indices_expr: Optional[str] = None) -> None:
        """Snapshot clone (``TransportCloneSnapshotAction``): the target
        references the SAME blobs (dedup by content hash), restricted to
        the requested indices."""
        repo = self.get_repository(repo_name)
        idx = repo.read_index()
        if not any(s["snapshot"] == snapshot for s in idx["snapshots"]):
            raise SnapshotMissingError(f"[{repo_name}:{snapshot}] is missing")
        if any(s["snapshot"] == target for s in idx["snapshots"]):
            raise ResourceAlreadyExistsError(
                f"[{repo_name}:{target}] snapshot with the same name "
                f"already exists")
        meta = dict(repo.read_snapshot(snapshot))
        if indices_expr:
            import fnmatch
            pats = indices_expr.split(",") \
                if isinstance(indices_expr, str) else list(indices_expr)
            meta["indices"] = {
                n: m for n, m in meta["indices"].items()
                if any(fnmatch.fnmatchcase(n, p) for p in pats)}
        meta["snapshot"] = target
        meta["uuid"] = uuid_mod.uuid4().hex[:20]
        shards_total = sum(m.get("num_shards", 0)
                           for m in meta["indices"].values())
        meta["shards"] = {"total": shards_total, "failed": 0,
                          "successful": shards_total}
        meta["total_files"] = sum(
            len(man) for m in meta["indices"].values()
            for man in m.get("shards", {}).values())
        meta["total_size_in_bytes"] = sum(
            int(e.get("size", 0)) for m in meta["indices"].values()
            for man in m.get("shards", {}).values() for e in man)
        repo.write_snapshot(target, meta)
        idx["snapshots"].append({"snapshot": target, "uuid": meta["uuid"],
                                 "state": meta.get("state", "SUCCESS"),
                                 "indices": sorted(meta["indices"])})
        repo.write_index(idx)

    def delete(self, repo_name: str, snapshot: str) -> None:
        repo = self.get_repository(repo_name)
        idx = repo.read_index()
        if not any(s["snapshot"] == snapshot for s in idx["snapshots"]):
            raise SnapshotMissingError(f"[{repo_name}:{snapshot}] is missing")
        idx["snapshots"] = [s for s in idx["snapshots"]
                            if s["snapshot"] != snapshot]
        repo.write_index(idx)
        repo.delete_snapshot_meta(snapshot)
        repo.gc_blobs()

    # -- restore ------------------------------------------------------------

    def restore(self, repo_name: str, snapshot: str,
                indices_expr: Optional[str] = None,
                rename_pattern: Optional[str] = None,
                rename_replacement: Optional[str] = None) -> dict:
        import re as re_mod
        repo = self.get_repository(repo_name)
        meta = repo.read_snapshot(snapshot)
        if isinstance(indices_expr, list):   # ES accepts array or CSV string
            indices_expr = ",".join(indices_expr)
        wanted = list(meta["indices"])
        if indices_expr and indices_expr not in ("_all", "*"):
            import fnmatch
            sel = []
            for part in indices_expr.split(","):
                hits = [n for n in meta["indices"]
                        if fnmatch.fnmatchcase(n, part)]
                if not hits:
                    raise SnapshotError(
                        f"[{repo_name}:{snapshot}] no index matches "
                        f"[{part}] in snapshot")
                sel.extend(h for h in hits if h not in sel)
            wanted = sel
        restored = []
        for name in wanted:
            target = name
            if rename_pattern and rename_replacement is not None:
                target = re_mod.sub(rename_pattern, rename_replacement, name)
            if self.indices.exists(target):
                existing = self.indices.indices.get(target)
                if existing is not None and existing.closed:
                    # restoring over a CLOSED index replaces it
                    # (RestoreService: only open indices conflict) —
                    # including its on-disk stores/translogs, which
                    # would otherwise replay the OLD index's ops over
                    # the restored commit
                    del self.indices.indices[target]
                    shutil.rmtree(os.path.join(
                        self.indices.data_path, target),
                        ignore_errors=True)
                else:
                    raise ResourceAlreadyExistsError(
                        f"cannot restore index [{target}] because an "
                        f"open index with same name already exists in "
                        f"the cluster")
            imeta = meta["indices"][name]
            path = os.path.join(self.indices.data_path, target)
            files_n = 0
            bytes_n = 0
            try:
                for shard_id_s, manifest in imeta["shards"].items():
                    store = os.path.join(path, shard_id_s, "store")
                    os.makedirs(store, exist_ok=True)
                    for entry in manifest:
                        repo.get_file(entry, store)
                        files_n += 1
                        bytes_n += int(entry.get("size", 0))
                # IndexService construction opens every shard engine, whose
                # recovery path reads the restored commit point — restore
                # IS recovery (RecoverySource.SnapshotRecoverySource)
                from ..node.indices_service import IndexService
                settings = {k: v for k, v in imeta["settings"].items()
                            if k != "index.uuid"}
                svc = IndexService(target, path, settings,
                                   imeta["mappings"])
                for alias, spec in imeta.get("aliases", {}).items():
                    svc.aliases[alias] = spec or {}
                svc.recovery_info = {"type": "SNAPSHOT",
                                     "files": files_n,
                                     "bytes": bytes_n}
                self.indices.indices[target] = svc
                restored.append(target)
            except Exception:
                shutil.rmtree(path, ignore_errors=True)
                raise
        return {"snapshot": {"snapshot": snapshot,
                             "indices": restored,
                             "shards": {"total": sum(
                                 meta["indices"][n]["num_shards"]
                                 for n in wanted), "failed": 0,
                                 "successful": sum(
                                     meta["indices"][n]["num_shards"]
                                     for n in wanted)}}}

    def status(self, repo_name: str, snapshot: str) -> dict:
        snaps = self.get(repo_name, snapshot)
        if not snaps:                        # wildcard matched nothing
            raise SnapshotMissingError(
                f"[{repo_name}:{snapshot}] is missing")
        meta = snaps[0]
        shards_total = sum(i["num_shards"] for i in meta["indices"].values())
        files = meta.get("total_files", 0)
        file_stats = {"file_count": files,
                      "size_in_bytes": meta.get("total_size_in_bytes", 0)}
        return {"snapshots": [{
            "snapshot": meta["snapshot"],
            "repository": repo_name,
            "uuid": meta["uuid"],
            "state": meta["state"],
            "shards_stats": {"done": shards_total, "failed": 0,
                             "total": shards_total},
            "stats": {"incremental": dict(file_stats),
                      "total": dict(file_stats),
                      "start_time_in_millis":
                          meta.get("start_time_in_millis", 0),
                      "time_in_millis": 0},
        }]}
