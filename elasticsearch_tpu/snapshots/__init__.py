from .repository import FsRepository, SnapshotsService

__all__ = ["FsRepository", "SnapshotsService"]
