"""Transforms: pivot / latest jobs that page the source through composite
aggregations into a destination index.

Reference: ``x-pack/plugin/transform/.../transforms/TransformIndexer.java``
— a checkpointed persistent task pages ``composite`` results and bulk-
indexes pivoted docs into the dest. Here a transform executes its full
batch synchronously on ``_start`` (the indexer loop collapses: page →
bulk → next ``after_key`` until drained), reusing the composite agg and
bulk machinery through the REST seam; ``docs_processed``/``pages``
surface in stats. Continuous (``sync``) transforms re-drain on each
``_start`` from their last checkpoint timestamp — the reference's
poll-loop reduced to an explicit trigger, same shape as the ILM tick.
"""
from __future__ import annotations

import hashlib
import json
import time
from typing import Any, Dict, List, Optional

from ..common.errors import (ElasticsearchError, IllegalArgumentError,
                             ResourceAlreadyExistsError,
                             ResourceNotFoundError)


class TransformService:
    PAGE = 500

    def __init__(self, search_fn, bulk_fn):
        self.search_fn = search_fn
        self.bulk_fn = bulk_fn
        self.transforms: Dict[str, dict] = {}

    # -- CRUD -----------------------------------------------------------
    def put(self, tid: str, body: dict) -> dict:
        if tid in self.transforms:
            raise ResourceAlreadyExistsError(
                f"Transform with id [{tid}] already exists")
        src = body.get("source") or {}
        if not src.get("index"):
            raise IllegalArgumentError("[source.index] is required")
        if not (body.get("dest") or {}).get("index"):
            raise IllegalArgumentError("[dest.index] is required")
        if not body.get("pivot") and not body.get("latest"):
            raise IllegalArgumentError(
                "Either [pivot] or [latest] must be specified")
        if body.get("pivot") and body.get("latest"):
            raise IllegalArgumentError(
                "[pivot] and [latest] are mutually exclusive")
        self.transforms[tid] = {
            "config": dict(body, id=tid),
            "state": "stopped",
            "checkpoint": 0,
            "stats": {"pages_processed": 0, "documents_processed": 0,
                      "documents_indexed": 0, "trigger_count": 0},
            "create_time": int(time.time() * 1000),
        }
        return {"acknowledged": True}

    def get(self, tid: Optional[str]) -> dict:
        if tid in (None, "_all", "*"):
            items = sorted(self.transforms.items())
        else:
            if tid not in self.transforms:
                raise ResourceNotFoundError(
                    f"Transform with id [{tid}] could not be found")
            items = [(tid, self.transforms[tid])]
        return {"count": len(items),
                "transforms": [t["config"] for _, t in items]}

    def stats(self, tid: Optional[str]) -> dict:
        if tid in (None, "_all", "*"):
            items = sorted(self.transforms.items())
        else:
            if tid not in self.transforms:
                raise ResourceNotFoundError(
                    f"Transform with id [{tid}] could not be found")
            items = [(tid, self.transforms[tid])]
        return {"count": len(items), "transforms": [
            {"id": k, "state": t["state"],
             "checkpointing": {"last": {
                 "checkpoint": t["checkpoint"]}},
             "stats": dict(t["stats"])} for k, t in items]}

    def delete(self, tid: str, force: bool = False) -> dict:
        t = self.transforms.get(tid)
        if t is None:
            raise ResourceNotFoundError(
                f"Transform with id [{tid}] could not be found")
        if t["state"] == "started" and not force:
            raise ElasticsearchError(
                f"Cannot delete transform [{tid}] as the task is running."
                f" Stop the transform first")
        del self.transforms[tid]
        return {"acknowledged": True}

    # -- execution ------------------------------------------------------
    def preview(self, body: dict) -> dict:
        docs = self._run_batch(body, write=False, limit=100)
        return {"preview": docs, "generated_dest_index": {
            "mappings": {"_meta": {"_transform": {
                "transform": "transform-preview"}}}}}

    def start(self, tid: str) -> dict:
        t = self.transforms.get(tid)
        if t is None:
            raise ResourceNotFoundError(
                f"Transform with id [{tid}] could not be found")
        cfg = t["config"]
        t["state"] = "indexing"
        t["stats"]["trigger_count"] += 1
        try:
            docs = self._run_batch(cfg, write=True, stats=t["stats"])
        finally:
            # batch transforms complete; continuous ones stay started
            t["state"] = ("started" if cfg.get("sync") else "stopped")
        t["checkpoint"] += 1
        return {"acknowledged": True}

    def stop(self, tid: str) -> dict:
        t = self.transforms.get(tid)
        if t is None:
            raise ResourceNotFoundError(
                f"Transform with id [{tid}] could not be found")
        t["state"] = "stopped"
        return {"acknowledged": True}

    def _run_batch(self, cfg: dict, write: bool, limit: int = 0,
                   stats: Optional[dict] = None) -> List[dict]:
        src = cfg["source"]
        dest_index = (cfg.get("dest") or {}).get("index")
        out_docs: List[dict] = []
        if cfg.get("pivot"):
            out_docs = self._run_pivot(cfg, src, limit, stats)
        else:
            out_docs = self._run_latest(cfg, src, limit, stats)
        if write and dest_index:
            lines: List[dict] = []
            for d in out_docs:
                lines.append({"index": {"_index": dest_index,
                                        "_id": d.pop("_transform_id_")}})
                lines.append(d)
            if lines:
                self.bulk_fn(dest_index, lines)
            if stats is not None:
                stats["documents_indexed"] += len(out_docs)
        else:
            for d in out_docs:
                d.pop("_transform_id_", None)
        return out_docs

    def _run_pivot(self, cfg, src, limit, stats) -> List[dict]:
        pivot = cfg["pivot"]
        group_by = pivot.get("group_by") or {}
        if not group_by:
            raise IllegalArgumentError("[pivot.group_by] is required")
        sources = []
        for name, spec in group_by.items():
            (kind, inner), = spec.items()
            if kind not in ("terms", "date_histogram", "histogram"):
                raise IllegalArgumentError(
                    f"Unsupported group_by type [{kind}]")
            sources.append({name: {kind: inner}})
        aggs_spec = pivot.get("aggregations") or pivot.get("aggs") or {}
        comp: dict = {"size": self.PAGE, "sources": sources}
        out: List[dict] = []
        after = None
        while True:
            agg_body: dict = {"composite": dict(comp)}
            if after is not None:
                agg_body["composite"]["after"] = after
            if aggs_spec:
                agg_body["aggs"] = aggs_spec
            body = {"size": 0, "aggs": {"_transform": agg_body}}
            if src.get("query"):
                body["query"] = src["query"]
            resp = self.search_fn(src["index"], body)
            node = (resp.get("aggregations") or {}).get("_transform") or {}
            buckets = node.get("buckets", [])
            if stats is not None:
                stats["pages_processed"] += 1
            for b in buckets:
                doc = dict(b["key"])
                for aname in aggs_spec:
                    av = b.get(aname) or {}
                    doc[aname] = av.get("value", av if av else None)
                key_blob = json.dumps(b["key"], sort_keys=True).encode()
                doc["_transform_id_"] = hashlib.sha1(
                    key_blob).hexdigest()[:20]
                out.append(doc)
                if stats is not None:
                    stats["documents_processed"] += b.get("doc_count", 0)
                if limit and len(out) >= limit:
                    return out
            after = node.get("after_key")
            if after is None or not buckets:
                return out

    def _run_latest(self, cfg, src, limit, stats) -> List[dict]:
        latest = cfg["latest"]
        keys = latest.get("unique_key")
        sort_field = latest.get("sort")
        if not keys or not sort_field:
            raise IllegalArgumentError(
                "[latest.unique_key] and [latest.sort] are required")
        sources = [{k: {"terms": {"field": k}}} for k in keys]
        out: List[dict] = []
        after = None
        while True:
            comp: dict = {"size": self.PAGE, "sources": sources}
            if after is not None:
                comp["after"] = after
            body = {"size": 0, "aggs": {"_transform": {
                "composite": comp,
                "aggs": {"_latest": {"top_hits": {
                    "size": 1, "sort": [{sort_field: "desc"}]}}}}}}
            if src.get("query"):
                body["query"] = src["query"]
            resp = self.search_fn(src["index"], body)
            node = (resp.get("aggregations") or {}).get("_transform") or {}
            buckets = node.get("buckets", [])
            if stats is not None:
                stats["pages_processed"] += 1
            for b in buckets:
                hits = (b.get("_latest") or {}).get(
                    "hits", {}).get("hits", [])
                if not hits:
                    continue
                doc = dict(hits[0].get("_source") or {})
                key_blob = json.dumps(b["key"], sort_keys=True).encode()
                doc["_transform_id_"] = hashlib.sha1(
                    key_blob).hexdigest()[:20]
                out.append(doc)
                if stats is not None:
                    stats["documents_processed"] += b.get("doc_count", 0)
                if limit and len(out) >= limit:
                    return out
            after = node.get("after_key")
            if after is None or not buckets:
                return out
