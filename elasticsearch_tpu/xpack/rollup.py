"""Rollup: scheduled downsampling jobs + rollup-aware search.

Reference: ``x-pack/plugin/rollup/`` — ``RollupIndexer.java`` pages a
composite aggregation over the job's groups and writes one summary doc
per bucket into the rollup index using the flattened column naming
(``<field>.date_histogram.timestamp``, ``<field>.terms.value``,
``<metric>.avg.value`` + ``.avg._count`` …); ``TransportRollupSearch
Action.java`` rewrites live aggregations onto those columns and repairs
averages from sum/count pairs. Both halves are reproduced here over the
shared search/bulk seams; jobs execute their full batch on ``_start``
(the indexer loop collapses, same stance as transforms)."""
from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional

from ..common.errors import (ElasticsearchError, IllegalArgumentError,
                             ResourceAlreadyExistsError,
                             ResourceNotFoundError)


class RollupService:
    PAGE = 500

    def __init__(self, search_fn, bulk_fn, create_index_fn=None):
        self.search_fn = search_fn
        self.bulk_fn = bulk_fn
        #: (index, mappings) -> None; pre-creates the rollup index with
        #: typed columns (keyword terms values, date timestamps) the way
        #: RollupIndexer does — dynamic mapping would text-ify them
        self.create_index_fn = create_index_fn
        self.jobs: Dict[str, dict] = {}

    # -- job CRUD -------------------------------------------------------
    def put_job(self, jid: str, body: dict) -> dict:
        if jid in self.jobs:
            raise ResourceAlreadyExistsError(
                f"Cannot create rollup job [{jid}] because job was "
                f"previously created (existing metadata)")
        for req_key in ("index_pattern", "rollup_index", "cron",
                        "page_size", "groups"):
            if req_key not in body:
                raise IllegalArgumentError(f"[{req_key}] is required")
        if "date_histogram" not in body["groups"]:
            raise IllegalArgumentError(
                "rollup requires a [groups.date_histogram]")
        self.jobs[jid] = {"config": dict(body, id=jid),
                          "status": {"job_state": "stopped"},
                          "stats": {"pages_processed": 0,
                                    "documents_processed": 0,
                                    "rollups_indexed": 0,
                                    "trigger_count": 0}}
        return {"acknowledged": True}

    def get_jobs(self, jid: Optional[str]) -> dict:
        if jid in (None, "_all"):
            items = sorted(self.jobs.items())
        else:
            items = [(jid, self.jobs[jid])] if jid in self.jobs else []
        return {"jobs": [{"config": j["config"], "status": j["status"],
                          "stats": j["stats"]} for _, j in items]}

    def delete_job(self, jid: str) -> dict:
        j = self.jobs.get(jid)
        if j is None:
            raise ResourceNotFoundError(f"the task with id [{jid}] "
                                        f"doesn't exist")
        if j["status"]["job_state"] == "started":
            raise ElasticsearchError(
                f"Could not delete job [{jid}] because indexer state is "
                f"[STARTED]. Job must be [STOPPED] before deletion.")
        del self.jobs[jid]
        return {"acknowledged": True}

    def start_job(self, jid: str) -> dict:
        j = self.jobs.get(jid)
        if j is None:
            raise ResourceNotFoundError(f"Task for Rollup Job [{jid}] "
                                        f"not found")
        j["status"]["job_state"] = "started"
        j["stats"]["trigger_count"] += 1
        try:
            self._run(j)
        finally:
            j["status"]["job_state"] = "stopped"
        return {"started": True}

    def stop_job(self, jid: str) -> dict:
        j = self.jobs.get(jid)
        if j is None:
            raise ResourceNotFoundError(f"Task for Rollup Job [{jid}] "
                                        f"not found")
        j["status"]["job_state"] = "stopped"
        return {"stopped": True}

    def caps(self, pattern: Optional[str]) -> dict:
        out: Dict[str, dict] = {}
        for jid, j in self.jobs.items():
            cfg = j["config"]
            if pattern not in (None, "_all") and \
                    cfg["index_pattern"] != pattern:
                continue
            fields: Dict[str, list] = {}
            groups = cfg["groups"]
            dh = groups["date_histogram"]
            fields.setdefault(dh["field"], []).append(
                {"agg": "date_histogram",
                 **{k: v for k, v in dh.items() if k != "field"}})
            for tf in (groups.get("terms") or {}).get("fields", []):
                fields.setdefault(tf, []).append({"agg": "terms"})
            for m in cfg.get("metrics", []):
                for op in m.get("metrics", []):
                    fields.setdefault(m["field"], []).append({"agg": op})
            out.setdefault(cfg["index_pattern"], {"rollup_jobs": []})[
                "rollup_jobs"].append({
                    "job_id": jid, "rollup_index": cfg["rollup_index"],
                    "index_pattern": cfg["index_pattern"],
                    "fields": fields})
        return out

    # -- the indexer ----------------------------------------------------
    def _run(self, j: dict) -> None:
        cfg = j["config"]
        groups = cfg["groups"]
        dh = groups["date_histogram"]
        date_field = dh["field"]
        sources: List[dict] = [{"_ts": {"date_histogram": {
            "field": date_field,
            **{k: v for k, v in dh.items()
               if k in ("fixed_interval", "calendar_interval",
                        "interval", "time_zone")}}}}]
        term_fields = (groups.get("terms") or {}).get("fields", [])
        for tf in term_fields:
            sources.append({f"_t_{tf}": {"terms": {"field": tf}}})
        hist = groups.get("histogram")
        hist_fields = (hist or {}).get("fields", [])
        for hf in hist_fields:
            sources.append({f"_h_{hf}": {"histogram": {
                "field": hf, "interval": hist["interval"]}}})
        aggs: Dict[str, dict] = {}
        for m in cfg.get("metrics", []):
            f = m["field"]
            for op in m.get("metrics", []):
                if op == "avg":
                    aggs[f"{f}_sum"] = {"sum": {"field": f}}
                    aggs[f"{f}_vc"] = {"value_count": {"field": f}}
                elif op in ("sum", "min", "max"):
                    aggs[f"{f}_{op}"] = {op: {"field": f}}
                elif op == "value_count":
                    aggs[f"{f}_vc"] = {"value_count": {"field": f}}
        if self.create_index_fn is not None:
            props: Dict[str, dict] = {
                f"{date_field}.date_histogram.timestamp":
                    {"type": "date"},
                f"{date_field}.date_histogram._count": {"type": "long"},
            }
            for tf in term_fields:
                props[f"{tf}.terms.value"] = {"type": "keyword"}
                props[f"{tf}.terms._count"] = {"type": "long"}
            for hf in hist_fields:
                props[f"{hf}.histogram.value"] = {"type": "double"}
            for m in cfg.get("metrics", []):
                for op in m.get("metrics", []):
                    if op == "avg":
                        props[f"{m['field']}.avg.value"] = \
                            {"type": "double"}
                        props[f"{m['field']}.avg._count"] = \
                            {"type": "long"}
                    else:
                        props[f"{m['field']}.{op}.value"] = \
                            {"type": "double"}
            self.create_index_fn(cfg["rollup_index"],
                                 {"properties": props})
        after = None
        page_size = min(int(cfg.get("page_size", self.PAGE)), 10_000)
        interval = (dh.get("fixed_interval") or dh.get("interval")
                    or dh.get("calendar_interval"))
        while True:
            comp: dict = {"size": page_size, "sources": sources}
            if after is not None:
                comp["after"] = after
            body: dict = {"size": 0, "aggs": {"_r": {
                "composite": comp, **({"aggs": aggs} if aggs else {})}}}
            resp = self.search_fn(cfg["index_pattern"], body)
            node = (resp.get("aggregations") or {}).get("_r") or {}
            buckets = node.get("buckets", [])
            j["stats"]["pages_processed"] += 1
            lines: List[dict] = []
            for b in buckets:
                doc: Dict[str, Any] = {
                    "_rollup.id": cfg["id"], "_rollup.version": 2,
                    f"{date_field}.date_histogram.timestamp":
                        b["key"]["_ts"],
                    f"{date_field}.date_histogram.interval": interval,
                    f"{date_field}.date_histogram._count":
                        b["doc_count"],
                }
                for tf in term_fields:
                    doc[f"{tf}.terms.value"] = b["key"].get(f"_t_{tf}")
                    doc[f"{tf}.terms._count"] = b["doc_count"]
                for hf in hist_fields:
                    doc[f"{hf}.histogram.value"] = b["key"].get(
                        f"_h_{hf}")
                    doc[f"{hf}.histogram.interval"] = hist["interval"]
                    doc[f"{hf}.histogram._count"] = b["doc_count"]
                for m in cfg.get("metrics", []):
                    f = m["field"]
                    for op in m.get("metrics", []):
                        if op == "avg":
                            doc[f"{f}.avg.value"] = \
                                (b.get(f"{f}_sum") or {}).get("value")
                            doc[f"{f}.avg._count"] = \
                                (b.get(f"{f}_vc") or {}).get("value")
                        elif op in ("sum", "min", "max"):
                            doc[f"{f}.{op}.value"] = \
                                (b.get(f"{f}_{op}") or {}).get("value")
                        elif op == "value_count":
                            doc[f"{f}.value_count.value"] = \
                                (b.get(f"{f}_vc") or {}).get("value")
                rid = hashlib.sha1(json.dumps(
                    b["key"], sort_keys=True).encode()).hexdigest()[:20]
                lines.append({"index": {"_index": cfg["rollup_index"],
                                        "_id": f"{cfg['id']}${rid}"}})
                lines.append(doc)
                j["stats"]["documents_processed"] += b["doc_count"]
                j["stats"]["rollups_indexed"] += 1
            if lines:
                self.bulk_fn(cfg["rollup_index"], lines)
            after = node.get("after_key")
            if after is None or not buckets:
                return

    # -- rollup search --------------------------------------------------
    def rollup_search(self, index: str, body: dict) -> dict:
        """Rewrite a live-shaped search onto rollup columns
        (``TransportRollupSearchAction`` RollupResponseTranslator)."""
        aggs_in = body.get("aggs") or body.get("aggregations") or {}
        if body.get("size", 0) != 0:
            raise IllegalArgumentError(
                "Rollup does not support returning search hits, please "
                "try again with [size: 0]")
        new_body: dict = {"size": 0}
        if body.get("query") is not None:
            new_body["query"] = self._rewrite_query(body["query"])
        if aggs_in:
            new_body["aggs"] = self._rewrite_aggs(aggs_in)
        resp = self.search_fn(index, new_body)
        aggs_out = resp.get("aggregations") or {}
        self._repair_avgs(aggs_out)
        out = {"took": resp.get("took", 0), "timed_out": False,
               "_shards": resp.get("_shards", {}),
               "hits": {"total": {"value": 0, "relation": "eq"},
                        "max_score": 0.0, "hits": []}}
        if aggs_out:
            out["aggregations"] = aggs_out
        return out

    #: marker suffix for staged avg-count siblings (stripped on repair)
    _AVG_COUNT = "__rollup_avg_count"

    def _rewrite_aggs(self, aggs_in: dict) -> dict:
        out: Dict[str, dict] = {}
        for name, spec in aggs_in.items():
            new_spec: Dict[str, Any] = {}
            for k, v in spec.items():
                if k in ("aggs", "aggregations"):
                    new_spec["aggs"] = self._rewrite_aggs(v)
                elif k == "date_histogram":
                    new_spec[k] = dict(
                        v, field=f"{v['field']}.date_histogram.timestamp")
                elif k == "terms":
                    new_spec[k] = dict(v,
                                       field=f"{v['field']}.terms.value")
                elif k == "histogram":
                    new_spec[k] = dict(
                        v, field=f"{v['field']}.histogram.value")
                elif k in ("sum", "min", "max"):
                    new_spec[k] = dict(v,
                                       field=f"{v['field']}.{k}.value")
                elif k == "value_count":
                    new_spec["sum"] = {
                        "field": f"{v['field']}.value_count.value"}
                elif k == "avg":
                    # stage sum(value) here + a sum(_count) sibling;
                    # _repair_avgs divides and strips the sibling
                    new_spec["sum"] = {"field": f"{v['field']}.avg.value"}
                    out[name + self._AVG_COUNT] = {"sum": {
                        "field": f"{v['field']}.avg._count"}}
                else:
                    new_spec[k] = v
            out[name] = new_spec
        return out

    def _repair_avgs(self, node: Any) -> None:
        if isinstance(node, list):
            for item in node:
                self._repair_avgs(item)
            return
        if not isinstance(node, dict):
            return
        for cname in [c for c in list(node)
                      if c.endswith(self._AVG_COUNT)]:
            base = cname[: -len(self._AVG_COUNT)]
            cnt = (node.pop(cname) or {}).get("value")
            tgt = node.get(base)
            if isinstance(tgt, dict):
                total = tgt.get("value")
                tgt["value"] = ((total / cnt)
                                if total is not None and cnt else None)
        for v in node.values():
            self._repair_avgs(v)

    def _group_fields(self):
        """(date_histogram fields, terms fields) across configured jobs —
        the caps the reference validates queried fields against."""
        date_fields, term_fields = set(), set()
        for j in self.jobs.values():
            groups = j["config"]["groups"]
            date_fields.add(groups["date_histogram"]["field"])
            term_fields.update(
                (groups.get("terms") or {}).get("fields", []))
        return date_fields, term_fields

    def _rewrite_query(self, q: dict) -> dict:
        date_fields, term_fields = self._group_fields()
        if "match_all" in q:
            return q
        if "range" in q:
            (f, spec), = q["range"].items()
            if f not in date_fields:
                raise IllegalArgumentError(
                    f"Field [{f}] in [range] query is not available in "
                    f"selected rollup indices, cannot query.")
            return {"range": {f"{f}.date_histogram.timestamp": spec}}
        if "term" in q:
            (f, spec), = q["term"].items()
            base = f[:-len(".keyword")] if f.endswith(".keyword") else f
            if base not in term_fields and f not in term_fields:
                raise IllegalArgumentError(
                    f"Field [{f}] in [term] query is not available in "
                    f"selected rollup indices, cannot query.")
            return {"term": {f"{base}.terms.value": spec}}
        raise IllegalArgumentError(
            f"Unsupported Query in rollup search: "
            f"[{next(iter(q), '?')}]")

