"""X-Pack analog features (SQL, EQL, transform, rollup, watcher, enrich,
graph, CCR) re-designed for the TPU-native stack.

Each feature translates its surface language down to the same query-DSL /
aggregation machinery the `_search` path runs (and therefore inherits the
cluster scatter-gather and the TPU scoring plane for free), instead of
maintaining a parallel execution engine the way the reference's separate
x-pack plugins do (reference: ``x-pack/plugin/*``).
"""
