"""Monitoring: self-metrics collectors exporting into local
``.monitoring-es-*`` indices + the ``/_monitoring/bulk`` intake.

Reference: ``x-pack/plugin/monitoring/`` — ``Collector`` subclasses
(cluster stats, node stats, index stats, shards) sample the running
node on an interval and the ``LocalExporter`` bulk-indexes the sampled
documents into ``.monitoring-es-7-<date>``; external agents (beats,
kibana) push through ``/_monitoring/bulk``.

Collection here rides the same internal REST seam as transform/rollup:
each collector issues the ordinary stats API call and wraps the response
in the reference's document envelope (``cluster_uuid``, ``timestamp``,
``type``), so the monitoring index is queryable with the standard DSL
the way Kibana's monitoring app expects.  The interval runs on the
injectable ``tick(now_ms)`` shared by ILM/SLM/watcher.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional


def _now_ms() -> int:
    return int(time.time() * 1000)


def _index_for(ms: int) -> str:
    return ".monitoring-es-8-" + time.strftime("%Y.%m.%d",
                                               time.gmtime(ms / 1000))


class MonitoringService:
    """``fetch(method, path) -> dict`` runs an internal REST call;
    ``bulk_fn(index, lines)`` writes export batches."""

    DEFAULT_INTERVAL_MS = 10_000

    def __init__(self, fetch: Callable[[str, str], dict],
                 bulk_fn: Callable[[str, List[dict]], dict],
                 cluster_uuid: str = "cluster"):
        self.fetch = fetch
        self.bulk_fn = bulk_fn
        self.cluster_uuid = cluster_uuid
        self.enabled = True
        self.interval_ms = self.DEFAULT_INTERVAL_MS
        self._next_due: Optional[int] = None
        self.collected_count = 0
        #: guards the tick schedule + collected_count: the collector
        #: runs on the node ticker thread while REST/stats threads read
        #: the rollup, and two ticker callers racing _next_due would
        #: double-collect a round (ESTP-R01/R02)
        self._tick_lock = threading.Lock()

    # -- collectors ------------------------------------------------------
    def collect(self, now_ms: Optional[int] = None) -> int:
        """One collection round: cluster stats, node stats, index stats
        → one bulk into today's monitoring index.  Returns doc count."""
        now = now_ms if now_ms is not None else _now_ms()
        ts = now
        docs: List[dict] = []

        cluster = self.fetch("GET", "/_cluster/stats")
        docs.append({"type": "cluster_stats",
                     "cluster_stats": {
                         "indices": cluster.get("indices"),
                         "nodes": cluster.get("nodes")},
                     "cluster_state": {
                         "status": cluster.get("status"),
                         "cluster_uuid": self.cluster_uuid}})

        nodes = self.fetch("GET", "/_nodes/stats")
        for node_id, nstats in (nodes.get("nodes") or {}).items():
            docs.append({"type": "node_stats",
                         "node_stats": {
                             "node_id": node_id,
                             "indices": nstats.get("indices"),
                             "jvm": nstats.get("jvm"),
                             "process": nstats.get("process"),
                             "thread_pool": nstats.get("thread_pool"),
                             # TPU-native sections: serving pipeline +
                             # device/XLA instrumentation must reach the
                             # monitoring indices, not just live stats
                             "plane_serving": (nstats.get("indices")
                                               or {}).get("plane_serving"),
                             "device": nstats.get("device")}})

        # telemetry collector: the registry snapshot (compile counts,
        # transfer bytes, breaker/pressure families) as its own doc type
        telemetry = self.fetch("GET", "/_nodes/telemetry")
        for node_id, tstats in (telemetry.get("nodes") or {}).items():
            docs.append({"type": "node_telemetry",
                         "node_telemetry": {
                             "node_id": node_id,
                             "device": tstats.get("device"),
                             "plane_serving": tstats.get("plane_serving"),
                             "registry": tstats.get("registry"),
                             "tasks": tstats.get("tasks")}})

        # health-report collector: indicator statuses land in the
        # monitoring index so a dashboard can chart color transitions
        # (rebuild storms, breaker trips) over time
        try:
            health = self.fetch("GET", "/_health_report")
        except Exception:   # noqa: BLE001 — health must never fail collect
            health = None
        if isinstance(health, dict) and health.get("indicators"):
            docs.append({"type": "health_report",
                         "health_report": {
                             "status": health.get("status"),
                             "indicators": {
                                 name: {"status": ind.get("status"),
                                        "symptom": ind.get("symptom")}
                                 for name, ind in
                                 health["indicators"].items()}}})

        stats = self.fetch("GET", "/_stats")
        for index, istats in (stats.get("indices") or {}).items():
            if index.startswith(".monitoring-"):
                continue
            docs.append({"type": "index_stats",
                         "index_stats": {
                             "index": index,
                             "primaries": istats.get("primaries"),
                             "total": istats.get("total")}})

        lines: List[dict] = []
        for d in docs:
            d["cluster_uuid"] = self.cluster_uuid
            d["timestamp"] = ts
            lines.append({"index": {}})
            lines.append(d)
        if lines:
            self.bulk_fn(_index_for(now), lines)
        with self._tick_lock:
            self.collected_count += len(docs)
        return len(docs)

    def tick(self, now_ms: Optional[int] = None) -> bool:
        if not self.enabled:
            return False
        now = now_ms if now_ms is not None else _now_ms()
        with self._tick_lock:
            # decide-and-advance atomically: the check and the schedule
            # write must not straddle the lock or two racing tickers
            # both pass the due check and collect twice (ESTP-R02)
            if self._next_due is None:
                self._next_due = now + self.interval_ms
                return False
            if now < self._next_due:
                return False
            self._next_due = now + self.interval_ms
        self.collect(now)
        return True

    # -- /_monitoring/bulk ----------------------------------------------
    def bulk(self, system_id: str, interval: str,
             payload: bytes) -> dict:
        """External intake: NDJSON of {index meta}\\n{doc} pairs, each
        doc wrapped in the envelope and routed to the monitoring index
        (``RestMonitoringBulkAction.java``)."""
        now = _now_ms()
        lines: List[dict] = []
        meta_type = "doc"
        text = payload.decode() if isinstance(payload,
                                              (bytes, bytearray)) \
            else str(payload)
        for raw in text.splitlines():
            raw = raw.strip()
            if not raw:
                continue
            doc = json.loads(raw)
            if "index" in doc and set(doc) == {"index"}:
                meta_type = (doc["index"] or {}).get("_type", "doc")
                continue
            doc = {"type": meta_type, meta_type: doc,
                   "cluster_uuid": self.cluster_uuid,
                   "timestamp": now,
                   "source_node": {"system_id": system_id,
                                   "interval": interval}}
            lines.append({"index": {}})
            lines.append(doc)
        if lines:
            self.bulk_fn(_index_for(now), lines)
        return {"took": 0, "ignored": False, "errors": False}
