"""SQL front-end: parse a SQL subset, fold it into query DSL + aggs, and
serve ES-SQL-shaped responses (columns/rows, cursors, txt/csv/tsv formats).

Reference: ``x-pack/plugin/sql`` — parser → analyzer → optimizer → physical
plan "folding" into a search request (``sql/{parser,analysis,planner}/``).
This implementation keeps the same *observable* pipeline (SELECT folds to a
search body; GROUP BY folds to a composite aggregation with metric
sub-aggs; cursors page through composite ``after_key``s) but is a compact
recursive-descent parser + direct folder rather than a multi-stage rule
optimizer: the heavy lifting (scoring, agg collection) already lives in the
TPU search path the folded request executes on.

Supported surface (documented subset):
  SELECT */cols/aggregate-functions [AS alias]
  FROM index [WHERE cond] [GROUP BY cols] [HAVING cond]
  [ORDER BY col [ASC|DESC], ...] [LIMIT n]
Predicates: =, !=/<>, <, <=, >, >=, [NOT] LIKE, [NOT] IN (...),
BETWEEN..AND, IS [NOT] NULL, AND/OR/NOT, MATCH(field, 'text'),
QUERY('query string'), SCORE().
Aggregates: COUNT(*|col|DISTINCT col), SUM, AVG, MIN, MAX.
Scalar date parts: YEAR/MONTH/DAY (host-evaluated over group keys).
"""
from __future__ import annotations

import json
import re
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..common.errors import ElasticsearchError, IllegalArgumentError


class SqlParsingError(ElasticsearchError):
    status = 400
    error_type = "parsing_exception"


class SqlVerificationError(ElasticsearchError):
    """Unknown column / invalid combination (``VerificationException``)."""
    status = 400
    error_type = "verification_exception"


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RX = re.compile(r"""
    \s*(?:
      (?P<num>-?\d+\.\d+|-?\d+)
    | '(?P<str>(?:[^']|'')*)'
    | "(?P<qid>(?:[^"]|"")*)"
    | (?P<op><=|>=|!=|<>|=|<|>|\(|\)|,|\*|\.)
    | (?P<id>[A-Za-z_][A-Za-z0-9_.*-]*)
    )""", re.VERBOSE)

_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "AND", "OR", "NOT", "LIKE", "IN", "BETWEEN", "IS", "NULL", "AS",
    "ASC", "DESC", "DISTINCT", "TRUE", "FALSE",
}


def _tokenize(text: str) -> List[Tuple[str, Any]]:
    out: List[Tuple[str, Any]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RX.match(text, pos)
        if m is None or m.end() == pos:
            rest = text[pos:].strip()
            if not rest:
                break
            raise SqlParsingError(f"line 1:{pos + 1}: token recognition "
                                  f"error at: '{rest[0]}'")
        pos = m.end()
        if m.group("num") is not None:
            n = m.group("num")
            out.append(("num", float(n) if "." in n else int(n)))
        elif m.group("str") is not None:
            out.append(("str", m.group("str").replace("''", "'")))
        elif m.group("qid") is not None:
            out.append(("id", m.group("qid").replace('""', '"')))
        elif m.group("op") is not None:
            out.append(("op", m.group("op")))
        else:
            word = m.group("id")
            if word.upper() in _KEYWORDS:
                out.append(("kw", word.upper()))
            else:
                out.append(("id", word))
    out.append(("eof", None))
    return out


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

class Expr:
    pass


class Col(Expr):
    def __init__(self, name: str):
        self.name = name


class Lit(Expr):
    def __init__(self, value: Any):
        self.value = value


class Func(Expr):
    def __init__(self, name: str, args: List[Expr], distinct: bool = False):
        self.name = name.upper()
        self.args = args
        self.distinct = distinct


class Cmp(Expr):
    def __init__(self, op: str, left: Expr, right: Expr):
        self.op, self.left, self.right = op, left, right


class Like(Expr):
    def __init__(self, col: Expr, pattern: str, negate: bool):
        self.col, self.pattern, self.negate = col, pattern, negate


class InList(Expr):
    def __init__(self, col: Expr, values: List[Any], negate: bool):
        self.col, self.values, self.negate = col, values, negate


class Between(Expr):
    def __init__(self, col: Expr, low: Any, high: Any):
        self.col, self.low, self.high = col, low, high


class IsNull(Expr):
    def __init__(self, col: Expr, negate: bool):
        self.col, self.negate = col, negate


class Bool(Expr):
    def __init__(self, op: str, parts: List[Expr]):
        self.op, self.parts = op, parts      # "and" | "or"


class Not(Expr):
    def __init__(self, part: Expr):
        self.part = part


class SelectItem:
    def __init__(self, expr: Expr, alias: Optional[str]):
        self.expr, self.alias = expr, alias


class Query:
    def __init__(self):
        self.items: List[SelectItem] = []
        self.star = False
        self.table: str = ""
        self.where: Optional[Expr] = None
        self.group_by: List[Expr] = []
        self.having: Optional[Expr] = None
        self.order_by: List[Tuple[Expr, bool]] = []   # (expr, asc)
        self.limit: Optional[int] = None


class _Parser:
    def __init__(self, tokens: List[Tuple[str, Any]]):
        self.toks = tokens
        self.i = 0

    # -- token helpers --------------------------------------------------
    def peek(self) -> Tuple[str, Any]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, Any]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *words: str) -> Optional[str]:
        k, v = self.peek()
        if k == "kw" and v in words:
            self.i += 1
            return v
        return None

    def expect_kw(self, word: str) -> None:
        if not self.accept_kw(word):
            k, v = self.peek()
            raise SqlParsingError(f"expected {word} but found [{v}]")

    def accept_op(self, op: str) -> bool:
        k, v = self.peek()
        if k == "op" and v == op:
            self.i += 1
            return True
        return False

    # -- grammar --------------------------------------------------------
    def parse(self) -> Query:
        q = Query()
        self.expect_kw("SELECT")
        if self.accept_op("*"):
            q.star = True
        else:
            q.items.append(self.select_item())
            while self.accept_op(","):
                q.items.append(self.select_item())
        self.expect_kw("FROM")
        q.table = self.table_name()
        if self.accept_kw("WHERE"):
            q.where = self.expr()
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            q.group_by.append(self.primary())
            while self.accept_op(","):
                q.group_by.append(self.primary())
        if self.accept_kw("HAVING"):
            q.having = self.expr()
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                e = self.primary()
                asc = True
                if self.accept_kw("DESC"):
                    asc = False
                else:
                    self.accept_kw("ASC")
                q.order_by.append((e, asc))
                if not self.accept_op(","):
                    break
        if self.accept_kw("LIMIT"):
            k, v = self.next()
            if k != "num" or not isinstance(v, int):
                raise SqlParsingError("LIMIT expects an integer")
            q.limit = v
        k, v = self.peek()
        if k != "eof":
            raise SqlParsingError(f"unexpected trailing input [{v}]")
        return q

    def table_name(self) -> str:
        k, v = self.next()
        if k not in ("id", "str"):
            raise SqlParsingError(f"expected index name but found [{v}]")
        name = str(v)
        # frozen-index syntax and catalog-qualified names are not needed;
        # allow  alias:index  (CCS) and patterns straight through
        return name

    def select_item(self) -> SelectItem:
        e = self.primary()
        alias = None
        if self.accept_kw("AS"):
            k, v = self.next()
            if k != "id":
                raise SqlParsingError("expected alias name")
            alias = v
        else:
            k, v = self.peek()
            if k == "id":
                self.i += 1
                alias = v
        return SelectItem(e, alias)

    def expr(self) -> Expr:
        parts = [self.and_expr()]
        while self.accept_kw("OR"):
            parts.append(self.and_expr())
        return parts[0] if len(parts) == 1 else Bool("or", parts)

    def and_expr(self) -> Expr:
        parts = [self.not_expr()]
        while self.accept_kw("AND"):
            parts.append(self.not_expr())
        return parts[0] if len(parts) == 1 else Bool("and", parts)

    def not_expr(self) -> Expr:
        if self.accept_kw("NOT"):
            return Not(self.not_expr())
        return self.predicate()

    def predicate(self) -> Expr:
        left = self.primary()
        k, v = self.peek()
        if k == "op" and v in ("=", "!=", "<>", "<", "<=", ">", ">="):
            self.i += 1
            right = self.primary()
            return Cmp("!=" if v == "<>" else v, left, right)
        negate = bool(self.accept_kw("NOT"))
        if self.accept_kw("LIKE"):
            kk, vv = self.next()
            if kk != "str":
                raise SqlParsingError("LIKE expects a string pattern")
            return Like(left, vv, negate)
        if self.accept_kw("IN"):
            if not self.accept_op("("):
                raise SqlParsingError("IN expects a value list")
            vals = []
            while True:
                kk, vv = self.next()
                if kk not in ("num", "str", "kw"):
                    raise SqlParsingError("IN expects literal values")
                vals.append(self._kw_literal(kk, vv))
                if self.accept_op(")"):
                    break
                if not self.accept_op(","):
                    raise SqlParsingError("expected , or ) in IN list")
            return InList(left, vals, negate)
        if self.accept_kw("BETWEEN"):
            lo = self.literal_value()
            self.expect_kw("AND")
            hi = self.literal_value()
            e: Expr = Between(left, lo, hi)
            return Not(e) if negate else e
        if negate:
            raise SqlParsingError("NOT must precede LIKE/IN/BETWEEN here")
        if self.accept_kw("IS"):
            neg = bool(self.accept_kw("NOT"))
            self.expect_kw("NULL")
            return IsNull(left, neg)
        return left

    @staticmethod
    def _kw_literal(kind: str, val: Any) -> Any:
        if kind == "kw":
            if val == "TRUE":
                return True
            if val == "FALSE":
                return False
            if val == "NULL":
                return None
            raise SqlParsingError(f"unexpected keyword [{val}] as value")
        return val

    def literal_value(self) -> Any:
        k, v = self.next()
        if k not in ("num", "str", "kw"):
            raise SqlParsingError(f"expected a literal but found [{v}]")
        return self._kw_literal(k, v)

    def primary(self) -> Expr:
        if self.accept_op("("):
            e = self.expr()
            if not self.accept_op(")"):
                raise SqlParsingError("expected )")
            return e
        k, v = self.next()
        if k == "num" or k == "str":
            return Lit(v)
        if k == "kw" and v in ("TRUE", "FALSE", "NULL"):
            return Lit({"TRUE": True, "FALSE": False, "NULL": None}[v])
        if k == "id":
            if self.accept_op("("):
                return self.func_call(v)
            return Col(v)
        raise SqlParsingError(f"unexpected token [{v}]")

    def func_call(self, name: str) -> Func:
        distinct = bool(self.accept_kw("DISTINCT"))
        args: List[Expr] = []
        if self.accept_op(")"):
            return Func(name, args, distinct)
        while True:
            if self.accept_op("*"):
                args.append(Lit("*"))
            else:
                args.append(self.primary())
            if self.accept_op(")"):
                break
            if not self.accept_op(","):
                raise SqlParsingError("expected , or ) in argument list")
        return Func(name, args, distinct)


def parse_sql(text: str) -> Query:
    return _Parser(_tokenize(text)).parse()


# ---------------------------------------------------------------------------
# folding: WHERE → query DSL
# ---------------------------------------------------------------------------

_CMP_RANGE = {"<": "lt", "<=": "lte", ">": "gt", ">=": "gte"}
_AGG_FUNCS = {"COUNT", "SUM", "AVG", "MIN", "MAX"}
_DATE_PARTS = {"YEAR", "MONTH", "DAY", "HOUR", "MINUTE"}


def _col_name(e: Expr) -> str:
    if not isinstance(e, Col):
        raise SqlVerificationError("expected a column reference")
    return e.name


def _like_to_wildcard(pattern: str) -> str:
    # SQL % / _ → wildcard * / ?, literal escapes kept simple
    return pattern.replace("%", "*").replace("_", "?")


def fold_condition(e: Expr, resolve=None) -> dict:
    """Fold a WHERE/HAVING-free condition into query DSL.

    ``resolve`` maps a column name to the field exact operations should
    target — ES SQL silently uses a text field's ``.keyword`` sub-field
    for exact semantics (``sql/analysis/analyzer/Analyzer.java`` exact
    -field resolution); full-text operators (MATCH/QUERY/LIKE-as-match)
    keep the raw field.
    """
    rf = resolve or (lambda n: n)
    if isinstance(e, Bool):
        key = "must" if e.op == "and" else "should"
        out: dict = {"bool": {key: [fold_condition(p, resolve)
                                    for p in e.parts]}}
        if e.op == "or":
            out["bool"]["minimum_should_match"] = 1
        return out
    if isinstance(e, Not):
        return {"bool": {"must_not": [fold_condition(e.part, resolve)]}}
    if isinstance(e, Cmp):
        if isinstance(e.left, Func):
            fn = e.left
            if fn.name == "SCORE":
                raise SqlVerificationError(
                    "SCORE() cannot be used in WHERE; use ORDER BY SCORE()")
            raise SqlVerificationError(
                f"scalar function [{fn.name}] not supported in WHERE")
        col, lit = e.left, e.right
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
        op = e.op
        if isinstance(col, Lit) and isinstance(lit, Col):
            col, lit = lit, col
            op = flip.get(op, op)
        if not isinstance(col, Col) or not isinstance(lit, Lit):
            raise SqlVerificationError(
                "comparison must be between a column and a literal")
        if op == "=":
            return {"term": {rf(col.name): {"value": lit.value}}}
        if op == "!=":
            return {"bool": {"must_not": [
                {"term": {rf(col.name): {"value": lit.value}}}]}}
        return {"range": {col.name: {_CMP_RANGE[op]: lit.value}}}
    if isinstance(e, Like):
        q = {"wildcard": {rf(_col_name(e.col)): {
            "value": _like_to_wildcard(e.pattern)}}}
        return {"bool": {"must_not": [q]}} if e.negate else q
    if isinstance(e, InList):
        q = {"terms": {rf(_col_name(e.col)): list(e.values)}}
        return {"bool": {"must_not": [q]}} if e.negate else q
    if isinstance(e, Between):
        return {"range": {_col_name(e.col): {"gte": e.low, "lte": e.high}}}
    if isinstance(e, IsNull):
        q = {"exists": {"field": _col_name(e.col)}}
        return q if e.negate else {"bool": {"must_not": [q]}}
    if isinstance(e, Func):
        if e.name == "MATCH":
            if len(e.args) < 2:
                raise SqlVerificationError("MATCH needs (field, text)")
            field = e.args[0].name if isinstance(e.args[0], Col) \
                else str(_lit(e.args[0]))
            return {"match": {field: {"query": _lit(e.args[1])}}}
        if e.name == "QUERY":
            return {"query_string": {"query": str(_lit(e.args[0]))}}
        raise SqlVerificationError(
            f"function [{e.name}] not valid as a condition")
    raise SqlVerificationError("condition not translatable")


def _lit(e: Expr) -> Any:
    if not isinstance(e, Lit):
        raise SqlVerificationError("expected a literal")
    return e.value


# ---------------------------------------------------------------------------
# type mapping
# ---------------------------------------------------------------------------

_SQL_TYPES = {
    "text": "text", "keyword": "keyword", "long": "long",
    "integer": "integer", "short": "short", "byte": "byte",
    "double": "double", "float": "float", "half_float": "half_float",
    "scaled_float": "scaled_float", "boolean": "boolean",
    "date": "datetime", "date_nanos": "datetime", "ip": "ip",
    "unsigned_long": "unsigned_long", "version": "version",
}


def _sql_type(type_name: Optional[str]) -> str:
    if type_name is None:
        return "keyword"
    return _SQL_TYPES.get(type_name, type_name)


# ---------------------------------------------------------------------------
# the service
# ---------------------------------------------------------------------------

class SqlService:
    """Holds cursors and executes folded SQL through the REST search seam.

    ``search_fn(index, body) -> response-dict`` is supplied by the REST
    layer so the folded request rides the full (cluster-aware, TPU-planed)
    search path.
    """

    MAX_PAGE = 1000
    #: bound on live cursors (abandoned pagers evict oldest-first; the
    #: reference expires cursors server-side the same way)
    MAX_CURSORS = 500

    def __init__(self, search_fn, mapper_fn):
        self.search_fn = search_fn
        self.mapper_fn = mapper_fn       # index -> MapperService or None
        self.cursors: Dict[str, dict] = {}

    def _new_cursor(self, state: dict) -> str:
        cur = uuid.uuid4().hex
        while len(self.cursors) >= self.MAX_CURSORS:
            self.cursors.pop(next(iter(self.cursors)))
        self.cursors[cur] = state
        return cur

    def _exact_resolver(self, mapper):
        """ES SQL targets a text field's ``.keyword`` sub-field for exact
        operations (sort, group, term equality); a text field with no
        keyword sub-field is not exact-capable."""
        def rf(name: str) -> str:
            if mapper is None:
                return name
            ft = mapper.field_type(name)
            if ft is not None and ft.type_name == "text":
                sub = mapper.field_type(name + ".keyword")
                if sub is not None and sub.type_name == "keyword":
                    return name + ".keyword"
            return name
        return rf

    # -- public entry ---------------------------------------------------
    def execute(self, payload: dict, fmt: str = "json") -> Any:
        if payload.get("cursor"):
            return self._continue_cursor(payload["cursor"], fmt)
        sql = payload.get("query")
        if not sql or not isinstance(sql, str):
            raise SqlParsingError("[query] is required")
        q = parse_sql(sql)
        fetch_size = int(payload.get("fetch_size", 1000))
        if q.group_by or any(self._is_agg_item(it) for it in q.items):
            return self._run_grouped(q, fetch_size, fmt, payload)
        return self._run_select(q, fetch_size, fmt, payload)

    def translate(self, payload: dict) -> dict:
        sql = payload.get("query")
        if not sql:
            raise SqlParsingError("[query] is required")
        q = parse_sql(sql)
        if q.group_by or any(self._is_agg_item(it) for it in q.items):
            body, _cols = self._fold_grouped(q, int(
                payload.get("fetch_size", 1000)))
        else:
            body, _cols = self._fold_select(q)
        return body

    def close_cursor(self, cursor: str) -> bool:
        return self.cursors.pop(cursor, None) is not None

    # -- plain SELECT ---------------------------------------------------
    @staticmethod
    def _is_agg_item(it: SelectItem) -> bool:
        return isinstance(it.expr, Func) and it.expr.name in _AGG_FUNCS

    def _columns_for(self, q: Query, mapper) -> List[dict]:
        cols = []
        if q.star:
            names = mapper.field_names() if mapper is not None else []
            for n in names:
                ft = mapper.field_type(n)
                tn = getattr(ft, "type_name", None)
                if tn in (None, "object", "nested", "alias", "completion"):
                    continue
                if n.startswith("_"):
                    continue
                cols.append({"name": n, "type": _sql_type(tn)})
            return cols
        for it in q.items:
            e = it.expr
            if isinstance(e, Col):
                tn = None
                if mapper is not None:
                    tn = getattr(mapper.field_type(e.name), "type_name",
                                 None)
                    if tn is None:
                        raise SqlVerificationError(
                            f"Unknown column [{e.name}]")
                cols.append({"name": it.alias or e.name,
                             "type": _sql_type(tn)})
            elif isinstance(e, Func) and e.name == "SCORE":
                cols.append({"name": it.alias or "SCORE()",
                             "type": "float"})
            elif isinstance(e, Lit):
                t = ("long" if isinstance(e.value, int)
                     else "double" if isinstance(e.value, float)
                     else "keyword")
                cols.append({"name": it.alias or str(e.value), "type": t})
            else:
                raise SqlVerificationError(
                    "only columns, literals and SCORE() are selectable "
                    "without GROUP BY")
        return cols

    def _fold_select(self, q: Query) -> Tuple[dict, List[dict]]:
        mapper = self.mapper_fn(q.table)
        rf = self._exact_resolver(mapper)
        cols = self._columns_for(q, mapper)
        body: dict = {"size": q.limit if q.limit is not None else 1000}
        if q.where is not None:
            body["query"] = fold_condition(q.where, rf)
        if q.order_by:
            sort = []
            for e, asc in q.order_by:
                order = "asc" if asc else "desc"
                if isinstance(e, Func) and e.name == "SCORE":
                    sort.append({"_score": {"order": order}})
                else:
                    sort.append({rf(_col_name(e)): {"order": order}})
            body["sort"] = sort
        else:
            # implicit sort so fetch_size paging always has a cursor key
            # (ES SQL pages unsorted selects the same way); relevance
            # order when SCORE() is projected, index order otherwise
            want_score = any(isinstance(it.expr, Func)
                             and it.expr.name == "SCORE" for it in q.items)
            body["sort"] = [{"_score": {"order": "desc"}}] if want_score \
                else [{"_doc": {"order": "asc"}}]
        fields = [it.expr.name for it in q.items
                  if isinstance(it.expr, Col)] if not q.star else True
        body["_source"] = fields if fields else True
        return body, cols

    def _run_select(self, q: Query, fetch_size: int, fmt: str,
                    payload: dict) -> Any:
        body, cols = self._fold_select(q)
        limit = body["size"]
        page = min(limit, fetch_size, self.MAX_PAGE)
        body["size"] = page
        want_score = any(isinstance(it.expr, Func)
                         and it.expr.name == "SCORE" for it in q.items)
        if want_score:
            body["track_scores"] = True
        resp = self.search_fn(q.table, body)
        rows = self._rows_from_hits(q, cols, resp["hits"]["hits"])
        out = {"columns": cols, "rows": rows}
        # deep SELECT pagination beyond one page is cursor-driven
        remaining = (limit - len(rows)) if q.limit is not None else None
        if len(rows) == page and (remaining is None or remaining > 0) and \
                resp["hits"]["hits"]:
            last = resp["hits"]["hits"][-1]
            if body.get("sort") and last.get("sort") is not None:
                cur = self._new_cursor({
                    "kind": "select", "q": q, "body": body, "cols": cols,
                    "after": last["sort"], "remaining": remaining,
                    "fetch": page})
                out["cursor"] = cur
        return self._format(out, fmt)

    def _rows_from_hits(self, q: Query, cols: List[dict],
                        hits: List[dict]) -> List[list]:
        rows = []
        for h in hits:
            src = h.get("_source") or {}
            row = []
            if q.star:
                for c in cols:
                    v = _path_get(src, c["name"])
                    if v is None and "." in c["name"]:
                        # multi-field sub-column (name.keyword) reads the
                        # parent's source value, like ES SQL
                        v = _path_get(src, c["name"].rsplit(".", 1)[0])
                    row.append(v)
            else:
                for it in q.items:
                    e = it.expr
                    if isinstance(e, Col):
                        row.append(_path_get(src, e.name))
                    elif isinstance(e, Func) and e.name == "SCORE":
                        row.append(h.get("_score"))
                    else:
                        row.append(_lit(e))
            rows.append(row)
        return rows

    # -- GROUP BY / aggregates -----------------------------------------
    def _fold_grouped(self, q: Query,
                      fetch_size: int) -> Tuple[dict, List[dict]]:
        mapper = self.mapper_fn(q.table)
        group_cols: List[Tuple[str, Optional[str], str]] = []
        # (composite source name, date_part, column name)
        for e in q.group_by:
            if isinstance(e, Func) and e.name in _DATE_PARTS:
                col = _col_name(e.args[0])
                group_cols.append((col, e.name, f"{e.name}({col})"))
            else:
                group_cols.append((_col_name(e), None, _col_name(e)))
        cols: List[dict] = []
        metrics: Dict[str, dict] = {}
        row_plan: List[Tuple[str, Any]] = []   # ("group", idx)|("metric", key)|("lit", v)
        items = q.items if q.items else [
            SelectItem(Col(c[2]), None) for c in group_cols]
        midx = 0
        for it in items:
            e = it.expr
            if isinstance(e, Func) and e.name in _AGG_FUNCS:
                arg = e.args[0] if e.args else Lit("*")
                label = it.alias or self._fn_label(e)
                if e.name == "COUNT" and isinstance(arg, Lit) \
                        and arg.value == "*":
                    row_plan.append(("count", None))
                    cols.append({"name": label, "type": "long"})
                    continue
                field = _col_name(arg)
                if mapper is not None and \
                        mapper.field_type(field) is None:
                    raise SqlVerificationError(f"Unknown column [{field}]")
                key = f"m{midx}"
                midx += 1
                exact = self._exact_resolver(mapper)(field)
                if e.name == "COUNT" and e.distinct:
                    metrics[key] = {"cardinality": {"field": exact}}
                    cols.append({"name": label, "type": "long"})
                elif e.name == "COUNT":
                    metrics[key] = {"value_count": {"field": exact}}
                    cols.append({"name": label, "type": "long"})
                else:
                    metrics[key] = {e.name.lower(): {"field": field}}
                    cols.append({"name": label, "type": "double"})
                row_plan.append(("metric", key))
            else:
                # must be one of the group-by expressions
                name = (f"{e.name}({_col_name(e.args[0])})"
                        if isinstance(e, Func) else _col_name(e))
                for gi, (_c, _p, cname) in enumerate(group_cols):
                    if cname == name:
                        row_plan.append(("group", gi))
                        tn = None
                        if _p is not None:
                            tn = "integer"
                        elif mapper is not None:
                            ft = mapper.field_type(_c)
                            if ft is None:
                                raise SqlVerificationError(
                                    f"Unknown column [{_c}]")
                            tn = _sql_type(ft.type_name)
                        cols.append({"name": it.alias or name,
                                     "type": tn or "keyword"})
                        break
                else:
                    raise SqlVerificationError(
                        f"Cannot use non-grouped column [{name}], "
                        f"expected one of {[c[2] for c in group_cols]}")
        if not q.group_by:
            # global aggregates: single row of top-level aggs
            body: dict = {"size": 0, "aggs": {
                k: v for k, v in metrics.items()}}
            if q.where is not None:
                body["query"] = fold_condition(
                    q.where, self._exact_resolver(mapper))
            body["track_total_hits"] = True
            return body, cols
        sources = []
        for (c, part, cname) in group_cols:
            if part is not None:
                cal = {"YEAR": "year", "MONTH": "month", "DAY": "day",
                       "HOUR": "hour", "MINUTE": "minute"}[part]
                sources.append({cname: {"date_histogram": {
                    "field": c, "calendar_interval": cal,
                    "missing_bucket": True}}})
            else:
                sources.append({cname: {"terms": {
                    "field": self._exact_resolver(mapper)(c),
                    "missing_bucket": True}}})
        comp: dict = {"size": min(fetch_size, self.MAX_PAGE),
                      "sources": sources}
        aggs: dict = {"groupby": {"composite": comp}}
        if metrics:
            aggs["groupby"]["aggs"] = dict(metrics)
        body = {"size": 0, "aggs": aggs}
        if q.where is not None:
            body["query"] = fold_condition(
                q.where, self._exact_resolver(mapper))
        return body, cols

    @staticmethod
    def _fn_label(e: Func) -> str:
        if e.name == "COUNT" and e.args and isinstance(e.args[0], Lit):
            return "COUNT(*)"
        inner = e.args[0].name if e.args and isinstance(e.args[0], Col) \
            else "*"
        d = "DISTINCT " if e.distinct else ""
        return f"{e.name}({d}{inner})"

    def _run_grouped(self, q: Query, fetch_size: int, fmt: str,
                     payload: dict) -> Any:
        body, cols = self._fold_grouped(q, fetch_size)
        if not q.group_by:
            resp = self.search_fn(q.table, body)
            aggs = resp.get("aggregations") or {}
            row = []
            items = q.items
            mi = 0
            for it in items:
                e = it.expr
                if isinstance(e, Func) and e.name == "COUNT" and e.args \
                        and isinstance(e.args[0], Lit) \
                        and e.args[0].value == "*":
                    row.append(resp["hits"]["total"]["value"])
                else:
                    row.append(aggs.get(f"m{mi}", {}).get("value"))
                    mi += 1
            rows = [row]
            if q.having is not None:
                n2i = {c["name"]: i for i, c in enumerate(cols)}
                rows = [r for r in rows
                        if _eval_having(q.having, n2i, r, q)]
            return self._format({"columns": cols, "rows": rows}, fmt)
        rows, after = self._grouped_page(q, body, cols)
        rows = self._post_group(q, cols, rows)
        out = {"columns": cols, "rows": rows}
        if after is not None and not q.having and not q.order_by \
                and q.limit is None:
            cur = self._new_cursor({"kind": "grouped", "q": q,
                                    "body": body, "cols": cols,
                                    "after": after})
            out["cursor"] = cur
        return self._format(out, fmt)

    def _grouped_page(self, q: Query, body: dict,
                      cols: List[dict]) -> Tuple[List[list], Optional[dict]]:
        """One composite page → rows (+ after_key). HAVING/ORDER BY/LIMIT
        queries drain ALL pages here so host-side filtering is exact."""
        drain = bool(q.having or q.order_by or q.limit is not None)
        rows: List[list] = []
        sources_def = body["aggs"]["groupby"]["composite"]["sources"]
        group_names = [list(s.keys())[0] for s in sources_def]
        date_parts = {}
        for s in sources_def:
            (gname, gdef), = s.items()
            m = re.match(r"(YEAR|MONTH|DAY|HOUR|MINUTE)\(", gname)
            if m and "date_histogram" in gdef:
                date_parts[gname] = m.group(1)
        after = None
        while True:
            resp = self.search_fn(q.table, body)
            comp = (resp.get("aggregations") or {}).get("groupby") or {}
            for b in comp.get("buckets", []):
                row = []
                items = q.items if q.items else [
                    SelectItem(Col(n), None) for n in group_names]
                for plan, it in zip(self._plan_of(q, group_names), items):
                    kind, ref = plan
                    if kind == "group":
                        v = b["key"].get(group_names[ref])
                        part = date_parts.get(group_names[ref])
                        if part is not None and v is not None:
                            v = _date_part(part, v)
                        row.append(v)
                    elif kind == "count":
                        row.append(b["doc_count"])
                    else:
                        row.append((b.get(ref) or {}).get("value"))
                rows.append(row)
            after = comp.get("after_key")
            if after is None or not comp.get("buckets"):
                return rows, None
            if not drain:
                return rows, after
            body = dict(body)
            newaggs = json.loads(json.dumps(body["aggs"]))
            newaggs["groupby"]["composite"]["after"] = after
            body["aggs"] = newaggs

    def _plan_of(self, q: Query,
                 group_names: List[str]) -> List[Tuple[str, Any]]:
        plan: List[Tuple[str, Any]] = []
        items = q.items if q.items else [SelectItem(Col(n), None)
                                         for n in group_names]
        mi = 0
        for it in items:
            e = it.expr
            if isinstance(e, Func) and e.name in _AGG_FUNCS:
                if e.name == "COUNT" and e.args and \
                        isinstance(e.args[0], Lit) and e.args[0].value == "*":
                    plan.append(("count", None))
                else:
                    plan.append(("metric", f"m{mi}"))
                    mi += 1
            else:
                name = (f"{e.name}({_col_name(e.args[0])})"
                        if isinstance(e, Func) else e.name)
                plan.append(("group", group_names.index(name)))
        return plan

    def _post_group(self, q: Query, cols: List[dict],
                    rows: List[list]) -> List[list]:
        name_to_idx = {c["name"]: i for i, c in enumerate(cols)}
        if q.having is not None:
            rows = [r for r in rows
                    if _eval_having(q.having, name_to_idx, r, q)]
        if q.order_by:
            for e, asc in reversed(q.order_by):
                if isinstance(e, Func) and e.name in _AGG_FUNCS:
                    key_name = self._fn_label(e)
                else:
                    key_name = _col_name(e) if isinstance(e, Col) else None
                idx = name_to_idx.get(key_name)
                if idx is None:
                    # maybe aliased: match by position in select items
                    for i, it in enumerate(q.items):
                        if _expr_eq(it.expr, e):
                            idx = i
                            break
                if idx is None:
                    raise SqlVerificationError(
                        f"ORDER BY refers to unknown output [{key_name}]")
                rows.sort(key=lambda r, j=idx: (r[j] is None,
                                                r[j] if r[j] is not None
                                                else 0),
                          reverse=not asc)
        if q.limit is not None:
            rows = rows[:q.limit]
        return rows

    # -- cursors --------------------------------------------------------
    def _continue_cursor(self, cursor: str, fmt: str) -> Any:
        st = self.cursors.get(cursor)
        if st is None:
            raise SqlParsingError("invalid or expired cursor")
        q, cols = st["q"], st["cols"]
        if st["kind"] == "select":
            body = dict(st["body"])
            body["search_after"] = st["after"]
            page = st["fetch"]
            if st["remaining"] is not None:
                page = min(page, st["remaining"])
            body["size"] = page
            resp = self.search_fn(q.table, body)
            rows = self._rows_from_hits(q, cols, resp["hits"]["hits"])
            out = {"columns": cols, "rows": rows}
            done = len(rows) < page or (
                st["remaining"] is not None
                and st["remaining"] - len(rows) <= 0)
            if not done and resp["hits"]["hits"]:
                st["after"] = resp["hits"]["hits"][-1]["sort"]
                if st["remaining"] is not None:
                    st["remaining"] -= len(rows)
                out["cursor"] = cursor
            else:
                self.cursors.pop(cursor, None)
            return self._format(out, fmt)
        body = json.loads(json.dumps(st["body"]))
        body["aggs"]["groupby"]["composite"]["after"] = st["after"]
        rows, after = self._grouped_page(q, body, cols)
        out = {"columns": cols, "rows": rows}
        if after is not None:
            st["after"] = after
            out["cursor"] = cursor
        else:
            self.cursors.pop(cursor, None)
        return self._format(out, fmt)

    # -- output formats -------------------------------------------------
    @staticmethod
    def _format(out: dict, fmt: str) -> Any:
        if fmt in ("json", None):
            return out
        cols = out["columns"]
        rows = out["rows"]

        def cell(v: Any) -> str:
            if v is None:
                return "null"
            if isinstance(v, bool):
                return "true" if v else "false"
            if isinstance(v, float):
                return repr(v)
            return str(v)

        if fmt in ("csv", "tsv"):
            sep = "," if fmt == "csv" else "\t"

            def esc(s: str) -> str:
                if fmt == "csv" and (sep in s or '"' in s or "\n" in s):
                    return '"' + s.replace('"', '""') + '"'
                return s
            lines = [sep.join(esc(c["name"]) for c in cols)]
            lines += [sep.join(esc(cell(v)) for v in r) for r in rows]
            return "\n".join(lines) + "\n"
        if fmt == "txt":
            headers = [c["name"] for c in cols]
            table = [[cell(v) for v in r] for r in rows]
            widths = [max([len(h)] + [len(r[i]) for r in table])
                      for i, h in enumerate(headers)]
            head = "|".join(h.ljust(w) for h, w in zip(headers, widths))
            rule = "+".join("-" * w for w in widths)
            body_lines = ["|".join(v.ljust(w) for v, w in zip(r, widths))
                          for r in table]
            return "\n".join([head, rule] + body_lines) + "\n"
        raise IllegalArgumentError(f"Invalid format [{fmt}]")


def _expr_eq(a: Expr, b: Expr) -> bool:
    if type(a) is not type(b):
        return False
    if isinstance(a, Col):
        return a.name == b.name
    if isinstance(a, Func):
        return a.name == b.name and len(a.args) == len(b.args) and \
            all(_expr_eq(x, y) for x, y in zip(a.args, b.args))
    if isinstance(a, Lit):
        return a.value == b.value
    return False


def _eval_having(e: Expr, name_to_idx: Dict[str, int], row: list,
                 q: Query) -> bool:
    if isinstance(e, Bool):
        vals = [_eval_having(p, name_to_idx, row, q) for p in e.parts]
        return all(vals) if e.op == "and" else any(vals)
    if isinstance(e, Not):
        return not _eval_having(e.part, name_to_idx, row, q)
    if isinstance(e, Cmp):
        left = _having_value(e.left, name_to_idx, row, q)
        right = _having_value(e.right, name_to_idx, row, q)
        if left is None or right is None:
            return False
        ops = {"=": lambda a, b: a == b, "!=": lambda a, b: a != b,
               "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
               ">": lambda a, b: a > b, ">=": lambda a, b: a >= b}
        return ops[e.op](left, right)
    raise SqlVerificationError("HAVING supports comparisons of aggregates")


def _having_value(e: Expr, name_to_idx: Dict[str, int], row: list,
                  q: Query) -> Any:
    if isinstance(e, Lit):
        return e.value
    label = None
    if isinstance(e, Func):
        label = SqlService._fn_label(e)
    elif isinstance(e, Col):
        label = e.name
    idx = name_to_idx.get(label)
    if idx is None:
        for i, it in enumerate(q.items):
            if it.alias == label or _expr_eq(it.expr, e):
                idx = i
                break
    if idx is None:
        raise SqlVerificationError(
            f"HAVING refers to [{label}] which is not in the SELECT list")
    return row[idx]


def _path_get(src: dict, path: str) -> Any:
    cur: Any = src
    for part in path.split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            return None
    if isinstance(cur, (dict,)):
        return None
    return cur


def _date_part(part: str, epoch_millis: Any) -> int:
    """Host-side calendar-part extraction over date_histogram keys."""
    import datetime as _dt
    dt = _dt.datetime.fromtimestamp(float(epoch_millis) / 1e3,
                                    _dt.timezone.utc)
    return {"YEAR": dt.year, "MONTH": dt.month, "DAY": dt.day,
            "HOUR": dt.hour, "MINUTE": dt.minute}[part]
