"""Autoscaling: capacity policies + the required-capacity calculation.

Reference: ``x-pack/plugin/autoscaling/`` — policies name a set of node
roles and a bag of deciders (``AutoscalingDeciderService`` impls); the
``GET /_autoscaling/capacity`` endpoint runs every policy's deciders
against current cluster state and reports the required capacity
(per-node floor + total) so an external operator can resize the
cluster.  Deciders implemented against live state:

* ``fixed`` (``FixedAutoscalingDeciderService``): operator-pinned
  storage/memory/processors × nodes.
* ``reactive_storage`` (``ReactiveStorageDeciderService``): required
  total storage = current data-set bytes × a headroom factor, so the
  answer grows as indices grow.

The service is deliberately side-effect free — like the reference, it
REPORTS capacity; it never resizes anything itself.
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional

from ..common.errors import (IllegalArgumentError,
                             ResourceNotFoundError)

_KNOWN_DECIDERS = {"fixed", "reactive_storage", "proactive_storage"}

_UNITS = {"b": 1, "kb": 1 << 10, "mb": 1 << 20, "gb": 1 << 30,
          "tb": 1 << 40}


def _bytes_of(v) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    m = re.fullmatch(r"([\d.]+)\s*(b|kb|mb|gb|tb)?", str(v).lower())
    if m is None:
        raise IllegalArgumentError(
            f"failed to parse [{v}] as a byte size")
    try:
        return int(float(m.group(1)) * _UNITS[m.group(2) or "b"])
    except ValueError:
        raise IllegalArgumentError(
            f"failed to parse [{v}] as a byte size")


class AutoscalingService:
    """``store_bytes() -> int`` samples the node's current total store
    size through the stats surface."""

    STORAGE_HEADROOM = 1.25      # reactive decider's growth allowance

    def __init__(self, store_bytes: Callable[[], int],
                 node_count: Callable[[], int] = lambda: 1):
        self.store_bytes = store_bytes
        self.node_count = node_count
        self.policies: Dict[str, dict] = {}

    # -- policy CRUD -----------------------------------------------------
    def put_policy(self, name: str, body: dict) -> dict:
        if not re.fullmatch(r"[a-z][a-z0-9_-]*", name):
            raise IllegalArgumentError(
                f"name must match [a-z][a-z0-9_-]*, but was [{name}]")
        roles = body.get("roles")
        if roles is None:
            raise IllegalArgumentError("[roles] is required")
        if not isinstance(roles, list) or \
                not all(isinstance(r, str) for r in roles):
            raise IllegalArgumentError(
                "[roles] must be an array of strings")
        deciders = body.get("deciders") or {}
        unknown = set(deciders) - _KNOWN_DECIDERS
        if unknown:
            raise IllegalArgumentError(
                f"unknown decider{'s' if len(unknown) > 1 else ''} "
                f"{sorted(unknown)}")
        self.policies[name] = {"roles": sorted(roles),
                               "deciders": deciders}
        return {"acknowledged": True}

    def get_policy(self, name: str) -> dict:
        p = self.policies.get(name)
        if p is None:
            raise ResourceNotFoundError(
                f"autoscaling policy with name [{name}] does not exist")
        return {"policy": p}

    def delete_policy(self, name: str) -> dict:
        """Wildcard deletes allowed, like the reference."""
        if "*" in name:
            import fnmatch
            hits = [n for n in self.policies
                    if fnmatch.fnmatchcase(n, name)]
            for n in hits:
                del self.policies[n]
            return {"acknowledged": True}
        if name not in self.policies:
            raise ResourceNotFoundError(
                f"autoscaling policy with name [{name}] does not exist")
        del self.policies[name]
        return {"acknowledged": True}

    # -- capacity --------------------------------------------------------
    def capacity(self) -> dict:
        out = {}
        # one stats sweep per request: every decider and the
        # current-capacity block see the same sample
        current_bytes = self.store_bytes()
        for name, p in sorted(self.policies.items()):
            per_decider = {}
            node_storage = node_memory = 0
            total_storage = total_memory = 0
            for decider, cfg in sorted((p["deciders"] or {}).items()):
                cfg = cfg or {}
                if decider == "fixed":
                    nodes = int(cfg.get("nodes", 1) or 1)
                    d_storage = _bytes_of(cfg.get("storage", 0) or 0)
                    d_memory = _bytes_of(cfg.get("memory", 0) or 0)
                    req = {"node": {"storage": d_storage,
                                    "memory": d_memory},
                           "total": {"storage": d_storage * nodes,
                                     "memory": d_memory * nodes}}
                elif decider in ("reactive_storage",
                                 "proactive_storage"):
                    current = current_bytes
                    factor = self.STORAGE_HEADROOM
                    if decider == "proactive_storage":
                        # forecast window adds further headroom
                        factor *= 1.25
                    need = int(current * factor)
                    nodes = max(1, self.node_count())
                    req = {"node": {"storage": need // nodes,
                                    "memory": 0},
                           "total": {"storage": need, "memory": 0}}
                else:     # validated at put; defensive
                    continue
                per_decider[decider] = {"required_capacity": req,
                                        "reason_summary": ""}
                node_storage = max(node_storage,
                                   req["node"]["storage"])
                node_memory = max(node_memory, req["node"]["memory"])
                total_storage = max(total_storage,
                                    req["total"]["storage"])
                total_memory = max(total_memory,
                                   req["total"]["memory"])
            out[name] = {
                "required_capacity": {
                    "node": {"storage": node_storage,
                             "memory": node_memory},
                    "total": {"storage": total_storage,
                              "memory": total_memory}},
                "current_capacity": {
                    "node": {"storage": current_bytes, "memory": 0},
                    "total": {"storage": current_bytes, "memory": 0}},
                "current_nodes": [],
                "deciders": per_decider}
        return {"policies": out}
