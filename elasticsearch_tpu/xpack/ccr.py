"""CCR: cross-cluster replication — followers replay the leader's
sequence-numbered operation history.

Reference: ``x-pack/plugin/ccr/.../ShardFollowNodeTask.java:64`` — the
follower task polls the leader's ``shard_changes`` action (ops from a
seq-no, served from translog/Lucene history) and replays batches on the
follower shard, tracking per-shard checkpoints; ``AutoFollowCoordinator``
watches remote cluster state for new leader indices matching patterns.

Here the leader surface is ``GET /{index}/_ccr/shard_changes`` (REST,
because remote clusters speak ``rest:exec`` — same wire the reference's
dedicated transport action rides), reading each shard's retained translog
ops. The follower replays ops through its local write path per poll
round; polling is driven by ``POST /_ccr/_tick`` (injectable clock, the
same explicit-trigger stance as the ILM/watcher ticks) and is drained
once inline when a follow starts. Checkpoints are per leader shard.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..common.errors import (ElasticsearchError, IllegalArgumentError,
                             ResourceAlreadyExistsError,
                             ResourceNotFoundError)


class CcrService:
    #: ops fetched per shard per poll round (the reference's
    #: max_read_request_operation_count default is 5120)
    BATCH = 5120

    def __init__(self, api):
        self.api = api                   # RestAPI (for local writes)
        self.followers: Dict[str, dict] = {}
        self.auto_patterns: Dict[str, dict] = {}

    # -- leader side ----------------------------------------------------
    def shard_changes(self, index: str, shard: int, from_seq_no: int,
                      max_ops: int) -> dict:
        names = self.api.indices.resolve(index)
        svc = self.api.indices.indices[names[0]]
        if shard >= len(svc.shards):
            raise IllegalArgumentError(
                f"no such shard [{shard}] in [{index}]")
        eng = svc.shards[shard]
        ops = eng.translog.read_ops(from_seq_no=from_seq_no)[: max_ops]
        return {
            "index": names[0], "shard": shard,
            "max_seq_no": int(eng.tracker.max_seq_no),
            "operations": [op.to_dict() for op in ops],
        }

    # -- follower side --------------------------------------------------
    def follow(self, follower_index: str, body: dict) -> dict:
        remote = body.get("remote_cluster")
        leader = body.get("leader_index")
        if not remote or not leader:
            raise IllegalArgumentError(
                "[remote_cluster] and [leader_index] are required")
        if follower_index in self.followers:
            raise ResourceAlreadyExistsError(
                f"follower [{follower_index}] already exists")
        client = self.api.remotes.client(remote)
        # bootstrap: create the follower with the leader's mappings
        st, _ct, out = client.exec("GET", f"/{leader}/_mapping", "", b"")
        import json as _json
        if st >= 400:
            raise ElasticsearchError(
                f"cannot read leader index [{leader}] on [{remote}]")
        mappings = next(iter(_json.loads(out).values()))["mappings"]
        st2, _ct2, out2 = client.exec("GET", f"/{leader}/_settings", "",
                                      b"")
        shards = 1
        if st2 < 400:
            st_doc = next(iter(_json.loads(out2).values()))
            shards = int(((st_doc.get("settings") or {}).get("index")
                          or {}).get("number_of_shards", 1))
        self._internal(
            "PUT", f"/{follower_index}",
            {"mappings": mappings,
             "settings": {"index": {"number_of_shards": shards}}})
        self.followers[follower_index] = {
            "remote_cluster": remote, "leader_index": leader,
            "status": "active",
            "checkpoints": {},           # leader shard -> next seq_no
            "stats": {"operations_read": 0, "operations_written": 0,
                      "failed_read_requests": 0, "poll_count": 0},
        }
        self.poll_one(follower_index)    # inline first drain
        return {"follow_index_created": True,
                "follow_index_shards_acked": True,
                "index_following_started": True}

    def pause(self, follower_index: str) -> dict:
        f = self._follower(follower_index)
        f["status"] = "paused"
        return {"acknowledged": True}

    def resume(self, follower_index: str) -> dict:
        f = self._follower(follower_index)
        f["status"] = "active"
        self.poll_one(follower_index)
        return {"acknowledged": True}

    def unfollow(self, follower_index: str) -> dict:
        f = self._follower(follower_index)
        if f["status"] != "paused":
            raise ElasticsearchError(
                f"cannot convert the follower index [{follower_index}] "
                f"to a non-follower, because it has not been paused")
        del self.followers[follower_index]
        return {"acknowledged": True}

    def stats(self) -> dict:
        return {"follow_stats": {"indices": [
            {"index": name,
             "shards": [{"shard_id": int(s),
                         "leader_index": f["leader_index"],
                         "remote_cluster": f["remote_cluster"],
                         "follower_global_checkpoint": cp - 1,
                         "operations_read":
                             f["stats"]["operations_read"]}
                        for s, cp in sorted(
                            f["checkpoints"].items())] or
             [{"shard_id": 0, "leader_index": f["leader_index"],
               "remote_cluster": f["remote_cluster"],
               "follower_global_checkpoint": -1,
               "operations_read": 0}]}
            for name, f in sorted(self.followers.items())]},
            "auto_follow_stats": {
                "number_of_successful_follow_indices":
                    len(self.followers)}}

    def _follower(self, name: str) -> dict:
        f = self.followers.get(name)
        if f is None:
            raise ResourceNotFoundError(
                f"follower index [{name}] does not exist")
        return f

    # -- polling --------------------------------------------------------
    def poll_one(self, follower_index: str) -> int:
        """One poll round: fetch + replay new leader ops; returns the
        number of ops applied."""
        import json as _json
        f = self._follower(follower_index)
        if f["status"] != "active":
            return 0
        client = self.api.remotes.client(f["remote_cluster"])
        f["stats"]["poll_count"] += 1
        applied = 0
        shard = 0
        while True:
            cp = f["checkpoints"].get(str(shard), 0)
            st, _ct, out = client.exec(
                "GET",
                f"/{f['leader_index']}/_ccr/shard_changes",
                f"shard={shard}&from_seq_no={cp}&max_ops={self.BATCH}",
                b"")
            if st == 400 and shard > 0:
                break                    # past the last leader shard
            if st >= 400:
                f["stats"]["failed_read_requests"] += 1
                break
            doc = _json.loads(out)
            ops = doc.get("operations", [])
            f["stats"]["operations_read"] += len(ops)
            next_cp = cp
            for op in ops:
                self._apply(follower_index, op)
                applied += 1
                f["stats"]["operations_written"] += 1
                next_cp = max(next_cp, int(op["seq_no"]) + 1)
            f["checkpoints"][str(shard)] = next_cp
            shard += 1
            # probe the next shard; shard_changes 400s past the end
            if shard > 64:
                break
        if applied:
            self._internal("POST", f"/{follower_index}/_refresh", None)
        return applied

    def tick(self) -> dict:
        polled = {}
        for name in list(self.followers):
            try:
                polled[name] = self.poll_one(name)
            except ElasticsearchError as e:
                polled[name] = f"error: {e}"
        created = self._auto_follow()
        return {"polled": polled, "auto_followed": created}

    def _apply(self, follower_index: str, op: dict) -> None:
        kind = op.get("op")
        if kind == "index":
            q = f"routing={op['routing']}" if op.get("routing") else ""
            self._internal("PUT",
                           f"/{follower_index}/_doc/{op['id']}",
                           op.get("source") or {}, query=q)
        elif kind == "delete":
            try:
                self._internal("DELETE",
                               f"/{follower_index}/_doc/{op['id']}", None)
            except ElasticsearchError:
                pass                     # already absent on the follower
        # no_op: checkpoint advances only

    def _internal(self, method: str, path: str, body, query: str = ""):
        import json as _json
        payload = b"" if body is None else _json.dumps(body).encode()
        prev = getattr(self.api._internal_tls, "active", False)
        self.api._internal_tls.active = True
        try:
            st, _ct, out = self.api.handle(method, path, query, payload)
        finally:
            self.api._internal_tls.active = prev
        if st >= 400:
            doc = _json.loads(out)
            err = (doc.get("error") or {})
            reason = err.get("reason") if isinstance(err, dict) else err
            e = ElasticsearchError(str(reason))
            e.status = st
            raise e
        return out

    # -- auto-follow ----------------------------------------------------
    def put_auto_follow(self, name: str, body: dict) -> dict:
        if not body.get("remote_cluster") or \
                not body.get("leader_index_patterns"):
            raise IllegalArgumentError(
                "[remote_cluster] and [leader_index_patterns] are "
                "required")
        self.auto_patterns[name] = {
            "remote_cluster": body["remote_cluster"],
            "leader_index_patterns": body["leader_index_patterns"],
            "follow_index_pattern": body.get("follow_index_pattern",
                                             "{{leader_index}}"),
        }
        return {"acknowledged": True}

    def get_auto_follow(self, name: Optional[str]) -> dict:
        if name is None:
            items = sorted(self.auto_patterns.items())
        else:
            if name not in self.auto_patterns:
                raise ResourceNotFoundError(
                    f"auto-follow pattern [{name}] is missing")
            items = [(name, self.auto_patterns[name])]
        return {"patterns": [{"name": n, "pattern": p}
                             for n, p in items]}

    def delete_auto_follow(self, name: str) -> dict:
        if self.auto_patterns.pop(name, None) is None:
            raise ResourceNotFoundError(
                f"auto-follow pattern [{name}] is missing")
        return {"acknowledged": True}

    def _auto_follow(self) -> List[str]:
        import fnmatch
        import json as _json
        created = []
        for pname, p in self.auto_patterns.items():
            try:
                client = self.api.remotes.client(p["remote_cluster"])
                st, _ct, out = client.exec("GET", "/_cat/indices",
                                           "format=json", b"")
                if st >= 400:
                    continue
                remote_indices = [row["index"]
                                  for row in _json.loads(out)]
            except ElasticsearchError:
                continue
            for li in remote_indices:
                if not any(fnmatch.fnmatch(li, pat)
                           for pat in p["leader_index_patterns"]):
                    continue
                follow_name = p["follow_index_pattern"].replace(
                    "{{leader_index}}", li)
                if follow_name in self.followers:
                    continue
                try:
                    self.follow(follow_name, {
                        "remote_cluster": p["remote_cluster"],
                        "leader_index": li})
                    created.append(follow_name)
                except ElasticsearchError:
                    continue
        return created
