"""Watcher: trigger → input → condition → actions alerting.

Reference: ``x-pack/plugin/watcher/`` — ``ExecutionService.java`` runs
each watch through input (search/simple/chain), condition (compare/
script/always/never), throttling, and actions (index/logging/webhook/
email). Here the same pipeline executes synchronously: on the manual
``_execute`` API and on the injectable-clock ``_tick`` (the schedule
trigger evaluated the same way the ILM service ticks), with the search
input riding the shared search seam and the index action the bulk seam.
Execution records land in an in-memory ring (queryable via stats) — the
reference's ``.watcher-history`` index reduced to its observable core.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..common.errors import (IllegalArgumentError, ResourceNotFoundError)


def _parse_interval_ms(s: Any) -> float:
    """Schedule intervals: bare numbers mean SECONDS (the reference's
    IntervalSchedule default unit); unit strings ride the shared parser."""
    if isinstance(s, (int, float)) and not isinstance(s, bool):
        return float(s) * 1e3
    from ..common.settings import parse_time_millis
    txt = str(s).strip()
    if txt.isdigit():
        return float(txt) * 1e3
    return parse_time_millis(txt)


def _path_get(obj: Any, path: str) -> Any:
    cur = obj
    for part in path.split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif isinstance(cur, list) and part.isdigit():
            i = int(part)
            cur = cur[i] if i < len(cur) else None
        else:
            return None
    return cur


class WatcherService:
    HISTORY_CAP = 1000

    def __init__(self, search_fn, bulk_fn):
        self.search_fn = search_fn
        self.bulk_fn = bulk_fn
        self.watches: Dict[str, dict] = {}
        self.history: List[dict] = []

    # -- CRUD -----------------------------------------------------------
    def put(self, wid: str, body: dict, active: bool = True) -> dict:
        if "trigger" not in body or "actions" not in body:
            raise IllegalArgumentError(
                "a watch requires [trigger] and [actions]")
        sched = (body.get("trigger") or {}).get("schedule") or {}
        if "interval" in sched:
            _parse_interval_ms(sched["interval"])   # reject bad units now
        created = wid not in self.watches
        self.watches[wid] = {
            "watch": body, "active": active,
            "last_run_ms": None,
            "status": {"state": {"active": active},
                       "actions": {}, "execution_state": None},
        }
        return {"_id": wid, "created": created,
                "_version": 1, "_seq_no": 0, "_primary_term": 1}

    def get(self, wid: str) -> dict:
        w = self.watches.get(wid)
        if w is None:
            raise ResourceNotFoundError(wid)
        return {"found": True, "_id": wid, "watch": w["watch"],
                "status": w["status"]}

    def delete(self, wid: str) -> dict:
        if self.watches.pop(wid, None) is None:
            raise ResourceNotFoundError(wid)
        return {"found": True, "_id": wid}

    def activate(self, wid: str, active: bool) -> dict:
        w = self.watches.get(wid)
        if w is None:
            raise ResourceNotFoundError(wid)
        w["active"] = active
        w["status"]["state"]["active"] = active
        return {"status": w["status"]}

    def stats(self) -> dict:
        return {"watcher_state": "started",
                "watch_count": len(self.watches),
                "execution_thread_pool": {"queue_size": 0,
                                          "max_size": 1}}

    # -- execution ------------------------------------------------------
    def execute(self, wid: str, payload: Optional[dict] = None) -> dict:
        w = self.watches.get(wid)
        if w is None:
            raise ResourceNotFoundError(wid)
        record = self._run(wid, w, alternative_input=(
            (payload or {}).get("alternative_input")))
        return {"_id": f"{wid}_{len(self.history)}",
                "watch_record": record}

    def tick(self, now_ms: Optional[int] = None) -> dict:
        """Evaluate schedule triggers; run due watches (injectable clock,
        same pattern as the ILM tick)."""
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        ran = []
        for wid, w in self.watches.items():
            if not w["active"]:
                continue
            sched = (w["watch"].get("trigger") or {}).get("schedule") or {}
            if "interval" in sched:
                iv = _parse_interval_ms(sched["interval"])
                last = w["last_run_ms"]
                if last is None or now - last >= iv:
                    self._run(wid, w, now_ms=now)
                    ran.append(wid)
        return {"ran": ran, "now_ms": now}

    def _run(self, wid: str, w: dict, now_ms: Optional[int] = None,
             alternative_input: Optional[dict] = None) -> dict:
        watch = w["watch"]
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        w["last_run_ms"] = now
        record: dict = {"watch_id": wid, "state": "executed",
                        "trigger_event": {"triggered_time": now},
                        "result": {}}
        # input
        payload: dict = {}
        inp = watch.get("input") or {"none": {}}
        try:
            if alternative_input is not None:
                payload = alternative_input
            elif "simple" in inp:
                payload = dict(inp["simple"])
            elif "search" in inp:
                req = inp["search"].get("request") or {}
                indices = req.get("indices") or ["*"]
                body = req.get("body") or {}
                payload = self.search_fn(",".join(indices), body)
            record["result"]["input"] = {"status": "success",
                                         "payload": payload}
        except Exception as e:   # noqa: BLE001 — recorded, not raised
            record["state"] = "failed"
            record["result"]["input"] = {"status": "failure",
                                         "reason": str(e)}
            self._record(record)
            return record
        # condition
        met = self._condition_met(watch.get("condition"), payload)
        record["result"]["condition"] = {
            "status": "success", "met": met,
            "type": next(iter(watch.get("condition") or {"always": {}}))}
        if not met:
            record["state"] = "execution_not_needed"
            self._record(record)
            return record
        # actions
        actions_out = []
        for aname, aspec in (watch.get("actions") or {}).items():
            out = {"id": aname, "status": "success"}
            try:
                if "logging" in aspec:
                    out["type"] = "logging"
                    out["logging"] = {"logged_text": self._render(
                        aspec["logging"].get("text", ""), payload)}
                elif "index" in aspec:
                    out["type"] = "index"
                    target = aspec["index"].get("index")
                    if not target:
                        raise IllegalArgumentError(
                            "[index] action requires [index]")
                    doc = {"watch_id": wid, "payload": payload,
                           "triggered_time": now}
                    self.bulk_fn(target, [
                        {"index": {"_index": target}}, doc])
                    out["index"] = {"response": {"index": target}}
                else:
                    out["status"] = "failure"
                    out["reason"] = (
                        f"unsupported action type in [{aname}]")
            except Exception as e:   # noqa: BLE001
                out["status"] = "failure"
                out["reason"] = str(e)
            actions_out.append(out)
        record["result"]["actions"] = actions_out
        w["status"]["actions"] = {
            a["id"]: {"last_execution": {
                "successful": a["status"] == "success"}}
            for a in actions_out}
        self._record(record)
        return record

    def _condition_met(self, cond: Optional[dict], payload: dict) -> bool:
        if not cond or "always" in cond:
            return True
        if "never" in cond:
            return False
        if "compare" in cond:
            for path, check in cond["compare"].items():
                val = _path_get({"ctx": {"payload": payload}}, path)
                for op, ref in check.items():
                    ops = {"eq": lambda a, b: a == b,
                           "not_eq": lambda a, b: a != b,
                           "gt": lambda a, b: a is not None and a > b,
                           "gte": lambda a, b: a is not None and a >= b,
                           "lt": lambda a, b: a is not None and a < b,
                           "lte": lambda a, b: a is not None and a <= b}
                    fn = ops.get(op)
                    if fn is None:
                        raise IllegalArgumentError(
                            f"unknown compare operator [{op}]")
                    if not fn(val, ref):
                        return False
            return True
        raise IllegalArgumentError(
            f"unsupported condition type [{next(iter(cond))}]")

    @staticmethod
    def _render(text: str, payload: dict) -> str:
        """{{ctx.payload.x}} substitution (mustache-lite, same dialect as
        the ingest layer's templates)."""
        import re as _re

        def sub(m):
            v = _path_get({"ctx": {"payload": payload}},
                          m.group(1).strip())
            return "" if v is None else str(v)
        return _re.sub(r"\{\{([^}]+)\}\}", sub, text)

    def _record(self, record: dict) -> None:
        self.history.append(record)
        if len(self.history) > self.HISTORY_CAP:
            del self.history[: len(self.history) - self.HISTORY_CAP]
