"""Enrich: lookup-join policies + the ``enrich`` ingest processor.

Reference: ``x-pack/plugin/enrich/`` — ``EnrichPolicyRunner.java`` builds
a hidden ``.enrich-*`` lookup index on ``_execute``; the
``MatchProcessor`` then term-joins incoming docs against it inside ingest
pipelines. Here ``_execute`` drains the source through the search seam
into an in-process hash table keyed on the match field (the observable
core of the hidden index: exact-match lookup with ``max_matches``), and
the processor registers through the same ingest SPI hook every other
processor uses. The table registry is process-global, mirroring the
ingest registry itself (policies are cluster state in the reference;
the cluster tier re-executes policies per node the same way pipelines
replicate)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..common.errors import (IllegalArgumentError,
                             ResourceAlreadyExistsError,
                             ResourceNotFoundError)
from ..ingest.pipeline import Processor, ProcessorException, _req, \
    register_processor

#: policy name → {"match_field", "lookup": {value: [enrich-doc, ...]}}
_ENRICH_LOOKUPS: Dict[str, dict] = {}


class EnrichService:
    MAX_DOCS = 100_000

    def __init__(self, search_fn):
        self.search_fn = search_fn
        self.policies: Dict[str, dict] = {}

    def put_policy(self, name: str, body: dict) -> dict:
        if name in self.policies:
            raise ResourceAlreadyExistsError(
                f"policy [{name}] already exists")
        ptype = next(iter(body), None)
        if ptype not in ("match", "range"):
            # geo_match needs shape containment, which the lookup table
            # design doesn't carry — reject at put rather than silently
            # degrade to exact matching
            raise IllegalArgumentError(
                f"unsupported policy type [{ptype}], supported types "
                f"are [match, range]")
        spec = body[ptype]
        for req_key in ("indices", "match_field", "enrich_fields"):
            if req_key not in spec:
                raise IllegalArgumentError(f"[{req_key}] is required")
        self.policies[name] = {"type": ptype, "spec": spec}
        return {"acknowledged": True}

    def get_policy(self, name: Optional[str]) -> dict:
        if name in (None, "_all", "*"):
            items = sorted(self.policies.items())
        else:
            if name not in self.policies:
                raise ResourceNotFoundError(
                    f"policy [{name}] not found")
            items = [(name, self.policies[name])]
        return {"policies": [
            {"config": {p["type"]: dict(p["spec"], name=n)}}
            for n, p in items]}

    def delete_policy(self, name: str) -> dict:
        if self.policies.pop(name, None) is None:
            raise ResourceNotFoundError(f"policy [{name}] not found")
        _ENRICH_LOOKUPS.pop(name, None)
        return {"acknowledged": True}

    def execute_policy(self, name: str) -> dict:
        p = self.policies.get(name)
        if p is None:
            raise ResourceNotFoundError(f"policy [{name}] not found")
        spec = p["spec"]
        indices = spec["indices"]
        if isinstance(indices, list):
            indices = ",".join(indices)
        match_field = spec["match_field"]
        enrich_fields = spec["enrich_fields"]
        lookup: Dict[Any, List[dict]] = {}
        intervals: List[tuple] = []      # (lo, hi, doc) for range policies
        is_range = p["type"] == "range"
        search_after = None
        while True:
            body: dict = {"size": 1000,
                          "sort": [{"_doc": {"order": "asc"}}],
                          "query": spec.get("query") or {"match_all": {}}}
            if search_after is not None:
                body["search_after"] = search_after
            resp = self.search_fn(indices, body)
            hits = resp["hits"]["hits"]
            for h in hits:
                src = h.get("_source") or {}
                key = src.get(match_field)
                if key is None:
                    continue
                doc = {f: src[f] for f in enrich_fields if f in src}
                doc[match_field] = key
                if is_range:
                    iv = _as_interval(key)
                    if iv is not None:
                        intervals.append((iv[0], iv[1], doc))
                    continue
                keys = key if isinstance(key, list) else [key]
                for k in keys:
                    lookup.setdefault(k, []).append(doc)
            if len(hits) < 1000 or sum(
                    len(v) for v in lookup.values()) + \
                    len(intervals) >= self.MAX_DOCS:
                break
            search_after = hits[-1]["sort"]
        _ENRICH_LOOKUPS[name] = {"match_field": match_field,
                                 "lookup": lookup,
                                 "intervals": intervals if is_range
                                 else None}
        return {"status": {"phase": "COMPLETE"}}


class EnrichProcessor(Processor):
    """``enrich`` ingest processor (``MatchProcessor.java``)."""

    type_name = "enrich"

    def __init__(self, body):
        super().__init__(body)
        self.policy_name = _req(body, "policy_name", "enrich")
        self.field = _req(body, "field", "enrich")
        self.target_field = _req(body, "target_field", "enrich")
        self.max_matches = int(body.get("max_matches", 1))
        self.override = body.get("override", True)
        if not (1 <= self.max_matches <= 128):
            raise ProcessorException(
                "[max_matches] should be between 1 and 128")

    def run(self, doc):
        table = _ENRICH_LOOKUPS.get(self.policy_name)
        if table is None:
            raise ProcessorException(
                f"no enrich index exists for policy with name "
                f"[{self.policy_name}]")
        key = doc.get(self.field)
        if key is None:
            return
        if not self.override and doc.get(self.target_field) is not None:
            return
        if table.get("intervals") is not None:
            # range policy: containment scan over stored intervals
            probe = _as_point(key)
            matches = [d for lo, hi, d in table["intervals"]
                       if probe is not None and lo <= probe <= hi][
                           : self.max_matches]
        else:
            matches = table["lookup"].get(key, [])[: self.max_matches]
        if not matches:
            return
        doc.set(self.target_field,
                matches[0] if self.max_matches == 1 else matches)


def _as_interval(value):
    """A range-policy match value → (lo, hi): {gte,lte} dicts, CIDR
    strings, or [lo, hi] pairs (EnrichPolicyRunner's range field
    semantics reduced to closed numeric/IP intervals)."""
    if isinstance(value, dict):
        lo = value.get("gte", value.get("gt"))
        hi = value.get("lte", value.get("lt"))
        lo_p, hi_p = _as_point(lo), _as_point(hi)
        if lo_p is None or hi_p is None:
            return None
        return lo_p, hi_p
    if isinstance(value, str) and "/" in value:
        import ipaddress
        try:
            net = ipaddress.ip_network(value, strict=False)
        except ValueError:
            return None
        return float(int(net.network_address)), \
            float(int(net.broadcast_address))
    if isinstance(value, (list, tuple)) and len(value) == 2:
        lo_p, hi_p = _as_point(value[0]), _as_point(value[1])
        if lo_p is None or hi_p is None:
            return None
        return lo_p, hi_p
    return None


def _as_point(value):
    if isinstance(value, bool) or value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    try:
        import ipaddress
        return float(int(ipaddress.ip_address(str(value))))
    except ValueError:
        try:
            return float(value)
        except (TypeError, ValueError):
            return None


register_processor(EnrichProcessor)
