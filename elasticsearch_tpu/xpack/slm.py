"""Snapshot lifecycle management: scheduled snapshots + retention.

Reference: ``x-pack/plugin/core/src/main/java/org/elasticsearch/xpack/
core/slm/`` + ``x-pack/plugin/ilm/.../slm/SnapshotLifecycleService.java``
— policies carry a cron schedule, a name pattern, a repository, snapshot
config, and a retention block; a scheduler triggers snapshot creation
and a periodic retention task deletes expired snapshots.

Same collapse as ILM/watcher here: scheduling rides an injectable
``tick(now_ms)`` instead of a background thread, so tests (and the
cluster tier, which ticks all services together) drive time explicitly.
Snapshot naming resolves ``<date-math>`` headers the way
``IndexNameExpressionResolver`` does for date-math index names.
"""
from __future__ import annotations

import re
import time
from typing import Any, Callable, Dict, List, Optional

from ..common.errors import (IllegalArgumentError,
                             ResourceNotFoundError)


def _now_ms() -> int:
    return int(time.time() * 1000)


def _duration_ms(v: Any) -> int:
    s = str(v).strip().lower()
    units = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
             "d": 86_400_000}
    for suffix in ("ms", "s", "m", "h", "d"):
        if s.endswith(suffix):
            num = s[: -len(suffix)]
            try:
                return int(float(num) * units[suffix])
            except ValueError:
                break
    raise IllegalArgumentError(
        f"failed to parse [{v}] as a time value")


def _interval_of_schedule(schedule: str) -> int:
    """Interval in ms from a cron-ish schedule.

    The reference uses full Quartz cron (``slm/SnapshotLifecyclePolicy``);
    here the supported shapes are the common periodic ones: a plain
    interval (``30m``/``1d``) or the daily/hourly cron forms
    (``0 30 1 * * ?`` → daily, ``0 */N * * * ?`` → every N minutes).
    """
    schedule = schedule.strip()
    try:
        return _duration_ms(schedule)
    except IllegalArgumentError:
        pass
    parts = schedule.split()
    if len(parts) in (6, 7):
        m = re.fullmatch(r"\*/(\d+)", parts[1])
        if m:
            return int(m.group(1)) * 60_000
        m = re.fullmatch(r"\*/(\d+)", parts[2])
        if m:
            return int(m.group(1)) * 3_600_000
        if parts[3] in ("*", "?") and parts[1].isdigit():
            return 86_400_000 if parts[2].isdigit() else 3_600_000
        return 86_400_000
    raise IllegalArgumentError(
        f"invalid schedule [{schedule}]: must be a time value or cron "
        f"expression")


class SlmService:
    """``create_snapshot(repo, name, config) -> info``,
    ``delete_snapshot(repo, name)``, ``list_snapshots(repo) -> [info]``
    are bound to the snapshot layer through the REST seam."""

    def __init__(self,
                 create_snapshot: Callable[[str, str, dict], dict],
                 delete_snapshot: Callable[[str, str], None],
                 list_snapshots: Callable[[str], List[dict]]):
        self.create_snapshot = create_snapshot
        self.delete_snapshot = delete_snapshot
        self.list_snapshots = list_snapshots
        self.policies: Dict[str, dict] = {}
        self.running = True
        self.stats = {"retention_runs": 0, "retention_deleted": 0,
                      "retention_failed": 0,
                      "total_snapshots_taken": 0,
                      "total_snapshots_failed": 0,
                      "total_snapshots_deleted": 0}

    # -- policy CRUD -----------------------------------------------------
    def put_policy(self, pid: str, body: dict) -> dict:
        for req in ("schedule", "name", "repository"):
            if not body.get(req):
                raise IllegalArgumentError(f"[{req}] is required")
        _interval_of_schedule(body["schedule"])  # validate
        if not str(body["name"]).startswith("<") and \
                not re.fullmatch(r"[a-z0-9._-]+", str(body["name"])):
            raise IllegalArgumentError(
                f"invalid snapshot name [{body['name']}]")
        existing = self.policies.get(pid)
        self.policies[pid] = {
            "policy": dict(body),
            "version": (existing["version"] + 1) if existing else 1,
            "modified_date_millis": _now_ms(),
            "last_success": existing.get("last_success")
            if existing else None,
            "last_failure": existing.get("last_failure")
            if existing else None,
            "next_due": None,        # resolved lazily on first tick
        }
        return {"acknowledged": True}

    def get_policies(self, pid: Optional[str]) -> dict:
        if pid in (None, "", "*", "_all"):
            ids = sorted(self.policies)
        else:
            missing = [p for p in pid.split(",")
                       if p not in self.policies]
            if missing:
                raise ResourceNotFoundError(
                    f"snapshot lifecycle policy or policies "
                    f"{missing} not found")
            ids = pid.split(",")
        out = {}
        for i in ids:
            p = self.policies[i]
            entry = {"version": p["version"],
                     "modified_date_millis": p["modified_date_millis"],
                     "policy": p["policy"],
                     "stats": {"policy": i,
                               "snapshots_taken":
                                   p.get("snapshots_taken", 0),
                               "snapshots_failed":
                                   p.get("snapshots_failed", 0),
                               "snapshots_deleted":
                                   p.get("snapshots_deleted", 0)}}
            if p["last_success"]:
                entry["last_success"] = p["last_success"]
            if p["last_failure"]:
                entry["last_failure"] = p["last_failure"]
            out[i] = entry
        return out

    def delete_policy(self, pid: str) -> dict:
        if pid not in self.policies:
            raise ResourceNotFoundError(
                f"snapshot lifecycle policy or policies [{pid}] not "
                f"found")
        del self.policies[pid]
        return {"acknowledged": True}

    # -- execution -------------------------------------------------------
    def _resolve_name(self, pattern: str, now_ms: int) -> str:
        """``<name-{date}>`` date-math headers → concrete names, plus a
        uniquifying suffix like ``SnapshotLifecycleTask`` appends."""
        name = pattern
        if name.startswith("<") and name.endswith(">"):
            name = name[1:-1]
            tm = time.gmtime(now_ms / 1000)

            def sub(m):
                fmt = m.group(1)
                fmt = (fmt.replace("yyyy", "%Y").replace("MM", "%m")
                       .replace("dd", "%d").replace("HH", "%H"))
                return time.strftime(fmt, tm)
            name = re.sub(r"\{([^}]+)\}", sub, name)
        return f"{name}-{now_ms % 1_000_000:06d}"

    def execute_policy(self, pid: str,
                       now_ms: Optional[int] = None) -> dict:
        p = self.policies.get(pid)
        if p is None:
            raise ResourceNotFoundError(
                f"snapshot lifecycle policy or policies [{pid}] not "
                f"found")
        now = now_ms if now_ms is not None else _now_ms()
        cfg = p["policy"]
        snap_name = self._resolve_name(cfg["name"], now)
        import copy
        config = copy.deepcopy(cfg.get("config") or {})
        config.setdefault("metadata", {})["policy"] = pid
        try:
            self.create_snapshot(cfg["repository"], snap_name, config)
        except Exception as e:   # noqa: BLE001 — recorded, not raised
            p["last_failure"] = {"snapshot_name": snap_name, "time": now,
                                 "details": str(e)}
            p["snapshots_failed"] = p.get("snapshots_failed", 0) + 1
            self.stats["total_snapshots_failed"] += 1
            raise
        p["last_success"] = {"snapshot_name": snap_name, "time": now}
        p["snapshots_taken"] = p.get("snapshots_taken", 0) + 1
        self.stats["total_snapshots_taken"] += 1
        return {"snapshot_name": snap_name}

    def execute_retention(self, now_ms: Optional[int] = None) -> dict:
        """Delete snapshots whose policy retention has expired
        (``SnapshotRetentionTask.java``): expire_after by age,
        min_count floor, max_count ceiling."""
        now = now_ms if now_ms is not None else _now_ms()
        self.stats["retention_runs"] += 1
        deleted = 0
        for pid, p in self.policies.items():
            ret = (p["policy"].get("retention") or {})
            if not ret:
                continue
            repo = p["policy"]["repository"]
            try:
                snaps = [s for s in self.list_snapshots(repo)
                         if (s.get("metadata") or {}).get(
                             "policy") == pid]
            except Exception:    # noqa: BLE001 — repo gone: skip policy
                continue
            snaps.sort(key=lambda s: s.get("start_time_in_millis", 0))
            expire_after = ret.get("expire_after")
            min_count = int(ret.get("min_count", 0) or 0)
            max_count = ret.get("max_count")
            to_delete: List[dict] = []
            if expire_after:
                ttl = _duration_ms(expire_after)
                expired = [s for s in snaps
                           if now - s.get("start_time_in_millis",
                                          now) > ttl]
                keep_floor = max(min_count, 0)
                # never delete below min_count, oldest expire first
                n_deletable = max(0, len(snaps) - keep_floor)
                to_delete.extend(expired[:n_deletable])
            if max_count is not None:
                overflow = len(snaps) - len(to_delete) - int(max_count)
                if overflow > 0:
                    remaining = [s for s in snaps if s not in to_delete]
                    to_delete.extend(remaining[:overflow])
            for s in to_delete:
                try:
                    self.delete_snapshot(repo, s["snapshot"])
                    deleted += 1
                    p["snapshots_deleted"] = \
                        p.get("snapshots_deleted", 0) + 1
                except Exception:  # noqa: BLE001
                    self.stats["retention_failed"] += 1
        self.stats["retention_deleted"] += deleted
        self.stats["total_snapshots_deleted"] += deleted
        return {"deleted": deleted}

    def tick(self, now_ms: Optional[int] = None) -> List[str]:
        """Run every policy whose schedule interval has elapsed."""
        if not self.running:
            return []
        now = now_ms if now_ms is not None else _now_ms()
        fired = []
        for pid, p in self.policies.items():
            interval = _interval_of_schedule(p["policy"]["schedule"])
            if p["next_due"] is None:
                p["next_due"] = now + interval
                continue
            if now >= p["next_due"]:
                p["next_due"] = now + interval
                try:
                    self.execute_policy(pid, now)
                    fired.append(pid)
                except Exception:   # noqa: BLE001 — recorded on policy
                    pass
        return fired

    # -- status ----------------------------------------------------------
    def status(self) -> dict:
        return {"operation_mode": "RUNNING" if self.running
                else "STOPPED"}

    def start(self) -> dict:
        self.running = True
        return {"acknowledged": True}

    def stop(self) -> dict:
        self.running = False
        return {"acknowledged": True}

    def get_stats(self) -> dict:
        per_policy = [{"policy": pid,
                       "snapshots_taken": p.get("snapshots_taken", 0),
                       "snapshots_failed": p.get("snapshots_failed", 0),
                       "snapshots_deleted": p.get("snapshots_deleted", 0)}
                      for pid, p in sorted(self.policies.items())]
        return {"retention_runs": self.stats["retention_runs"],
                "retention_deleted": self.stats["retention_deleted"],
                "retention_failed": self.stats["retention_failed"],
                "total_snapshots_taken":
                    self.stats["total_snapshots_taken"],
                "total_snapshots_failed":
                    self.stats["total_snapshots_failed"],
                "total_snapshots_deleted":
                    self.stats["total_snapshots_deleted"],
                "policy_stats": per_policy}
