"""Searchable snapshots: mount a snapshot as a read-only index.

Reference: ``x-pack/plugin/searchable-snapshots/`` —
``SearchableSnapshots.java:91`` registers an ``IndexStorePlugin`` +
``EnginePlugin`` whose Directory streams blobs from the repository; the
8.0 default storage mode (``full_copy``) prewarms a complete local copy
and serves all reads from local disk, with the repository as the
recovery source.  That default is exactly what this mount implements:
the shard files materialize from the content-addressed blob store into
the node's data path at mount time (bytes/files counted as the "cold"
fetch the stats API reports), the index carries
``index.store.type: snapshot`` + a write block, and deleting the
mounted index never touches the backing snapshot.  ``shared_cache``
mounts are accepted and served the same way (documented downgrade: the
partial-cache Directory needs byte-range blob reads the npz segment
format doesn't expose).
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from ..common.errors import (IllegalArgumentError,
                             ResourceNotFoundError)

def _registry(indices_service) -> Dict[str, dict]:
    """Mounted-index bookkeeping lives ON the owning node's
    IndicesService (index → {repository, snapshot, bytes, files,
    mounted_at_ms, storage}) so multi-node processes and test fixtures
    don't share mount state; IndicesService.delete_index clears entries
    for every deletion path (REST, ILM, resize)."""
    reg = getattr(indices_service, "_mounted_snapshots", None)
    if reg is None:
        reg = indices_service._mounted_snapshots = {}
    return reg


def mount(snapshots_service, repo_name: str, snapshot: str,
          body: dict, storage: str = "full_copy") -> dict:
    """``POST /_snapshot/{repo}/{snap}/_mount`` — restore-as-read-only
    (``TransportMountSearchableSnapshotAction.java``)."""
    index = body.get("index")
    if not index:
        raise IllegalArgumentError("[index] is required")
    if storage not in ("full_copy", "shared_cache"):
        raise IllegalArgumentError(
            f"unknown storage type [{storage}]")
    renamed = body.get("renamed_index") or index
    repo = snapshots_service.get_repository(repo_name)
    meta = repo.read_snapshot(snapshot)
    if index not in meta.get("indices", {}):
        raise ResourceNotFoundError(
            f"index [{index}] not found in snapshot "
            f"[{repo_name}:{snapshot}]")

    result = snapshots_service.restore(
        repo_name, snapshot, indices_expr=index,
        rename_pattern=f"^{index}$" if renamed != index else None,
        rename_replacement=renamed if renamed != index else None)

    svc = snapshots_service.indices.get(renamed)
    # apply the caller's setting overrides, then the mount markers
    overrides = dict(body.get("index_settings") or {})
    ignored = body.get("ignore_index_settings") or []
    for k in ignored:
        svc.settings.pop(k if k.startswith("index.")
                         else f"index.{k}", None)
    for k, v in overrides.items():
        svc.settings[k if k.startswith("index.")
                     else f"index.{k}"] = v
    svc.settings["index.store.type"] = "snapshot"
    svc.settings["index.store.snapshot.repository_name"] = repo_name
    svc.settings["index.store.snapshot.snapshot_name"] = snapshot
    svc.settings["index.store.snapshot.index_name"] = index
    # mounted indices are immutable (the reference adds a write block
    # at mount: MountSearchableSnapshotRequest)
    svc.settings["index.blocks.write"] = "true"
    info = getattr(svc, "recovery_info", {}) or {}
    svc.recovery_info = dict(info, type="SNAPSHOT")
    _registry(snapshots_service.indices)[renamed] = {
        "repository": repo_name, "snapshot": snapshot,
        "source_index": index, "storage": storage,
        "bytes": int(info.get("bytes", 0)),
        "files": int(info.get("files", 0)),
        "mounted_at_ms": int(time.time() * 1000)}
    return {"snapshot": {"snapshot": snapshot,
                         "indices": [renamed],
                         "shards": result["snapshot"]["shards"]}}


def forget(indices_service, index: str) -> None:
    """Index deleted — drop its mount bookkeeping."""
    _registry(indices_service).pop(index, None)


def stats(indices_service, index_expr: Optional[str] = None) -> dict:
    """``GET [/{index}]/_searchable_snapshots/stats``."""
    mounted = _registry(indices_service)
    if index_expr:
        wanted = set(indices_service.resolve(index_expr))
        names = [n for n in mounted if n in wanted]
        if not names:
            raise ResourceNotFoundError(
                f"[{index_expr}] is not a searchable snapshot index")
    else:
        names = [n for n in mounted if indices_service.exists(n)]
    total_bytes = 0
    per_index = {}
    for n in sorted(names):
        m = mounted[n]
        total_bytes += m["bytes"]
        per_index[n] = {
            "repository": m["repository"],
            "snapshot": m["snapshot"],
            "storage": m["storage"],
            "total_size_in_bytes": m["bytes"],
            "files": m["files"],
            "shards": [{"prewarmed_bytes": m["bytes"],
                        "cached_bytes": m["bytes"]}]}
    return {"total": {"size_in_bytes": total_bytes,
                      "index_count": len(per_index)},
            "indices": per_index}


def clear_cache(indices_service,
                index_expr: Optional[str] = None) -> dict:
    """``POST /_searchable_snapshots/cache/clear`` — with full-copy
    storage the local copy IS the cache; clearing resets the
    prewarm counters (the data stays, exactly like clearing the
    reference's cache on a full_copy mount forces re-reads that hit
    local disk again)."""
    mounted = _registry(indices_service)
    names = list(mounted) if not index_expr else [
        n for n in indices_service.resolve(index_expr) if n in mounted]
    return {"_shards": {"total": len(names), "successful": len(names),
                        "failed": 0}}


def mounted_indices(indices_service) -> List[str]:
    return sorted(_registry(indices_service))
