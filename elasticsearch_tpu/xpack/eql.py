"""EQL front-end: event queries, sequences (by / with maxspan / until),
and head/tail pipes over the standard search path.

Reference: ``x-pack/plugin/eql`` — EQL parses to the shared ``ql`` tree and
compiles event filters down to query DSL; sequences run as an iterative
state machine over time-ordered event batches
(``eql/execution/sequence/TumblingWindow.java``, ``SequenceMatcher``).
Here each step's filter folds to DSL and executes through the (cluster-
aware, TPU-planed) search seam; the sequence automaton then runs host-side
over the time-merged event stream — same observable semantics (partial
sequences keyed by join keys, maxspan windows, ``until`` clearing), sized
for the response's ``size`` cap.

Surface (documented subset):
  <category> where <cond>           event query
  sequence [by f1[,f2]] [with maxspan=Nu]
    [cat1 where c1] [by g1] ... [until [cat where c]]
  pipes: | head N   | tail N
Conditions: ==, !=, <, <=, >, >=, :/like (wildcard match), in, in~,
and/or/not, parentheses, wildcard(field, "p1", ...), true/false/null
literals, double-quoted strings.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

from ..common.errors import ElasticsearchError


class EqlParsingError(ElasticsearchError):
    status = 400
    error_type = "parsing_exception"


class EqlVerificationError(ElasticsearchError):
    status = 400
    error_type = "verification_exception"


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

_TOK_RX = re.compile(r"""
    \s*(?:
      (?P<num>-?\d+\.\d+|-?\d+)
    | "(?P<str>(?:[^"\\]|\\.)*)"
    | (?P<op>==|!=|<=|>=|<|>|\(|\)|\[|\]|,|\||=|:)
    | (?P<id>[A-Za-z_@][A-Za-z0-9_.@-]*~?)
    )""", re.VERBOSE)

_KEYWORDS = {"where", "and", "or", "not", "in", "like", "sequence", "by",
             "with", "maxspan", "until", "head", "tail", "true", "false",
             "null", "any"}


def _untokenize_str(s: str) -> str:
    return re.sub(r"\\(.)", r"\1", s)


def _tokenize(text: str) -> List[Tuple[str, Any]]:
    out: List[Tuple[str, Any]] = []
    pos = 0
    while pos < len(text):
        m = _TOK_RX.match(text, pos)
        if m is None or m.end() == pos:
            rest = text[pos:].strip()
            if not rest:
                break
            raise EqlParsingError(
                f"line 1:{pos + 1}: token recognition error at: "
                f"'{rest[0]}'")
        pos = m.end()
        if m.group("num") is not None:
            n = m.group("num")
            out.append(("num", float(n) if "." in n else int(n)))
        elif m.group("str") is not None:
            out.append(("str", _untokenize_str(m.group("str"))))
        elif m.group("op") is not None:
            out.append(("op", m.group("op")))
        else:
            word = m.group("id")
            low = word.lower().rstrip("~")
            if low in _KEYWORDS and word.rstrip("~").islower():
                out.append(("kw", low + ("~" if word.endswith("~")
                                         else "")))
            else:
                out.append(("id", word))
    out.append(("eof", None))
    return out


# ---------------------------------------------------------------------------
# condition AST → DSL folding (shares design with xpack/sql.fold_condition)
# ---------------------------------------------------------------------------

class _P:
    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind, val=None):
        k, v = self.peek()
        if k == kind and (val is None or v == val):
            self.i += 1
            return v if val is None else True
        return None if val is None else False

    def expect_op(self, op):
        if not self.accept("op", op):
            raise EqlParsingError(f"expected '{op}' at [{self.peek()[1]}]")


def _fold_cond(p: _P, resolve) -> dict:
    return _or(p, resolve)


def _or(p: _P, rf) -> dict:
    parts = [_and(p, rf)]
    while p.accept("kw", "or"):
        parts.append(_and(p, rf))
    if len(parts) == 1:
        return parts[0]
    return {"bool": {"should": parts, "minimum_should_match": 1}}


def _and(p: _P, rf) -> dict:
    parts = [_not(p, rf)]
    while p.accept("kw", "and"):
        parts.append(_not(p, rf))
    if len(parts) == 1:
        return parts[0]
    return {"bool": {"must": parts}}


def _not(p: _P, rf) -> dict:
    if p.accept("kw", "not"):
        return {"bool": {"must_not": [_not(p, rf)]}}
    return _pred(p, rf)


_RANGE_OP = {"<": "lt", "<=": "lte", ">": "gt", ">=": "gte"}


def _pred(p: _P, rf) -> dict:
    if p.accept("op", "("):
        inner = _fold_cond(p, rf)
        p.expect_op(")")
        return inner
    k, v = p.next()
    if k == "kw" and v == "true":
        return {"match_all": {}}
    if k == "kw" and v == "false":
        return {"bool": {"must_not": [{"match_all": {}}]}}
    if k == "id" and p.accept("op", "("):
        return _func(p, v, rf)
    if k != "id":
        raise EqlParsingError(f"unexpected token [{v}] in condition")
    field = v
    kk, vv = p.peek()
    if kk == "op" and vv in ("==", "!="):
        p.next()
        val = _value(p)
        if val is None:
            q: dict = {"exists": {"field": field}}
            return q if vv == "!=" else {"bool": {"must_not": [q]}}
        q = {"term": {rf(field): {"value": val}}}
        return q if vv == "==" else {"bool": {"must_not": [q]}}
    if kk == "op" and vv in _RANGE_OP:
        p.next()
        val = _value(p)
        return {"range": {field: {_RANGE_OP[vv]: val}}}
    if kk == "op" and vv == ":":
        p.next()
        return _like(p, field, rf)
    if (kk == "kw" and vv in ("like", "like~")) or \
            (kk == "id" and vv in ("like", "like~")):
        p.next()
        return _like(p, field, rf, ci=str(vv).endswith("~"))
    if kk == "kw" and vv in ("in", "in~"):
        ci = vv.endswith("~")
        p.next()
        p.expect_op("(")
        vals = []
        while True:
            vals.append(_value(p))
            if p.accept("op", ")"):
                break
            if not p.accept("op", ","):
                raise EqlParsingError("expected , or ) in value list")
        if ci:
            # in~ is case-insensitive membership: disjunction of ci terms
            return {"bool": {"should": [
                {"term": {rf(field): {"value": v,
                                      "case_insensitive": True}}}
                for v in vals], "minimum_should_match": 1}}
        return {"terms": {rf(field): vals}}
    if kk == "kw" and vv == "not":
        p.next()
        ci = bool(p.accept("kw", "in~"))
        if not ci and not p.accept("kw", "in"):
            raise EqlParsingError("expected 'in' after 'not'")
        p.expect_op("(")
        vals = []
        while True:
            vals.append(_value(p))
            if p.accept("op", ")"):
                break
            if not p.accept("op", ","):
                raise EqlParsingError("expected , or ) in value list")
        if ci:
            return {"bool": {"must_not": [
                {"term": {rf(field): {"value": v,
                                      "case_insensitive": True}}}
                for v in vals]}}
        return {"bool": {"must_not": [{"terms": {rf(field): vals}}]}}
    raise EqlParsingError(f"expected an operator after [{field}]")


def _like(p: _P, field: str, rf, ci: bool = False) -> dict:
    k, v = p.next()
    single = None
    if k == "str":
        single = v
    elif k == "op" and v == "(":
        pats = []
        while True:
            kk, vv = p.next()
            if kk != "str":
                raise EqlParsingError("like expects string patterns")
            pats.append(vv)
            if p.accept("op", ")"):
                break
            if not p.accept("op", ","):
                raise EqlParsingError("expected , or ) in pattern list")
        shoulds = [_one_like(field, pt, rf, ci) for pt in pats]
        return {"bool": {"should": shoulds, "minimum_should_match": 1}}
    else:
        raise EqlParsingError("like expects a string pattern")
    return _one_like(field, single, rf, ci)


def _one_like(field: str, pattern: str, rf, ci: bool) -> dict:
    if "*" in pattern or "?" in pattern:
        q: dict = {"value": pattern}
        if ci:
            q["case_insensitive"] = True
        return {"wildcard": {rf(field): q}}
    tq: dict = {"value": pattern}
    if ci:
        tq["case_insensitive"] = True
    return {"term": {rf(field): tq}}


def _func(p: _P, name: str, rf) -> dict:
    """wildcard(field, "p1", ...) / cidrMatch(field, "cidr", ...) analogs."""
    args: List[Any] = []
    while True:
        k, v = p.next()
        if k == "id":
            args.append(("field", v))
        elif k in ("str", "num"):
            args.append(("lit", v))
        else:
            raise EqlParsingError(f"unexpected token in {name}()")
        if p.accept("op", ")"):
            break
        if not p.accept("op", ","):
            raise EqlParsingError(f"expected , or ) in {name}()")
    lname = name.lower()
    if lname == "wildcard":
        if not args or args[0][0] != "field":
            raise EqlVerificationError("wildcard() needs a field first")
        field = args[0][1]
        pats = [a[1] for a in args[1:] if a[0] == "lit"]
        shoulds = [_one_like(field, str(pt), rf, False) for pt in pats]
        return {"bool": {"should": shoulds, "minimum_should_match": 1}}
    if lname == "cidrmatch":
        if not args or args[0][0] != "field":
            raise EqlVerificationError("cidrMatch() needs a field first")
        field = args[0][1]
        nets = [str(a[1]) for a in args[1:] if a[0] == "lit"]
        return {"terms": {field: nets}}
    raise EqlVerificationError(f"unknown function [{name}]")


def _value(p: _P) -> Any:
    k, v = p.next()
    if k == "num" or k == "str":
        return v
    if k == "kw" and v in ("true", "false", "null"):
        return {"true": True, "false": False, "null": None}[v]
    raise EqlParsingError(f"expected a value but found [{v}]")


# ---------------------------------------------------------------------------
# top-level query parsing
# ---------------------------------------------------------------------------

class EventQuery:
    def __init__(self, category: Optional[str], cond_dsl: dict,
                 join_fields: Optional[List[str]] = None):
        self.category = category
        self.cond_dsl = cond_dsl
        self.join_fields = join_fields or []


class ParsedEql:
    def __init__(self):
        self.kind = "event"              # event | sequence
        self.event: Optional[EventQuery] = None
        self.steps: List[EventQuery] = []
        self.until: Optional[EventQuery] = None
        self.by: List[str] = []
        self.maxspan_ms: Optional[float] = None
        self.pipes: List[Tuple[str, int]] = []


def _span_ms(num: float, unit: str) -> float:
    from ..common.settings import parse_time_millis
    return parse_time_millis(f"{num}{unit}")


_SPAN_UNITS = ("ms", "s", "m", "h", "d")


def parse_eql(text: str, resolve) -> ParsedEql:
    p = _P(_tokenize(text))
    out = ParsedEql()
    k, v = p.peek()
    if k == "kw" and v == "sequence":
        p.next()
        out.kind = "sequence"
        if p.accept("kw", "by"):
            out.by.append(_field_name(p))
            while p.accept("op", ","):
                out.by.append(_field_name(p))
        if p.accept("kw", "with"):
            if not p.accept("kw", "maxspan"):
                raise EqlParsingError("expected maxspan after 'with'")
            if not p.accept("op", "="):
                raise EqlParsingError("expected = after maxspan")
            kk, vv = p.next()
            if kk != "num":
                raise EqlParsingError("maxspan expects a number+unit")
            ku, vu = p.peek()
            unit = "s"
            if ku == "id" and vu in _SPAN_UNITS:
                p.next()
                unit = vu
            out.maxspan_ms = _span_ms(float(vv), unit)
        while True:
            kk, vv = p.peek()
            if kk == "op" and vv == "[":
                p.next()
                out.steps.append(_bracketed_event(p, resolve))
                if p.accept("kw", "by"):
                    out.steps[-1].join_fields.append(_field_name(p))
                    while p.accept("op", ","):
                        out.steps[-1].join_fields.append(_field_name(p))
            elif kk == "kw" and vv == "until":
                p.next()
                if not p.accept("op", "["):
                    raise EqlParsingError("until expects [event where ...]")
                out.until = _bracketed_event(p, resolve)
            else:
                break
        if len(out.steps) < 2:
            raise EqlParsingError(
                "a sequence requires a minimum of 2 queries")
        for s in out.steps:
            if len(s.join_fields) != len(out.steps[0].join_fields):
                raise EqlParsingError(
                    "per-step 'by' arity must match across the sequence")
    else:
        out.event = _event_query(p, resolve)
    # pipes
    while p.accept("op", "|"):
        kk, vv = p.next()
        if kk not in ("kw", "id") or vv not in ("head", "tail"):
            raise EqlParsingError(f"unknown pipe [{vv}]")
        kn, vn = p.next()
        if kn != "num" or not isinstance(vn, int):
            raise EqlParsingError(f"pipe {vv} expects an integer")
        out.pipes.append((vv, vn))
    k, v = p.peek()
    if k != "eof":
        raise EqlParsingError(f"unexpected trailing input [{v}]")
    return out


def _field_name(p: _P) -> str:
    k, v = p.next()
    if k != "id":
        raise EqlParsingError(f"expected a field name but found [{v}]")
    return v


def _event_query(p: _P, resolve) -> EventQuery:
    k, v = p.next()
    if k == "kw" and v == "any":
        category = None
    elif k in ("id", "str"):
        category = str(v)
    else:
        raise EqlParsingError(f"expected an event category, found [{v}]")
    if not p.accept("kw", "where"):
        raise EqlParsingError("expected 'where'")
    cond = _fold_cond(p, resolve)
    return EventQuery(category, cond)


def _bracketed_event(p: _P, resolve) -> EventQuery:
    ev = _event_query(p, resolve)
    if not p.accept("op", "]"):
        raise EqlParsingError("expected ]")
    return ev


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------

class EqlService:
    """Executes parsed EQL through the search seam.

    ``search_fn(index, body) -> response`` and ``mapper_fn(index)`` come
    from the REST layer (same seam as ``SqlService``).
    """

    #: per-step event fetch bound for the host-side sequence automaton
    #: (the reference windows in batches of ``eql.fetch_size``; one large
    #: time-ordered page keeps the automaton exact at conformance scale
    #: and is documented as the scale limit)
    SEQUENCE_FETCH = 10_000

    def __init__(self, search_fn, mapper_fn):
        self.search_fn = search_fn
        self.mapper_fn = mapper_fn

    def _resolver(self, index: str):
        mapper = self.mapper_fn(index)

        def rf(name: str) -> str:
            if mapper is None:
                return name
            ft = mapper.field_type(name)
            if ft is not None and ft.type_name == "text":
                sub = mapper.field_type(name + ".keyword")
                if sub is not None and sub.type_name == "keyword":
                    return name + ".keyword"
            return name
        return rf

    def search(self, index: str, payload: dict) -> dict:
        import time as _time
        t0 = _time.time()
        query = payload.get("query")
        if not query or not isinstance(query, str):
            raise EqlParsingError("[query] is required")
        ts_field = payload.get("timestamp_field", "@timestamp")
        cat_field = payload.get("event_category_field", "event.category")
        tiebreak = payload.get("tiebreaker_field")
        size = int(payload.get("size", 10))
        rf = self._resolver(index)
        parsed = parse_eql(query, rf)
        if parsed.kind == "event":
            hits, total = self._run_event(
                index, parsed, payload, ts_field, cat_field, tiebreak,
                size, rf)
            body: dict = {"events": hits,
                          "total": {"value": total, "relation": "eq"}}
        else:
            seqs = self._run_sequence(
                index, parsed, payload, ts_field, cat_field, tiebreak,
                size, rf)
            body = {"sequences": seqs,
                    "total": {"value": len(seqs), "relation": "eq"}}
        return {
            "is_partial": False, "is_running": False,
            "took": int((_time.time() - t0) * 1000), "timed_out": False,
            "hits": body,
        }

    # -- event queries --------------------------------------------------
    def _event_filter(self, ev: EventQuery, payload: dict,
                      cat_field: str, rf) -> dict:
        must: List[dict] = [ev.cond_dsl]
        if ev.category is not None:
            must.append({"term": {rf(cat_field): {"value": ev.category}}})
        if payload.get("filter"):
            must.append(payload["filter"])
        return {"bool": {"must": must}} if len(must) > 1 else must[0]

    @staticmethod
    def _event_hit(h: dict) -> dict:
        return {"_index": h["_index"], "_id": h["_id"],
                "_source": h.get("_source")}

    def _run_event(self, index, parsed, payload, ts_field, cat_field,
                   tiebreak, size, rf):
        head_n, tail = size, False
        for pipe, n in parsed.pipes:
            head_n = min(head_n, n) if pipe == "head" else head_n
            if pipe == "tail":
                head_n, tail = min(size, n), True
        sort: List[dict] = [{ts_field: {
            "order": "desc" if tail else "asc"}}]
        if tiebreak:
            sort.append({rf(tiebreak): {
                "order": "desc" if tail else "asc"}})
        body = {"size": head_n, "sort": sort, "track_total_hits": True,
                "query": self._event_filter(parsed.event, payload,
                                            cat_field, rf)}
        resp = self.search_fn(index, body)
        hits = [self._event_hit(h) for h in resp["hits"]["hits"]]
        if tail:
            hits.reverse()
        return hits, resp["hits"]["total"]["value"]

    # -- sequences ------------------------------------------------------
    def _fetch_step(self, index, ev, payload, ts_field, cat_field,
                    tiebreak, rf) -> List[dict]:
        sort: List[dict] = [{ts_field: {"order": "asc"}}]
        if tiebreak:
            sort.append({rf(tiebreak): {"order": "asc"}})
        body = {"size": self.SEQUENCE_FETCH, "sort": sort,
                "query": self._event_filter(ev, payload, cat_field, rf)}
        return self.search_fn(index, body)["hits"]["hits"]

    def _run_sequence(self, index, parsed, payload, ts_field, cat_field,
                      tiebreak, size, rf) -> List[dict]:
        steps = parsed.steps
        n = len(steps)
        streams = [self._fetch_step(index, ev, payload, ts_field,
                                    cat_field, tiebreak, rf)
                   for ev in steps]
        until_stream = (self._fetch_step(index, parsed.until, payload,
                                         ts_field, cat_field, tiebreak,
                                         rf)
                        if parsed.until is not None else [])
        # merge into one time-ordered stream tagged by step index
        # (reference: TumblingWindow advances all stages in one ordered
        # pass); -1 tags until-events
        merged: List[Tuple[Any, int, int, dict]] = []
        for si, hs in enumerate(streams):
            for hi, h in enumerate(hs):
                merged.append((self._sort_key(h), si, hi, h))
        for hi, h in enumerate(until_stream):
            merged.append((self._sort_key(h), -1, hi, h))
        merged.sort(key=lambda t: (t[0], t[1]))

        def join_key(h: dict, si: int) -> Optional[tuple]:
            fields = list(parsed.by)
            if si >= 0 and steps[si].join_fields:
                fields = fields + steps[si].join_fields
            elif si < 0 and parsed.until is not None \
                    and parsed.until.join_fields:
                fields = fields + parsed.until.join_fields
            if not fields:
                return ()
            src = h.get("_source") or {}
            vals = []
            for f in fields:
                v = _dot_get(src, f)
                if v is None:
                    return None           # missing join key: not joinable
                vals.append(v)
            return tuple(vals)

        # partial sequences: key → list of event-lists awaiting stage len()
        partials: Dict[tuple, List[List[dict]]] = {}
        completed: List[dict] = []
        for sk, si, _hi, h in merged:
            if si == -1:
                k = join_key(h, -1)
                if k is not None and k in partials:
                    # until clears in-flight sequences for that key
                    partials.pop(k, None)
                continue
            k = join_key(h, si)
            if k is None:
                continue
            ts = sk[0]
            if si == 0:
                partials.setdefault(k, []).append([h])
                continue
            plist = partials.get(k)
            if not plist:
                continue
            # the automaton extends the MOST RECENT partial at stage si
            # (ES keeps one in-flight sequence per key per stage, last
            # writer wins — SequenceMatcher's stage replacement)
            for p in reversed(plist):
                if len(p) != si:
                    continue
                if parsed.maxspan_ms is not None:
                    t0 = self._ts_value(p[0])
                    if ts - t0 > parsed.maxspan_ms:
                        continue
                # a sequence needs DISTINCT events: the same doc matching
                # two step filters must not complete a stage with itself
                if any(e.get("_index") == h.get("_index")
                       and e.get("_id") == h.get("_id") for e in p):
                    continue
                p.append(h)
                if len(p) == n:
                    plist.remove(p)
                    completed.append({
                        "join_keys": list(k),
                        "events": [self._event_hit(e) for e in p]})
                break
            if not plist:
                partials.pop(k, None)
        for pipe, pn in parsed.pipes:
            completed = completed[:pn] if pipe == "head" \
                else completed[-pn:]
        return completed[:size]

    def _sort_key(self, h: dict) -> tuple:
        s = h.get("sort")
        if s:
            return tuple(s)
        return (0,)

    def _ts_value(self, h: dict) -> float:
        s = h.get("sort")
        return float(s[0]) if s else 0.0


def _dot_get(src: dict, path: str) -> Any:
    cur: Any = src
    for part in path.split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            return None
    return cur
