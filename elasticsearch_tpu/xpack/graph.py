"""Graph explore: entity co-occurrence expansion over terms aggregations.

Reference: ``x-pack/plugin/graph/.../TransportGraphExploreAction.java`` —
each hop runs a (sampled) significant/plain terms aggregation under the
seed query to pick vertices, then expands connections by co-occurrence
counting between the frontier's terms and the next hop's fields. Here each
hop folds into plain searches through the shared search seam: one terms
agg picks the hop's vertices, then one filtered terms agg per frontier
vertex counts co-occurrence (exact doc counts, not the reference's
sampler approximation — documented divergence that only strengthens
weights).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..common.errors import IllegalArgumentError


class GraphService:
    MAX_HOPS = 5

    def __init__(self, search_fn):
        self.search_fn = search_fn

    def explore(self, index: str, payload: dict) -> dict:
        import time as _time
        t0 = _time.time()
        hop = payload
        if "vertices" not in hop:
            raise IllegalArgumentError(
                "Graph explore request requires [vertices]")
        vertices: List[dict] = []     # {field, term, weight, depth}
        connections: List[dict] = []  # {source, target, weight, doc_count}
        vkey: Dict[Tuple[str, str], int] = {}

        def add_vertex(field: str, term: str, weight: float,
                       depth: int) -> int:
            k = (field, term)
            if k in vkey:
                return vkey[k]
            vkey[k] = len(vertices)
            vertices.append({"field": field, "term": term,
                             "weight": weight, "depth": depth})
            return vkey[k]

        # hop 0: seed vertices under the seed query
        seed_query = hop.get("query") or {"match_all": {}}
        frontier: List[int] = []
        for vspec in hop["vertices"]:
            field = vspec["field"]
            size = int(vspec.get("size", 5))
            min_dc = int(vspec.get("min_doc_count", 3))
            body = {"size": 0, "query": seed_query, "aggs": {
                "v": {"terms": {"field": field, "size": size,
                                "min_doc_count": min_dc}}}}
            resp = self.search_fn(index, body)
            total = max(resp["hits"]["total"]["value"], 1)
            for b in resp["aggregations"]["v"]["buckets"]:
                vi = add_vertex(field, str(b["key"]),
                                b["doc_count"] / total, 0)
                frontier.append(vi)

        # connection hops expand from the current frontier
        depth = 1
        conn = hop.get("connections")
        while conn is not None and depth <= self.MAX_HOPS:
            if "vertices" not in conn:
                raise IllegalArgumentError(
                    "[connections] requires [vertices]")
            next_frontier: List[int] = []
            frontier_seen: set = set()
            for src_i in frontier:
                src = vertices[src_i]
                for vspec in conn["vertices"]:
                    field = vspec["field"]
                    size = int(vspec.get("size", 5))
                    min_dc = int(vspec.get("min_doc_count", 3))
                    must: List[dict] = [
                        {"term": {src["field"]: src["term"]}}]
                    if conn.get("query"):
                        must.append(conn["query"])
                    body = {"size": 0,
                            "query": {"bool": {"must": must}},
                            "aggs": {"v": {"terms": {
                                "field": field, "size": size,
                                "min_doc_count": min_dc}}}}
                    resp = self.search_fn(index, body)
                    total = max(resp["hits"]["total"]["value"], 1)
                    for b in resp["aggregations"]["v"]["buckets"]:
                        term = str(b["key"])
                        if field == src["field"] and term == src["term"]:
                            continue       # self-loop
                        tgt_i = add_vertex(field, term,
                                           b["doc_count"] / total, depth)
                        connections.append({
                            "source": src_i, "target": tgt_i,
                            "weight": b["doc_count"] / total,
                            "doc_count": b["doc_count"]})
                        if vertices[tgt_i]["depth"] == depth and \
                                tgt_i not in frontier_seen:
                            frontier_seen.add(tgt_i)
                            next_frontier.append(tgt_i)
            frontier = next_frontier
            conn = conn.get("connections")
            depth += 1

        return {"took": int((_time.time() - t0) * 1000),
                "timed_out": False,
                "vertices": vertices, "connections": connections}
