"""Licensing + the ``/_xpack`` info/usage surface.

Reference: ``x-pack/plugin/core/.../license/LicenseService.java`` (state
machine over basic/trial/gold/platinum licenses, trial-once semantics),
``rest/action/XPackInfoAction`` and ``XPackUsageAction``.  The licensing
model here is the observable subset: a self-generated basic license by
default, one 30-day trial upgrade, explicit license PUT, and the feature
availability matrix the ``/_xpack`` endpoints render — actual feature
gating stays off (everything is enabled) exactly like the reference's
default basic-with-everything-OSS posture in tests.
"""
from __future__ import annotations

import hashlib
import time
from typing import Dict, Optional

from ..common.errors import IllegalArgumentError

_TRIAL_DAYS = 30

#: feature → minimum license level that enables it (reference:
#: ``XPackLicenseState.java`` feature checks)
FEATURES = {
    "security": "basic", "monitoring": "basic", "rollup": "basic",
    "ilm": "basic", "slm": "basic", "transform": "basic",
    "data_streams": "basic", "eql": "basic", "sql": "basic",
    "frozen_indices": "basic", "vectors": "basic",
    "analytics": "basic", "searchable_snapshots": "enterprise",
    "ml": "platinum", "graph": "platinum", "watcher": "gold",
    "ccr": "platinum", "enrich": "basic", "spatial": "basic",
    "logstash": "gold", "voting_only": "basic", "aggregate_metric":
    "basic", "autoscaling": "enterprise", "data_tiers": "basic",
}

_LEVELS = ["basic", "standard", "gold", "platinum", "enterprise",
           "trial"]


def _now_ms() -> int:
    return int(time.time() * 1000)


class LicenseService:
    def __init__(self, cluster_uuid: str = "cluster"):
        self.cluster_uuid = cluster_uuid
        self.trial_used = False
        self.license = self._self_generated("basic")

    def _self_generated(self, ltype: str) -> dict:
        now = _now_ms()
        uid = hashlib.sha1(
            f"{self.cluster_uuid}:{ltype}:{now}".encode()).hexdigest()
        lic = {"status": "active", "uid": uid, "type": ltype,
               "issue_date_in_millis": now,
               "issued_to": self.cluster_uuid,
               "issuer": "elasticsearch",
               "start_date_in_millis": now,
               "max_nodes": 1000}
        if ltype == "trial":
            lic["expiry_date_in_millis"] = \
                now + _TRIAL_DAYS * 86_400_000
        return lic

    def _level(self) -> str:
        lic = self.license
        if lic is None or lic["status"] != "active":
            return "none"
        t = lic["type"]
        # an active trial unlocks everything, like the reference
        return "enterprise" if t == "trial" else t

    def feature_active(self, feature: str) -> bool:
        need = FEATURES.get(feature, "basic")
        level = self._level()
        if level == "none":
            return False
        return _LEVELS.index(level if level in _LEVELS else "basic") >= \
            _LEVELS.index(need if need in _LEVELS else "basic")

    # -- REST ------------------------------------------------------------
    def get_license(self) -> dict:
        if self.license is None:
            from ..common.errors import ResourceNotFoundError
            raise ResourceNotFoundError("no license is installed")
        out = dict(self.license)
        out["issue_date"] = _iso(out["issue_date_in_millis"])
        if "expiry_date_in_millis" in out:
            out["expiry_date"] = _iso(out["expiry_date_in_millis"])
        return {"license": out}

    def put_license(self, body: dict, acknowledge: bool) -> dict:
        licenses = body.get("licenses") or \
            ([body["license"]] if body.get("license") else [])
        if not licenses:
            raise IllegalArgumentError(
                "The license must be provided in the request body")
        lic = licenses[0]
        ltype = lic.get("type", "basic")
        if ltype not in _LEVELS:
            raise IllegalArgumentError(
                f"unknown license type [{ltype}]")
        if not acknowledge and ltype != (self.license or {}).get("type"):
            return {"acknowledged": False,
                    "license_status": "valid",
                    "acknowledge": {
                        "message": "This license update requires "
                                   "acknowledgement. To acknowledge the "
                                   "license, please read the following "
                                   "messages and update the license "
                                   "again, this time with the "
                                   "\"acknowledge=true\" parameter:"}}
        self.license = dict(self._self_generated(ltype), **{
            k: v for k, v in lic.items() if k in
            ("uid", "issued_to", "issuer", "expiry_date_in_millis",
             "max_nodes", "type")})
        return {"acknowledged": True, "license_status": "valid"}

    def delete_license(self) -> dict:
        self.license = None
        return {"acknowledged": True}

    def start_trial(self, acknowledge: bool) -> dict:
        if self.trial_used:
            return {"acknowledged": True, "trial_was_started": False,
                    "error_message": "Operation failed: Trial was "
                                     "already activated."}
        if not acknowledge:
            return {"acknowledged": False, "trial_was_started": False,
                    "error_message": "Operation failed: Needs "
                                     "acknowledgement."}
        self.trial_used = True
        self.license = self._self_generated("trial")
        return {"acknowledged": True, "trial_was_started": True,
                "type": "trial"}

    def start_basic(self, acknowledge: bool) -> dict:
        if self.license is not None and \
                self.license.get("type") == "basic":
            return {"acknowledged": True, "basic_was_started": False,
                    "error_message": "Operation failed: Current license "
                                     "is basic."}
        if not acknowledge and self.license is not None:
            return {"acknowledged": False, "basic_was_started": False,
                    "error_message": "Operation failed: Needs "
                                     "acknowledgement."}
        self.license = self._self_generated("basic")
        return {"acknowledged": True, "basic_was_started": True}

    def trial_status(self) -> dict:
        return {"eligible_to_start_trial": not self.trial_used}

    def basic_status(self) -> dict:
        eligible = self.license is None or \
            self.license.get("type") != "basic"
        return {"eligible_to_start_basic": eligible}

    # -- /_xpack ---------------------------------------------------------
    def xpack_info(self, build_hash: str = "tpu-native") -> dict:
        lic = self.license or {}
        features: Dict[str, dict] = {}
        for feat in sorted(FEATURES):
            features[feat] = {"available": self.feature_active(feat),
                              "enabled": True}
        return {
            "build": {"hash": build_hash, "date": _iso(_now_ms())},
            "license": {"uid": lic.get("uid"),
                        "type": lic.get("type"),
                        "mode": lic.get("type"),
                        "status": lic.get("status", "invalid")},
            "features": features,
            "tagline": "You know, for X"}


def _iso(ms: int) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S",
                         time.gmtime(ms / 1000)) + \
        f".{ms % 1000:03d}Z"
