"""Machine learning: anomaly detection, datafeeds, trained-model
inference, and dataframe analytics.

Reference: ``x-pack/plugin/ml/`` (67k Java LoC + the native C++
``autodetect`` process managed through ``process/NativeController.java:35``).
The reference's split is: job/datafeed config management in Java, the
statistical modeling in a side-car C++ process fed over named pipes, and
tree-ensemble inference evaluated per-document in Java
(``inference/trainedmodel/ensemble/Ensemble.java``).

TPU-native re-design — the compute lives on device, not in a side-car:

* **Anomaly detection** (``job/``, ``autodetect``): per-series online
  Gaussian baselines (exponentially decayed Welford moments) updated as
  buckets close; the anomaly score is the two-sided (or one-sided for
  ``high_``/``low_`` functions) normal tail probability mapped onto the
  reference's 0-100 score scale.  Results are indexed into
  ``.ml-anomalies-shared`` exactly like the reference's results index, so
  they are searchable with the ordinary query DSL.
* **Inference** (``inference/``): tree ensembles are flattened into
  padded ``(tree, node)`` arrays and evaluated as a single jitted XLA
  program — a ``lax.fori_loop`` over tree depth with gathered node
  indices, ``vmap`` over trees, batched over documents.  One dispatch
  scores ``docs x trees`` on the MXU-adjacent vector units instead of the
  reference's per-document recursive Java walk.
* **Dataframe analytics** (``dataframe/``): outlier detection is a
  pairwise-distance kernel (the classic ``|x|^2 + |y|^2 - 2 x.y^T``
  matmul form, which XLA tiles onto the MXU) + ``top_k``; regression is a
  device least-squares solve; classification is full-batch multinomial
  logistic regression trained under ``jax.jit`` with ``lax.fori_loop``.

Kept host-side on purpose: config CRUD, datafeed paging (IO-bound), and
bucket bookkeeping — same boundary the reference draws between its Java
layer and the native process.
"""
from __future__ import annotations

import json
import math
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import (ElasticsearchError, IllegalArgumentError,
                             ResourceAlreadyExistsError,
                             ResourceNotFoundError)
from ..ingest.pipeline import (Processor, ProcessorException, _req,
                               register_processor)

RESULTS_INDEX = ".ml-anomalies-shared"


def _now_ms() -> int:
    return int(time.time() * 1000)


def _parse_time(v: Any) -> Optional[int]:
    """Epoch ms from epoch-seconds, epoch-ms, or ISO8601."""
    if v is None:
        return None
    if isinstance(v, (int, float)):
        # heuristic matching the reference's epoch/epoch_ms sniffing
        return int(v * 1000) if v < 10_000_000_000 else int(v)
    s = str(v)
    if s.isdigit():
        return _parse_time(int(s))
    import datetime as _dt
    try:
        dt = _dt.datetime.fromisoformat(s.replace("Z", "+00:00"))
    except ValueError:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp() * 1000)


def _span_ms(span: Any) -> int:
    """Parse a bucket_span like ``15m``/``1h``/``300s`` to ms."""
    if isinstance(span, (int, float)):
        return int(span * 1000)
    s = str(span).strip().lower()
    units = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
             "d": 86_400_000}
    for suffix in ("ms", "s", "m", "h", "d"):
        if s.endswith(suffix) and s[: -len(suffix)].replace(".", "").isdigit():
            return int(float(s[: -len(suffix)]) * units[suffix])
    raise IllegalArgumentError(
        f"failed to parse setting [bucket_span] with value [{span}]")


# ---------------------------------------------------------------------------
# Anomaly detection: per-series decayed-Welford baseline + tail-prob score
# ---------------------------------------------------------------------------

#: functions → (needs_field, one_sided: None both / +1 high / -1 low)
_FUNCTIONS: Dict[str, Tuple[bool, Optional[int]]] = {
    "count": (False, None), "high_count": (False, 1),
    "low_count": (False, -1), "non_zero_count": (False, None),
    "mean": (True, None), "avg": (True, None), "high_mean": (True, 1),
    "low_mean": (True, -1), "min": (True, -1), "max": (True, 1),
    "sum": (True, None), "high_sum": (True, 1), "low_sum": (True, -1),
    "metric": (True, None), "distinct_count": (True, None),
    "median": (True, None),
}

_DECAY = 0.98          # per-bucket decay on the baseline moments
_MIN_BASELINE = 3      # buckets before a series can produce anomalies


class _SeriesModel:
    """Decayed Welford moments for one (detector, by, partition) series.

    Stands in for the C++ autodetect per-series model
    (`x-pack/plugin/ml` native process); the decay keeps the baseline
    adaptive the way the reference's time-based model pruning does.
    """

    __slots__ = ("n", "mean", "m2")

    def __init__(self):
        self.n = 0.0
        self.mean = 0.0
        self.m2 = 0.0

    def score(self, x: float, side: Optional[int]) -> Tuple[float, float]:
        """(probability, score 0-100) of observing x under the baseline."""
        if self.n < _MIN_BASELINE:
            return 1.0, 0.0
        var = self.m2 / max(self.n - 1.0, 1.0)
        sd = math.sqrt(var) if var > 1e-12 else max(abs(self.mean), 1.0) * 0.01
        z = (x - self.mean) / sd
        if side == 1 and z < 0:
            return 1.0, 0.0
        if side == -1 and z > 0:
            return 1.0, 0.0
        # two-sided tail probability; one-sided keeps its own tail only
        tail = math.erfc(abs(z) / math.sqrt(2.0))
        p = tail if side is None else tail / 2.0
        p = max(p, 1e-308)
        # probability → 0-100 score, the reference's log-scale shape
        # (ml/anomaly score normalization): p=0.05 → ~13, p=1e-10 → ~100
        score = min(100.0, max(0.0, -10.0 * math.log10(p) - 10.0))
        return p, score

    def update(self, x: float) -> None:
        self.n = self.n * _DECAY + 1.0
        delta = x - self.mean
        self.mean += delta / self.n
        self.m2 = self.m2 * _DECAY + delta * (x - self.mean)


class _BucketAcc:
    """Accumulates one in-flight bucket for one series."""

    __slots__ = ("count", "total", "mn", "mx", "distinct")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.mn = math.inf
        self.mx = -math.inf
        self.distinct: set = set()

    def add(self, value: Optional[float]) -> None:
        self.count += 1
        if value is not None:
            self.total += value
            self.mn = min(self.mn, value)
            self.mx = max(self.mx, value)
            self.distinct.add(value)

    def value(self, func: str) -> Optional[float]:
        base = func.replace("high_", "").replace("low_", "")
        if base in ("count", "non_zero_count"):
            return float(self.count)
        if self.count == 0 or self.mn is math.inf:
            return None
        if base in ("mean", "avg", "metric", "median"):
            return self.total / self.count
        if base == "sum":
            return self.total
        if base == "min":
            return self.mn
        if base == "max":
            return self.mx
        if base == "distinct_count":
            return float(len(self.distinct))
        return None


class AnomalyJob:
    def __init__(self, job_id: str, body: dict):
        ac = body.get("analysis_config") or {}
        detectors = ac.get("detectors")
        if not detectors:
            raise IllegalArgumentError(
                "An analysis_config with at least one detector is required")
        for d in detectors:
            fn = d.get("function")
            if fn not in _FUNCTIONS:
                raise IllegalArgumentError(
                    f"Unknown function '{fn}'")
            needs_field, _side = _FUNCTIONS[fn]
            if needs_field and not d.get("field_name"):
                raise IllegalArgumentError(
                    f"Unless the function is 'count' one of field_name, "
                    f"by_field_name or over_field_name must be set")
        self.job_id = job_id
        self.config = dict(body, job_id=job_id,
                           create_time=_now_ms(),
                           job_type="anomaly_detector")
        self.bucket_span = _span_ms(ac.get("bucket_span", "5m"))
        self.detectors = detectors
        dd = body.get("data_description") or {}
        self.time_field = dd.get("time_field", "time")
        self.time_format = dd.get("time_format", "epoch_ms")
        self.state = "closed"
        #: (det_idx, by, partition) → _SeriesModel
        self.models: Dict[tuple, _SeriesModel] = {}
        #: bucket_start → {(det_idx, by, partition): _BucketAcc}
        self.pending: Dict[int, Dict[tuple, _BucketAcc]] = {}
        self.results: List[dict] = []      # buckets + records, time order
        self.snapshots: List[dict] = []
        self.counts = {"processed_record_count": 0,
                       "processed_field_count": 0,
                       "invalid_date_count": 0,
                       "missing_field_count": 0,
                       "out_of_order_timestamp_count": 0,
                       "bucket_count": 0,
                       "earliest_record_timestamp": None,
                       "latest_record_timestamp": None}
        self._latest_finalized = -1

    def _record_time(self, v: Any) -> Optional[int]:
        """Record timestamps follow data_description.time_format —
        ``epoch_ms`` (the default) must NOT be sniffed as seconds."""
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            if self.time_format == "epoch":
                return int(v * 1000)
            return int(v)
        return _parse_time(v)

    # -- data ingestion --------------------------------------------------
    def post(self, records: List[dict]) -> None:
        for rec in records:
            ts = self._record_time(rec.get(self.time_field))
            if ts is None:
                self.counts["invalid_date_count"] += 1
                continue
            if (self._latest_finalized >= 0
                    and ts < self._latest_finalized):
                self.counts["out_of_order_timestamp_count"] += 1
                continue
            self.counts["processed_record_count"] += 1
            c = self.counts
            c["earliest_record_timestamp"] = ts if \
                c["earliest_record_timestamp"] is None else \
                min(c["earliest_record_timestamp"], ts)
            c["latest_record_timestamp"] = ts if \
                c["latest_record_timestamp"] is None else \
                max(c["latest_record_timestamp"], ts)
            bucket = ts - ts % self.bucket_span
            accs = self.pending.setdefault(bucket, {})
            for di, det in enumerate(self.detectors):
                needs_field, _ = _FUNCTIONS[det["function"]]
                val = None
                if needs_field:
                    raw = rec.get(det["field_name"])
                    if raw is None:
                        self.counts["missing_field_count"] += 1
                        continue
                    try:
                        val = float(raw)
                    except (TypeError, ValueError):
                        self.counts["missing_field_count"] += 1
                        continue
                    self.counts["processed_field_count"] += 1
                by = rec.get(det["by_field_name"]) \
                    if det.get("by_field_name") else None
                part = rec.get(det["partition_field_name"]) \
                    if det.get("partition_field_name") else None
                accs.setdefault((di, by, part), _BucketAcc()).add(val)
        # finalize every bucket strictly older than the newest seen:
        # the newest may still receive records (stream semantics)
        if self.pending:
            newest = max(self.pending)
            for b in sorted(self.pending):
                if b < newest:
                    self._finalize(b)

    def flush(self) -> None:
        for b in sorted(self.pending):
            self._finalize(b)

    def _finalize(self, bucket_ts: int) -> None:
        accs = self.pending.pop(bucket_ts, None)
        if accs is None:
            return
        self._latest_finalized = max(self._latest_finalized,
                                     bucket_ts + self.bucket_span)
        self.counts["bucket_count"] += 1
        records: List[dict] = []
        max_score = 0.0
        for (di, by, part), acc in sorted(
                accs.items(), key=lambda kv: (kv[0][0], str(kv[0][1]),
                                              str(kv[0][2]))):
            det = self.detectors[di]
            func = det["function"]
            _needs, side = _FUNCTIONS[func]
            val = acc.value(func)
            if val is None:
                continue
            model = self.models.setdefault((di, by, part), _SeriesModel())
            prob, score = model.score(val, side)
            typical = model.mean
            model.update(val)
            if score > 0.0:
                rec = {"job_id": self.job_id, "result_type": "record",
                       "timestamp": bucket_ts,
                       "bucket_span": self.bucket_span // 1000,
                       "detector_index": di, "function": func,
                       "probability": prob, "record_score": score,
                       "initial_record_score": score,
                       "actual": [val], "typical": [typical],
                       "is_interim": False}
                if det.get("field_name"):
                    rec["field_name"] = det["field_name"]
                if by is not None:
                    rec["by_field_name"] = det["by_field_name"]
                    rec["by_field_value"] = by
                if part is not None:
                    rec["partition_field_name"] = det["partition_field_name"]
                    rec["partition_field_value"] = part
                records.append(rec)
                max_score = max(max_score, score)
        self.results.append(
            {"job_id": self.job_id, "result_type": "bucket",
             "timestamp": bucket_ts,
             "bucket_span": self.bucket_span // 1000,
             "anomaly_score": max_score,
             "initial_anomaly_score": max_score,
             "event_count": sum(a.count for a in accs.values()),
             "is_interim": False})
        self.results.extend(records)

    # -- snapshots -------------------------------------------------------
    def snapshot(self) -> dict:
        snap = {"job_id": self.job_id,
                "snapshot_id": str(len(self.snapshots) + 1),
                "timestamp": _now_ms(),
                "snapshot_doc_count": len(self.models),
                "_models": [(k, m.n, m.mean, m.m2)
                            for k, m in self.models.items()]}
        self.snapshots.append(snap)
        return snap

    def revert(self, snapshot_id: str) -> dict:
        for snap in self.snapshots:
            if snap["snapshot_id"] == snapshot_id:
                self.models = {}
                for k, n, mean, m2 in snap["_models"]:
                    m = _SeriesModel()
                    m.n, m.mean, m.m2 = n, mean, m2
                    self.models[k] = m
                return snap
        raise ResourceNotFoundError(
            f"No model snapshot with id [{snapshot_id}] exists for job "
            f"[{self.job_id}]")


# ---------------------------------------------------------------------------
# Trained-model inference: padded tree arrays evaluated in one XLA program
# ---------------------------------------------------------------------------

_EVAL_TREES = None


def _eval_trees(X, feats, thresh, left, right, dleft, depth):
    """Walk every (tree, doc) pair down to its leaf node index.

    X: (n, f) float32; feats/left/right/dleft: (T, N) int32 (feat = -1
    marks a leaf); thresh: (T, N) float32.  Returns leaf node indices
    (T, n) int32.  One fori_loop iteration per level — data-independent
    trip count, so XLA compiles a single static program
    (vs the reference's per-doc recursion in
    ``inference/trainedmodel/tree/Tree.java``).
    """
    global _EVAL_TREES
    if _EVAL_TREES is None:
        import jax
        import jax.numpy as jnp

        def kern(X, feats, thresh, left, right, dleft, depth):
            n = X.shape[0]

            def one_tree(tf, tt, tl, tr, td):
                idx = jnp.zeros((n,), dtype=jnp.int32)

                def body(_, idx):
                    f = tf[idx]                      # (n,)
                    is_leaf = f < 0
                    xv = jnp.take_along_axis(
                        X, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
                    go_left = jnp.where(jnp.isnan(xv),
                                        td[idx].astype(bool),
                                        xv < tt[idx])
                    nxt = jnp.where(go_left, tl[idx], tr[idx])
                    return jnp.where(is_leaf, idx, nxt)

                return jax.lax.fori_loop(0, depth, body, idx)

            return jax.vmap(one_tree)(feats, thresh, left, right, dleft)

        _EVAL_TREES = jax.jit(kern, static_argnames=("depth",))
    return _EVAL_TREES(X, feats, thresh, left, right, dleft, depth)


class TrainedModel:
    """A parsed tree/ensemble definition flattened to device arrays.

    Reference format: ``inference/trainedmodel/ensemble/Ensemble.java``
    and ``tree/Tree.java`` — the JSON model definition is identical; the
    evaluation strategy is not (see module docstring).
    """

    def __init__(self, model_id: str, body: dict):
        self.model_id = model_id
        self.config = dict(body, model_id=model_id,
                           create_time=_now_ms())
        inf_cfg = body.get("inference_config") or {}
        self.task = next(iter(inf_cfg), "regression")
        definition = body.get("definition")
        self.preprocessors = (definition or {}).get("preprocessors") or []
        self.feature_names: List[str] = []
        self.trees: List[dict] = []
        self.weights: List[float] = []
        self.aggregate = "weighted_sum"
        self.classification_labels: List[str] = []
        self._arrays = None
        self._depth = 1
        if definition:
            self._parse(definition.get("trained_model") or {})
        self.stats = {"inference_count": 0, "failure_count": 0,
                      "cache_miss_count": 0}

    def _parse(self, tm: dict) -> None:
        if "tree" in tm:
            t = tm["tree"]
            self.feature_names = t.get("feature_names") or []
            self.trees = [t]
            self.weights = [1.0]
            self.classification_labels = \
                t.get("classification_labels") or []
        elif "ensemble" in tm:
            ens = tm["ensemble"]
            self.feature_names = ens.get("feature_names") or []
            agg = ens.get("aggregate_output") or {}
            self.aggregate = next(iter(agg), "weighted_sum")
            spec = agg.get(self.aggregate) or {}
            raw_w = spec.get("weights")
            self.classification_labels = \
                ens.get("classification_labels") or []
            for m in ens.get("trained_models") or []:
                if "tree" not in m:
                    raise IllegalArgumentError(
                        "ensemble members must be trees")
                self.trees.append(m["tree"])
                if not self.feature_names:
                    self.feature_names = m["tree"].get(
                        "feature_names") or []
            self.weights = list(raw_w) if raw_w else [1.0] * len(self.trees)
        else:
            raise IllegalArgumentError(
                "[definition.trained_model] must contain [tree] or "
                "[ensemble]")
        if self.trees:
            self._flatten()

    def _flatten(self) -> None:
        max_nodes = max(len(t["tree_structure"]) for t in self.trees)
        T = len(self.trees)
        feats = np.full((T, max_nodes), -1, dtype=np.int32)
        thresh = np.zeros((T, max_nodes), dtype=np.float32)
        left = np.zeros((T, max_nodes), dtype=np.int32)
        right = np.zeros((T, max_nodes), dtype=np.int32)
        dleft = np.zeros((T, max_nodes), dtype=np.int32)
        n_classes = max(1, len(self.classification_labels))
        leaves = np.zeros((T, max_nodes, n_classes), dtype=np.float32)
        depth = 1
        for ti, t in enumerate(self.trees):
            nodes = {n.get("node_index", i): n
                     for i, n in enumerate(t["tree_structure"])}
            for ni, node in nodes.items():
                if "left_child" in node:
                    feats[ti, ni] = node.get("split_feature", 0)
                    thresh[ti, ni] = node.get("threshold", 0.0)
                    left[ti, ni] = node["left_child"]
                    right[ti, ni] = node["right_child"]
                    # the reference defaults default_left to TRUE
                    # (inference/trainedmodel/tree/TreeNode.java)
                    dleft[ti, ni] = 0 if node.get(
                        "default_left") is False else 1
                else:
                    lv = node.get("leaf_value", 0.0)
                    if isinstance(lv, list):
                        leaves[ti, ni, :len(lv)] = lv
                    else:
                        leaves[ti, ni, 0] = lv

            def _d(ni, seen=()):
                node = nodes.get(ni)
                if node is None or "left_child" not in node or ni in seen:
                    return 1
                s = seen + (ni,)
                return 1 + max(_d(node["left_child"], s),
                               _d(node["right_child"], s))
            depth = max(depth, _d(0))
        self._arrays = (feats, thresh, left, right, dleft, leaves)
        self._depth = depth

    # -- feature assembly ------------------------------------------------
    def _vectorize(self, docs: List[dict]) -> np.ndarray:
        X = np.full((len(docs), max(1, len(self.feature_names))),
                    np.nan, dtype=np.float32)
        for i, doc in enumerate(docs):
            d = dict(doc)
            for pp in self.preprocessors:
                self._preprocess(pp, d)
            for j, name in enumerate(self.feature_names):
                v = d.get(name)
                if isinstance(v, bool):
                    v = int(v)
                if isinstance(v, (int, float)):
                    X[i, j] = v
        return X

    @staticmethod
    def _preprocess(pp: dict, d: dict) -> None:
        kind = next(iter(pp), None)
        spec = pp.get(kind) or {}
        field = spec.get("field")
        if kind == "one_hot_encoding":
            for val, feat in (spec.get("hot_map") or {}).items():
                d[feat] = 1 if str(d.get(field)) == val else 0
        elif kind == "frequency_encoding":
            d[spec.get("feature_name")] = (
                spec.get("frequency_map") or {}).get(
                    str(d.get(field)), 0.0)
        elif kind == "target_mean_encoding":
            d[spec.get("feature_name")] = (
                spec.get("target_map") or {}).get(
                    str(d.get(field)), spec.get("default_value", 0.0))

    # -- inference -------------------------------------------------------
    def infer(self, docs: List[dict],
              inference_config: Optional[dict] = None) -> List[dict]:
        import jax.numpy as jnp

        if self._arrays is None:
            raise IllegalArgumentError(
                f"[{self.model_id}] has no model definition")
        X = self._vectorize(docs)
        feats, thresh, left, right, dleft, leaves = self._arrays
        idx = np.asarray(_eval_trees(
            jnp.asarray(X), jnp.asarray(feats), jnp.asarray(thresh),
            jnp.asarray(left), jnp.asarray(right), jnp.asarray(dleft),
            self._depth))                              # (T, n)
        per_tree = leaves[np.arange(len(self.trees))[:, None], idx]
        # per_tree: (T, n, C)
        w = np.asarray(self.weights, dtype=np.float32)[:, None, None]
        self.stats["inference_count"] += len(docs)
        cfg = dict((inference_config or {}).get(self.task) or {})
        base_cfg = (self.config.get("inference_config") or {}).get(
            self.task) or {}
        num_top = cfg.get("num_top_classes",
                          base_cfg.get("num_top_classes", 0))
        results_field = cfg.get(
            "results_field", base_cfg.get("results_field", "predicted_value"))
        out: List[dict] = []
        if self.task == "classification":
            labels = self.classification_labels or ["0", "1"]
            if per_tree.shape[2] > 1:
                scores = (per_tree * w).sum(axis=0)   # (n, C)
                e = np.exp(scores - scores.max(axis=1, keepdims=True))
                probs = e / e.sum(axis=1, keepdims=True)
            else:
                margin = (per_tree[:, :, 0] * w[:, :, 0]).sum(axis=0)
                p1 = 1.0 / (1.0 + np.exp(-margin))
                probs = np.stack([1.0 - p1, p1], axis=1)
            for i in range(len(docs)):
                order = np.argsort(-probs[i])
                top = [{"class_name": labels[c] if c < len(labels)
                        else str(c),
                        "class_probability": float(probs[i, c]),
                        "class_score": float(probs[i, c])}
                       for c in order[:max(num_top, 1)]]
                r = {results_field: top[0]["class_name"],
                     "prediction_probability": top[0]["class_probability"]}
                if num_top:
                    r["top_classes"] = top
                out.append(r)
        else:
            if self.aggregate == "logistic_regression":
                margin = (per_tree[:, :, 0] * w[:, :, 0]).sum(axis=0)
                vals = 1.0 / (1.0 + np.exp(-margin))
            elif self.aggregate == "weighted_mode":
                vals = []
                for i in range(per_tree.shape[1]):
                    votes: Dict[float, float] = {}
                    for t in range(per_tree.shape[0]):
                        v = float(per_tree[t, i, 0])
                        votes[v] = votes.get(v, 0.0) + float(w[t, 0, 0])
                    vals.append(max(votes.items(), key=lambda kv: kv[1])[0])
                vals = np.asarray(vals)
            else:                                      # weighted_sum
                vals = (per_tree[:, :, 0] * w[:, :, 0]).sum(axis=0)
            out = [{results_field: float(v)} for v in vals]
        return out


# ---------------------------------------------------------------------------
# Dataframe analytics device kernels
# ---------------------------------------------------------------------------

def _knn_outlier_scores(X: np.ndarray, k: int) -> np.ndarray:
    """kNN-distance outlier scores in [0, 1].

    The pairwise-distance matrix is computed in its matmul form so XLA
    maps the O(n^2 f) work onto the MXU; ``top_k`` extracts the k nearest.
    Score = sigmoid of the z-scored mean-kNN distance (the reference
    ensembles distance_kth_nn / distance_knn / lof —
    ``dataframe/process/` via the native process; one robust member
    suffices here and keeps the kernel single-pass).
    """
    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnames=("kk",))
    def kern(Xd, kk):
        sq = jnp.sum(Xd * Xd, axis=1)
        d2 = sq[:, None] + sq[None, :] - 2.0 * (Xd @ Xd.T)
        d2 = jnp.maximum(d2, 0.0)
        n = Xd.shape[0]
        d2 = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, d2)
        nn = -jax.lax.top_k(-d2, kk)[0]               # (n, k) smallest
        dk = jnp.sqrt(jnp.mean(nn, axis=1))
        mu = jnp.mean(dk)
        sd = jnp.std(dk) + 1e-9
        return jax.nn.sigmoid((dk - mu) / sd * 2.0 - 2.0)

    if X.shape[0] < 2:
        # no neighbors to measure against — nothing is an outlier
        return np.zeros((X.shape[0],), dtype=np.float32)
    return np.asarray(kern(jnp.asarray(X, dtype=jnp.float32),
                           min(k, X.shape[0] - 1)))


def _train_logreg(X: np.ndarray, y: np.ndarray, n_classes: int,
                  steps: int = 500, lr: float = 0.5) -> np.ndarray:
    """Full-batch multinomial logistic regression on device."""
    import jax
    import jax.numpy as jnp

    n, f = X.shape
    Xb = jnp.concatenate(
        [jnp.asarray(X, dtype=jnp.float32),
         jnp.ones((n, 1), dtype=jnp.float32)], axis=1)
    Y = jax.nn.one_hot(jnp.asarray(y), n_classes, dtype=jnp.float32)

    @partial(jax.jit, static_argnames=("nsteps",))
    def train(Xb, Y, nsteps):
        W0 = jnp.zeros((Xb.shape[1], Y.shape[1]), dtype=jnp.float32)

        def step(_, W):
            p = jax.nn.softmax(Xb @ W, axis=1)
            g = Xb.T @ (p - Y) / Xb.shape[0] + 1e-4 * W
            return W - lr * g

        return jax.lax.fori_loop(0, nsteps, step, W0)

    return np.asarray(train(Xb, Y, steps))


# ---------------------------------------------------------------------------
# The service
# ---------------------------------------------------------------------------

class MlService:
    """Config management + orchestration over the REST seams.

    ``search_fn(index, body) -> response`` and
    ``bulk_fn(index, action_lines)`` ride the cluster-aware internal
    dispatch exactly like transform/rollup (rest/api.py seam), so ML
    results indices behave like any other index.
    """

    DF_PAGE = 1000

    def __init__(self, search_fn: Callable[[str, dict], dict],
                 bulk_fn: Callable[[str, List[dict]], dict]):
        self.search_fn = search_fn
        self.bulk_fn = bulk_fn
        self.jobs: Dict[str, AnomalyJob] = {}
        self.datafeeds: Dict[str, dict] = {}
        self.models: Dict[str, TrainedModel] = {}
        self.analytics: Dict[str, dict] = {}
        self.calendars: Dict[str, dict] = {}
        self.filters: Dict[str, dict] = {}
        self.upgrade_mode = False

    # ==== anomaly detection jobs =======================================
    def put_job(self, job_id: str, body: dict) -> dict:
        if job_id in self.jobs:
            raise ResourceAlreadyExistsError(
                f"The job cannot be created with the Id '{job_id}'. "
                f"The Id is already used.")
        job = AnomalyJob(job_id, body)
        self.jobs[job_id] = job
        return job.config

    def _job(self, job_id: str) -> AnomalyJob:
        job = self.jobs.get(job_id)
        if job is None:
            raise ResourceNotFoundError(
                f"No known job with id '{job_id}'")
        return job

    def _select_jobs(self, job_id: Optional[str]) -> List[AnomalyJob]:
        if job_id in (None, "", "_all", "*"):
            return [self.jobs[k] for k in sorted(self.jobs)]
        return [self._job(job_id)]

    def get_jobs(self, job_id: Optional[str]) -> dict:
        jobs = self._select_jobs(job_id)
        return {"count": len(jobs), "jobs": [j.config for j in jobs]}

    def job_stats(self, job_id: Optional[str]) -> dict:
        jobs = self._select_jobs(job_id)
        return {"count": len(jobs), "jobs": [
            {"job_id": j.job_id, "state": j.state,
             "data_counts": dict(j.counts, job_id=j.job_id),
             "model_size_stats": {
                 "job_id": j.job_id, "result_type": "model_size_stats",
                 "model_bytes": 64 * len(j.models),
                 "total_by_field_count": len(
                     {k[1] for k in j.models if k[1] is not None}),
                 "total_partition_field_count": len(
                     {k[2] for k in j.models if k[2] is not None}),
                 "bucket_allocation_failures_count": 0,
                 "memory_status": "ok"},
             "timing_stats": {"job_id": j.job_id,
                              "bucket_count": j.counts["bucket_count"]}}
            for j in jobs]}

    def delete_job(self, job_id: str, force: bool = False) -> dict:
        job = self._job(job_id)
        if job.state == "opened" and not force:
            raise ElasticsearchError(
                f"Cannot delete job [{job_id}] because the job is opened")
        for feed_id, feed in list(self.datafeeds.items()):
            if feed["config"].get("job_id") == job_id:
                if force:
                    del self.datafeeds[feed_id]
                else:
                    raise ElasticsearchError(
                        f"Cannot delete job [{job_id}] because datafeed "
                        f"[{feed_id}] refers to it")
        del self.jobs[job_id]
        return {"acknowledged": True}

    def open_job(self, job_id: str) -> dict:
        self._job(job_id).state = "opened"
        return {"opened": True, "node": ""}

    def close_job(self, job_id: str, force: bool = False) -> dict:
        job = self._job(job_id)
        job.flush()
        self._index_results(job)
        job.snapshot()
        job.state = "closed"
        return {"closed": True}

    def post_data(self, job_id: str, payload: bytes) -> dict:
        job = self._job(job_id)
        if job.state != "opened":
            raise ElasticsearchError(
                f"Cannot process data because job [{job_id}] is not open",
                )
        records: List[dict] = []
        text = payload.decode() if isinstance(payload, (bytes, bytearray)) \
            else str(payload)
        try:
            # a single JSON document or array (possibly pretty-printed)
            doc = json.loads(text)
            records = doc if isinstance(doc, list) else [doc]
        except json.JSONDecodeError:
            # NDJSON: one record per line
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                doc = json.loads(line)
                if isinstance(doc, list):
                    records.extend(doc)
                else:
                    records.append(doc)
        job.post(records)
        return dict(job.counts, job_id=job_id)

    def flush_job(self, job_id: str) -> dict:
        job = self._job(job_id)
        job.flush()
        self._index_results(job)
        return {"flushed": True,
                "last_finalized_bucket_end": job._latest_finalized}

    def _index_results(self, job: AnomalyJob) -> None:
        """Mirror finalized results into the shared results index."""
        fresh = [r for r in job.results if not r.get("_indexed")]
        if not fresh:
            return
        lines: List[dict] = []
        for r in fresh:
            r["_indexed"] = True
            doc = {k: v for k, v in r.items() if k != "_indexed"}
            lines.append({"index": {}})
            lines.append(doc)
        try:
            self.bulk_fn(RESULTS_INDEX, lines)
        except ElasticsearchError:
            pass  # results remain queryable through the in-memory APIs

    # -- results ---------------------------------------------------------
    def get_buckets(self, job_id: str, body: Optional[dict] = None,
                    params: Optional[dict] = None) -> dict:
        job = self._job(job_id)
        body = body or {}
        buckets = [dict((k, v) for k, v in r.items() if k != "_indexed")
                   for r in job.results
                   if r["result_type"] == "bucket"]
        start = _parse_time(body.get("start") or (params or {}).get("start"))
        end = _parse_time(body.get("end") or (params or {}).get("end"))
        if start is not None:
            buckets = [b for b in buckets if b["timestamp"] >= start]
        if end is not None:
            buckets = [b for b in buckets if b["timestamp"] < end]
        threshold = float(body.get("anomaly_score", 0.0) or 0.0)
        if threshold:
            buckets = [b for b in buckets
                       if b["anomaly_score"] >= threshold]
        buckets.sort(key=lambda b: b["timestamp"])
        return {"count": len(buckets), "buckets": buckets}

    def get_records(self, job_id: str,
                    body: Optional[dict] = None,
                    params: Optional[dict] = None) -> dict:
        job = self._job(job_id)
        body = body or {}
        params = params or {}
        records = [dict((k, v) for k, v in r.items() if k != "_indexed")
                   for r in job.results
                   if r["result_type"] == "record"]
        start = _parse_time(body.get("start") or params.get("start"))
        end = _parse_time(body.get("end") or params.get("end"))
        if start is not None:
            records = [r for r in records if r["timestamp"] >= start]
        if end is not None:
            records = [r for r in records if r["timestamp"] < end]
        threshold = float(body.get("record_score")
                          or params.get("record_score") or 0.0)
        if threshold:
            records = [r for r in records
                       if r["record_score"] >= threshold]
        records.sort(key=lambda r: (-r["record_score"], r["timestamp"]))
        return {"count": len(records), "records": records}

    def get_overall_buckets(self, job_id: str,
                            body: Optional[dict] = None) -> dict:
        jobs = self._select_jobs(job_id)
        by_ts: Dict[int, List[float]] = {}
        for j in jobs:
            for r in j.results:
                if r["result_type"] == "bucket":
                    by_ts.setdefault(r["timestamp"], []).append(
                        r["anomaly_score"])
        buckets = [{"timestamp": ts, "bucket_span":
                    max(j.bucket_span for j in jobs) // 1000,
                    "overall_score": max(scores),
                    "jobs": [{"job_id": j.job_id} for j in jobs],
                    "is_interim": False, "result_type": "overall_bucket"}
                   for ts, scores in sorted(by_ts.items())]
        return {"count": len(buckets), "overall_buckets": buckets}

    # -- model snapshots -------------------------------------------------
    def get_model_snapshots(self, job_id: str) -> dict:
        job = self._job(job_id)
        snaps = [{k: v for k, v in s.items() if k != "_models"}
                 for s in job.snapshots]
        return {"count": len(snaps), "model_snapshots": snaps}

    def revert_model_snapshot(self, job_id: str,
                              snapshot_id: str) -> dict:
        snap = self._job(job_id).revert(snapshot_id)
        return {"model": {k: v for k, v in snap.items()
                          if k != "_models"}}

    # ==== datafeeds =====================================================
    def put_datafeed(self, feed_id: str, body: dict) -> dict:
        if feed_id in self.datafeeds:
            raise ResourceAlreadyExistsError(
                f"A datafeed with id [{feed_id}] already exists")
        job_id = body.get("job_id")
        if not job_id or job_id not in self.jobs:
            raise ResourceNotFoundError(
                f"No known job with id '{job_id}'")
        if not body.get("indices") and not body.get("indexes"):
            raise IllegalArgumentError("[indices] is required")
        cfg = dict(body, datafeed_id=feed_id)
        self.datafeeds[feed_id] = {"config": cfg, "state": "stopped",
                                   "search_count": 0}
        return cfg

    def _feed(self, feed_id: str) -> dict:
        feed = self.datafeeds.get(feed_id)
        if feed is None:
            raise ResourceNotFoundError(
                f"No datafeed with id [{feed_id}] exists")
        return feed

    def get_datafeeds(self, feed_id: Optional[str]) -> dict:
        if feed_id in (None, "", "_all", "*"):
            items = [self.datafeeds[k] for k in sorted(self.datafeeds)]
        else:
            items = [self._feed(feed_id)]
        return {"count": len(items),
                "datafeeds": [f["config"] for f in items]}

    def datafeed_stats(self, feed_id: Optional[str]) -> dict:
        if feed_id in (None, "", "_all", "*"):
            items = sorted(self.datafeeds.items())
        else:
            items = [(feed_id, self._feed(feed_id))]
        return {"count": len(items), "datafeeds": [
            {"datafeed_id": k, "state": f["state"],
             "timing_stats": {"job_id": f["config"].get("job_id"),
                              "search_count": f["search_count"]}}
            for k, f in items]}

    def delete_datafeed(self, feed_id: str) -> dict:
        self._feed(feed_id)
        del self.datafeeds[feed_id]
        return {"acknowledged": True}

    def start_datafeed(self, feed_id: str, start: Any = None,
                       end: Any = None) -> dict:
        """Drain the source into the job synchronously.

        The reference's ``DatafeedJob`` polls on a timer; here one _start
        call pages [start, end) through the search seam, posts to the
        job, and flushes — same collapse as transform's indexer loop.
        """
        feed = self._feed(feed_id)
        cfg = feed["config"]
        job = self._job(cfg["job_id"])
        if job.state != "opened":
            raise ElasticsearchError(
                f"cannot start datafeed [{feed_id}] because job "
                f"[{job.job_id}] is not open")
        feed["state"] = "started"
        try:
            time_field = job.time_field
            indices = cfg.get("indices") or cfg.get("indexes")
            index = ",".join(indices) if isinstance(indices, list) \
                else indices
            must: List[dict] = [cfg.get("query") or {"match_all": {}}]
            rng: Dict[str, Any] = {}
            s_ms, e_ms = _parse_time(start), _parse_time(end)
            if s_ms is not None:
                rng["gte"] = s_ms
            if e_ms is not None:
                rng["lt"] = e_ms
            if rng:
                must.append({"range": {time_field: dict(
                    rng, format="epoch_millis")}})
            search_after = None
            while True:
                body = {"size": self.DF_PAGE,
                        "query": {"bool": {"filter": must}},
                        "sort": [{time_field: "asc"},
                                 {"_shard_doc": "asc"}]}
                if search_after is not None:
                    body["search_after"] = search_after
                resp = self.search_fn(index, body)
                feed["search_count"] += 1
                hits = resp["hits"]["hits"]
                if not hits:
                    break
                job.post([h["_source"] for h in hits])
                search_after = hits[-1]["sort"]
                if len(hits) < self.DF_PAGE:
                    break
            job.flush()
            self._index_results(job)
        finally:
            feed["state"] = "stopped"
        return {"started": True, "node": ""}

    def stop_datafeed(self, feed_id: str) -> dict:
        self._feed(feed_id)["state"] = "stopped"
        return {"stopped": True}

    def preview_datafeed(self, feed_id: str) -> List[dict]:
        feed = self._feed(feed_id)
        cfg = feed["config"]
        indices = cfg.get("indices") or cfg.get("indexes")
        index = ",".join(indices) if isinstance(indices, list) else indices
        resp = self.search_fn(index, {
            "size": 100, "query": cfg.get("query") or {"match_all": {}}})
        return [h["_source"] for h in resp["hits"]["hits"]]

    # ==== trained models + inference ===================================
    def put_trained_model(self, model_id: str, body: dict) -> dict:
        if model_id in self.models:
            raise ResourceAlreadyExistsError(
                f"Trained machine learning model [{model_id}] already "
                f"exists")
        model = TrainedModel(model_id, body)
        self.models[model_id] = model
        cfg = {k: v for k, v in model.config.items() if k != "definition"}
        return cfg

    def _model(self, model_id: str) -> TrainedModel:
        m = self.models.get(model_id)
        if m is None:
            raise ResourceNotFoundError(
                f"No known trained model with model_id [{model_id}]")
        return m

    def get_trained_models(self, model_id: Optional[str]) -> dict:
        if model_id in (None, "", "_all", "*"):
            models = [self.models[k] for k in sorted(self.models)]
        else:
            models = [self._model(model_id)]
        return {"count": len(models), "trained_model_configs": [
            {k: v for k, v in m.config.items() if k != "definition"}
            for m in models]}

    def trained_model_stats(self, model_id: Optional[str]) -> dict:
        if model_id in (None, "", "_all", "*"):
            models = [self.models[k] for k in sorted(self.models)]
        else:
            models = [self._model(model_id)]
        return {"count": len(models), "trained_model_stats": [
            {"model_id": m.model_id,
             "inference_stats": dict(m.stats,
                                     timestamp=_now_ms())}
            for m in models]}

    def delete_trained_model(self, model_id: str) -> dict:
        self._model(model_id)
        del self.models[model_id]
        return {"acknowledged": True}

    def infer(self, model_id: str, body: dict) -> dict:
        model = self._model(model_id)
        docs = body.get("docs")
        if not isinstance(docs, list) or not docs:
            raise IllegalArgumentError("[docs] must be a non-empty array")
        results = model.infer(docs, body.get("inference_config"))
        return {"inference_results": results}

    # ==== dataframe analytics ==========================================
    def put_analytics(self, aid: str, body: dict) -> dict:
        if aid in self.analytics:
            raise ResourceAlreadyExistsError(
                f"A data frame analytics with id [{aid}] already exists")
        src = body.get("source") or {}
        if not src.get("index"):
            raise IllegalArgumentError("[source.index] is required")
        if not (body.get("dest") or {}).get("index"):
            raise IllegalArgumentError("[dest.index] is required")
        analysis = body.get("analysis") or {}
        kind = next(iter(analysis), None)
        if kind not in ("outlier_detection", "regression",
                        "classification"):
            raise IllegalArgumentError(
                "[analysis] must be one of [outlier_detection, "
                "regression, classification]")
        if kind in ("regression", "classification") and \
                not analysis[kind].get("dependent_variable"):
            raise IllegalArgumentError(
                "[dependent_variable] is required")
        cfg = dict(body, id=aid, create_time=_now_ms(), version="8.0.0")
        self.analytics[aid] = {"config": cfg, "state": "stopped",
                               "progress": []}
        return cfg

    def _analytics(self, aid: str) -> dict:
        a = self.analytics.get(aid)
        if a is None:
            raise ResourceNotFoundError(
                f"No known data frame analytics with id [{aid}]")
        return a

    def get_analytics(self, aid: Optional[str]) -> dict:
        if aid in (None, "", "_all", "*"):
            items = [self.analytics[k] for k in sorted(self.analytics)]
        else:
            items = [self._analytics(aid)]
        return {"count": len(items),
                "data_frame_analytics": [a["config"] for a in items]}

    def analytics_stats(self, aid: Optional[str]) -> dict:
        if aid in (None, "", "_all", "*"):
            items = sorted(self.analytics.items())
        else:
            items = [(aid, self._analytics(aid))]
        return {"count": len(items), "data_frame_analytics": [
            {"id": k, "state": a["state"],
             "progress": a["progress"]} for k, a in items]}

    def delete_analytics(self, aid: str) -> dict:
        self._analytics(aid)
        del self.analytics[aid]
        return {"acknowledged": True}

    def start_analytics(self, aid: str) -> dict:
        a = self._analytics(aid)
        cfg = a["config"]
        a["state"] = "started"
        try:
            self._run_analytics(cfg, a)
        finally:
            a["state"] = "stopped"
        a["progress"] = [
            {"phase": "reindexing", "progress_percent": 100},
            {"phase": "loading_data", "progress_percent": 100},
            {"phase": "analyzing", "progress_percent": 100},
            {"phase": "writing_results", "progress_percent": 100}]
        return {"acknowledged": True}

    def stop_analytics(self, aid: str) -> dict:
        self._analytics(aid)["state"] = "stopped"
        return {"stopped": True}

    def explain_analytics(self, body: dict) -> dict:
        src = (body.get("source") or {}).get("index")
        if not src:
            raise IllegalArgumentError("[source.index] is required")
        docs, fields = self._load_frame(body)
        analysis = body.get("analysis") or {}
        kind = next(iter(analysis), "outlier_detection")
        dep = (analysis.get(kind) or {}).get("dependent_variable")
        included = [f for f in fields if f != dep]
        return {"field_selection": [
            {"name": f, "mapping_types": ["double"], "is_included": True,
             "is_required": False, "feature_type": "numerical"}
            for f in included],
            "memory_estimation": {
                "expected_memory_without_disk":
                    f"{max(1, len(docs) * len(fields) * 8 // 1024)}kb"}}

    # -- frame loading / writing ----------------------------------------
    def _load_frame(self, cfg: dict) -> Tuple[List[dict], List[str]]:
        src = cfg.get("source") or {}
        indices = src.get("index")
        index = ",".join(indices) if isinstance(indices, list) else indices
        analyzed = (cfg.get("analyzed_fields") or {})
        includes = analyzed.get("includes") or []
        excludes = set(analyzed.get("excludes") or [])
        docs: List[dict] = []
        search_after = None
        while True:
            body = {"size": self.DF_PAGE,
                    "query": src.get("query") or {"match_all": {}},
                    "sort": [{"_shard_doc": "asc"}]}
            if search_after is not None:
                body["search_after"] = search_after
            resp = self.search_fn(index, body)
            hits = resp["hits"]["hits"]
            if not hits:
                break
            for h in hits:
                docs.append({"_id": h["_id"], **h["_source"]})
            search_after = hits[-1]["sort"]
            if len(hits) < self.DF_PAGE:
                break
        field_set: set = set()
        for d in docs:
            for k, v in d.items():
                if k == "_id":
                    continue
                if includes and k not in includes:
                    continue
                if k in excludes:
                    continue
                field_set.add(k)
        return docs, sorted(field_set)

    def _numeric_matrix(self, docs: List[dict],
                        fields: List[str]) -> Tuple[np.ndarray, List[str]]:
        numeric = [f for f in fields if any(
            isinstance(d.get(f), (int, float))
            and not isinstance(d.get(f), bool) for d in docs)]
        X = np.zeros((len(docs), len(numeric)), dtype=np.float32)
        for i, d in enumerate(docs):
            for j, f in enumerate(numeric):
                v = d.get(f)
                X[i, j] = float(v) if isinstance(v, (int, float)) \
                    and not isinstance(v, bool) else 0.0
        return X, numeric

    def _run_analytics(self, cfg: dict, state: dict) -> None:
        analysis = cfg["analysis"]
        kind = next(iter(analysis))
        spec = analysis[kind] or {}
        docs, fields = self._load_frame(cfg)
        if not docs:
            raise ElasticsearchError(
                "Unable to start because no documents were found in the "
                "source index")
        dest = cfg["dest"]["index"]
        results_field = (cfg.get("dest") or {}).get(
            "results_field", "ml")
        out_lines: List[dict] = []
        if kind == "outlier_detection":
            X, numeric = self._numeric_matrix(docs, fields)
            if not numeric:
                raise ElasticsearchError(
                    "No numeric fields found for outlier detection")
            # standardize so no single wide-range feature dominates
            mu = X.mean(axis=0)
            sd = X.std(axis=0) + 1e-9
            scores = _knn_outlier_scores(
                (X - mu) / sd, int(spec.get("n_neighbors") or 5))
            for d, s in zip(docs, scores):
                src_doc = {k: v for k, v in d.items() if k != "_id"}
                src_doc[results_field] = {"outlier_score": float(s)}
                out_lines.append({"index": {"_id": d["_id"]}})
                out_lines.append(src_doc)
        elif kind == "regression":
            dep = spec["dependent_variable"]
            train_mask = np.array(
                [isinstance(d.get(dep), (int, float))
                 and not isinstance(d.get(dep), bool) for d in docs])
            feat_fields = [f for f in fields if f != dep]
            X, numeric = self._numeric_matrix(docs, feat_fields)
            if not numeric or not train_mask.any():
                raise ElasticsearchError(
                    "Unable to train: no numeric features or no labeled "
                    "rows")
            y = np.array([float(d.get(dep) or 0.0) for d in docs],
                         dtype=np.float32)
            pct = float(spec.get("training_percent", 100.0))
            rng = np.random.RandomState(
                int(spec.get("randomize_seed", 42)) & 0x7FFFFFFF)
            is_training = train_mask & (
                rng.uniform(size=len(docs)) * 100.0 < pct
                if pct < 100.0 else np.ones(len(docs), bool))
            if not is_training.any():
                is_training = train_mask
            Xb = np.concatenate(
                [X, np.ones((len(docs), 1), np.float32)], axis=1)
            # least-squares solve on device (vs the reference's boosted
            # trees trained in the native process)
            w, *_ = np.linalg.lstsq(Xb[is_training], y[is_training],
                                    rcond=None)
            pred = Xb @ w
            pred_field = spec.get("prediction_field_name",
                                  f"{dep}_prediction")
            for i, d in enumerate(docs):
                src_doc = {k: v for k, v in d.items() if k != "_id"}
                src_doc[results_field] = {
                    pred_field: float(pred[i]),
                    "is_training": bool(is_training[i])}
                out_lines.append({"index": {"_id": d["_id"]}})
                out_lines.append(src_doc)
            resid = y[train_mask] - pred[train_mask]
            state["metrics"] = {
                "mse": float(np.mean(resid ** 2)),
                "r_squared": float(
                    1.0 - np.sum(resid ** 2)
                    / max(np.sum((y[train_mask]
                                  - y[train_mask].mean()) ** 2), 1e-9))}
        else:                                          # classification
            dep = spec["dependent_variable"]
            labeled = [d for d in docs if d.get(dep) is not None]
            classes = sorted({str(d[dep]) for d in labeled})
            if len(classes) < 2:
                raise ElasticsearchError(
                    "Classification requires at least 2 classes")
            cls_idx = {c: i for i, c in enumerate(classes)}
            feat_fields = [f for f in fields if f != dep]
            X, numeric = self._numeric_matrix(docs, feat_fields)
            if not numeric:
                raise ElasticsearchError(
                    "No numeric features found for classification")
            mu = X.mean(axis=0)
            sd = X.std(axis=0) + 1e-9
            Xn = (X - mu) / sd
            train_mask = np.array([d.get(dep) is not None for d in docs])
            y = np.array([cls_idx.get(str(d.get(dep)), 0) for d in docs])
            W = _train_logreg(Xn[train_mask], y[train_mask], len(classes))
            Xb = np.concatenate(
                [Xn, np.ones((len(docs), 1), np.float32)], axis=1)
            logits = Xb @ W
            e = np.exp(logits - logits.max(axis=1, keepdims=True))
            probs = e / e.sum(axis=1, keepdims=True)
            pred_field = spec.get("prediction_field_name",
                                  f"{dep}_prediction")
            num_top = int(spec.get("num_top_classes", 2))
            for i, d in enumerate(docs):
                src_doc = {k: v for k, v in d.items() if k != "_id"}
                order = np.argsort(-probs[i])
                top = [{"class_name": classes[c],
                        "class_probability": float(probs[i, c])}
                       for c in order[:num_top]]
                src_doc[results_field] = {
                    pred_field: classes[int(order[0])],
                    "prediction_probability": float(probs[i, order[0]]),
                    "top_classes": top,
                    "is_training": bool(train_mask[i])}
                out_lines.append({"index": {"_id": d["_id"]}})
                out_lines.append(src_doc)
            correct = sum(
                1 for i in range(len(docs))
                if train_mask[i] and int(np.argmax(probs[i])) == y[i])
            state["metrics"] = {"accuracy":
                                correct / max(1, int(train_mask.sum()))}
        self.bulk_fn(dest, out_lines)

    # ==== calendars / filters / info ===================================
    def put_calendar(self, cal_id: str, body: Optional[dict]) -> dict:
        if cal_id in self.calendars:
            raise ResourceAlreadyExistsError(
                f"Cannot create calendar with id [{cal_id}] as it "
                f"already exists")
        cal = {"calendar_id": cal_id,
               "job_ids": (body or {}).get("job_ids") or [],
               "description": (body or {}).get("description"),
               "events": []}
        self.calendars[cal_id] = cal
        return {k: v for k, v in cal.items() if k != "events"}

    def get_calendars(self, cal_id: Optional[str]) -> dict:
        if cal_id in (None, "", "_all", "*"):
            items = [self.calendars[k] for k in sorted(self.calendars)]
        else:
            if cal_id not in self.calendars:
                raise ResourceNotFoundError(
                    f"No calendar with id [{cal_id}]")
            items = [self.calendars[cal_id]]
        return {"count": len(items), "calendars": [
            {k: v for k, v in c.items() if k != "events"}
            for c in items]}

    def delete_calendar(self, cal_id: str) -> dict:
        if cal_id not in self.calendars:
            raise ResourceNotFoundError(f"No calendar with id [{cal_id}]")
        del self.calendars[cal_id]
        return {"acknowledged": True}

    def post_calendar_events(self, cal_id: str, body: dict) -> dict:
        if cal_id not in self.calendars:
            raise ResourceNotFoundError(f"No calendar with id [{cal_id}]")
        events = body.get("events") or []
        for ev in events:
            ev.setdefault("calendar_id", cal_id)
        self.calendars[cal_id]["events"].extend(events)
        return {"events": events}

    def get_calendar_events(self, cal_id: str) -> dict:
        if cal_id not in self.calendars:
            raise ResourceNotFoundError(f"No calendar with id [{cal_id}]")
        events = self.calendars[cal_id]["events"]
        return {"count": len(events), "events": events}

    def put_filter(self, filter_id: str, body: dict) -> dict:
        if filter_id in self.filters:
            raise ResourceAlreadyExistsError(
                f"A filter with id [{filter_id}] already exists")
        f = {"filter_id": filter_id,
             "description": body.get("description", ""),
             "items": sorted(body.get("items") or [])}
        self.filters[filter_id] = f
        return f

    def get_filters(self, filter_id: Optional[str]) -> dict:
        if filter_id in (None, "", "_all", "*"):
            items = [self.filters[k] for k in sorted(self.filters)]
        else:
            if filter_id not in self.filters:
                raise ResourceNotFoundError(
                    f"No filter with id [{filter_id}]")
            items = [self.filters[filter_id]]
        return {"count": len(items), "filters": items}

    def delete_filter(self, filter_id: str) -> dict:
        if filter_id not in self.filters:
            raise ResourceNotFoundError(
                f"No filter with id [{filter_id}]")
        del self.filters[filter_id]
        return {"acknowledged": True}

    def info(self) -> dict:
        return {
            "defaults": {
                "anomaly_detectors": {
                    "model_memory_limit": "1gb",
                    "categorization_examples_limit": 4,
                    "model_snapshot_retention_days": 10,
                    "daily_model_snapshot_retention_after_days": 1},
                "datafeeds": {"scroll_size": 1000}},
            "upgrade_mode": self.upgrade_mode,
            "native_code": {"version": "8.0.0",
                            "build_hash": "tpu-native"},
            "limits": {"effective_max_model_memory_limit": "4gb",
                       "total_ml_memory": "4gb"}}

    def set_upgrade_mode(self, enabled: bool) -> dict:
        self.upgrade_mode = enabled
        return {"acknowledged": True}


# ---------------------------------------------------------------------------
# The `inference` ingest processor
# ---------------------------------------------------------------------------

#: process-global model registry the processor resolves through — mirrors
#: the ingest registry itself (see xpack/enrich.py for the same pattern)
_MODEL_REGISTRY: Dict[str, TrainedModel] = {}


def registry_bind(svc: MlService) -> None:
    """Point the ingest-visible registry at a service's models."""
    global _MODEL_REGISTRY
    _MODEL_REGISTRY = svc.models  # type: ignore[assignment]


class InferenceProcessor(Processor):
    """``inference`` ingest processor
    (``x-pack/plugin/ml/.../InferenceProcessor.java``)."""

    type_name = "inference"

    def __init__(self, body):
        super().__init__(body)
        self.model_id = _req(body, "model_id", "inference")
        self.target_field = body.get("target_field", "ml.inference")
        self.field_map = body.get("field_map") or {}
        self.inference_config = body.get("inference_config")

    def run(self, doc):
        model = _MODEL_REGISTRY.get(self.model_id)
        if model is None:
            raise ProcessorException(
                f"Could not find trained model [{self.model_id}]")
        src = doc.source
        feats = dict(src)
        for from_f, to_f in self.field_map.items():
            if from_f in src:
                feats[to_f] = src[from_f]
        result = model.infer([feats], self.inference_config)[0]
        result["model_id"] = self.model_id
        doc.set(self.target_field, result)


register_processor(InferenceProcessor)
