"""Deprecation warnings: per-response ``Warning`` headers, a deprecation
log, and the ``/_migration/deprecations`` checkup API.

Reference: ``server/.../common/logging/DeprecationLogger.java`` (emits
RFC-7234 ``299`` warn-code response headers through the thread-local
``HeaderWarning`` and writes rate-limited deprecation log entries) +
``x-pack/plugin/deprecation/.../DeprecationInfoAction.java`` (runs a
checklist of cluster/node/index checks and buckets the findings).

The thread-local response-header channel is the same design: handlers
call ``warn()`` anywhere below the dispatcher; the HTTP layer drains the
accumulated warnings into ``Warning:`` headers after the handler
returns.  Each (key) is emitted once per request and once per process
into the in-memory log ring, mirroring the reference's deduplication.
"""
from __future__ import annotations

import contextvars
import time
from typing import Callable, Dict, List, Optional

_WARN_PREFIX = '299 Elasticsearch-8.0.0-tpu "'

#: Per-request accumulator.  A ContextVar holding a MUTABLE container:
#: the HTTP layer binds a fresh container before dispatch, and because
#: handlers may run on a worker thread (cluster mode dispatches through
#: an executor with ``contextvars.copy_context()``), warn() mutates the
#: shared container instead of rebinding the var — mutations are visible
#: to the draining side regardless of which thread the handler ran on.
_accum: contextvars.ContextVar = contextvars.ContextVar(
    "deprecation_accum")

#: process-wide deprecation log ring (the reference writes to the
#: ``_deprecation.json`` log file; bounded so it can't grow unbounded)
_LOG: List[dict] = []
_LOG_KEYS: set = set()
_LOG_MAX = 1000


def _container() -> dict:
    try:
        return _accum.get()
    except LookupError:
        c = {"msgs": [], "keys": set()}
        _accum.set(c)
        return c


def begin_request() -> None:
    """Reset the per-request warning accumulator (dispatcher calls this
    at entry; ``HeaderWarning.setThreadContext`` analog).  Clears the
    bound container IN PLACE so a container bound by an outer layer
    (the HTTP connection task) stays shared with it."""
    c = _container()
    c["msgs"].clear()
    c["keys"].clear()


def warn(key: str, message: str) -> None:
    """Record a deprecation: once per request in the response headers,
    once per process in the log."""
    c = _container()
    if key not in c["keys"]:
        c["keys"].add(key)
        c["msgs"].append(message)
    if key not in _LOG_KEYS and len(_LOG) < _LOG_MAX:
        _LOG_KEYS.add(key)
        _LOG.append({"key": key, "message": message,
                     "@timestamp": int(time.time() * 1000)})


def drain_warnings() -> List[str]:
    """Formatted ``Warning`` header values accumulated this request."""
    c = _container()
    out = [f'{_WARN_PREFIX}{m}"' for m in c["msgs"]]
    c["msgs"].clear()
    c["keys"].clear()
    return out


def deprecation_log() -> List[dict]:
    return list(_LOG)


# ---------------------------------------------------------------------------
# /_migration/deprecations checks
# ---------------------------------------------------------------------------

def deprecation_info(get_indices: Callable[[], Dict[str, dict]],
                     get_cluster_settings: Callable[[], dict],
                     legacy_templates: Callable[[], List[str]]) -> dict:
    """Run the checkup list (``DeprecationChecks.java``): each check
    returns issues shaped ``{level, message, url, details}``."""
    cluster_issues: List[dict] = []
    index_issues: Dict[str, List[dict]] = {}

    tmpl = legacy_templates()
    if tmpl:
        cluster_issues.append({
            "level": "warning",
            "message": "Legacy index templates are deprecated in favor "
                       "of composable templates.",
            "url": "https://ela.st/es-deprecation-7-legacy-index-"
                   "templates",
            "details": f"Legacy index templates {sorted(tmpl)} are in "
                       f"use."})

    for name, settings in get_indices().items():
        issues = []
        if str(settings.get("index.soft_deletes.enabled")) == "false":
            issues.append({
                "level": "warning",
                "message": "Setting [index.soft_deletes.enabled] to "
                           "[false] is deprecated.",
                "url": "https://ela.st/es-deprecation-7-soft-deletes",
                "details": "soft deletes cannot be disabled in 8.0"})
        shards = settings.get("index.number_of_shards")
        try:
            if shards is not None and int(shards) > 1024:
                issues.append({
                    "level": "critical",
                    "message": "Number of shards is too large.",
                    "url": "https://ela.st/es-max-shards",
                    "details": f"index has {shards} shards"})
        except (TypeError, ValueError):
            pass
        if issues:
            index_issues[name] = issues

    return {"cluster_settings": cluster_issues,
            "node_settings": [],
            "index_settings": index_issues,
            "ml_settings": []}
