"""Aggregation trees as planner stages of the one-dispatch pipeline.

The legacy analytics path runs aggregations as a host-side per-query
side pass AFTER retrieval: ``shard_search.py`` executes the query tree
per segment a second time to get doc masks, then walks
``search/aggregations.py`` collect/reduce. This module folds the agg
tree into the :class:`~.query_planner.FusedPlan` instead:

- :func:`lower_aggs` compiles an ``aggs`` body into an :class:`AggPlan`
  when every node of the tree is one the planes can serve as a masked
  segment-reduce stage (terms, histogram/date_histogram with nested
  sub-agg trees, the numeric metrics, percentiles, cardinality at both
  the exact-set and HLL++ regimes, and field-sorted top_hits). Anything
  else — pipelines, scripted metrics, score-sorted top_hits — returns
  None and the request keeps the legacy path unchanged.
- :func:`serve_agg_stages` executes the agg stages of a fused dispatch:
  the query's doc mask per view segment comes from the SAME host CSR
  pool the scoring stage used (base tier + eager delta twin, exactly
  merged), and the per-segment reductions run through the SAME
  ``Aggregator.collect``/``reduce`` tree as the legacy path — so
  int-count parity with the two-pass route is bitwise BY SHARED CODE,
  and the f32/f64 sum precision contract is inherited, not re-stated.

Regime choices that change representations (exact set vs HLL registers
in cardinality) key off per-(segment, field) ``distinct_count`` — a
route-independent property — so fused and legacy answers stay
identical. ``ES_TPU_FUSED_AGGS=0`` turns agg lowering off (the
bisection knob, same pattern as ``ES_TPU_FUSED_PLANNER``)."""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops import aggs as ops_aggs
from .aggregations import (
    AggregationContext, Aggregator, AvgAgg, CardinalityAgg,
    DateHistogramAgg, ExtendedStatsAgg, HistogramAgg, MaxAgg, MinAgg,
    PercentileRanksAgg, PercentilesAgg, StatsAgg, SumAgg, TermsAgg,
    TopHitsAgg, ValueCountAgg, parse_aggs, run_aggregations_multi)

#: aggregator types the planner can run as fused stages — exact-type
#: membership on purpose: subclasses registered by the extension modules
#: (significant_terms, auto_date_histogram, ...) carry semantics the
#: stage executor has not been audited against
_LOWERABLE = frozenset({
    AvgAgg, SumAgg, MinAgg, MaxAgg, ValueCountAgg, StatsAgg,
    ExtendedStatsAgg, CardinalityAgg, PercentilesAgg, PercentileRanksAgg,
    TermsAgg, HistogramAgg, DateHistogramAgg, TopHitsAgg,
})


@dataclass
class AggPlan:
    """A lowered ``aggs`` body: the planner IR for the analytics stages.

    ``shape`` is the name-independent tree signature the micro-batcher
    co-batches on (same discipline as the (B, k, L, params) lattice:
    requests that differ only in bucket VALUES share a dispatch;
    requests with different tree structure do not). ``spec_key`` is the
    canonical spec serialization used for in-flight dedup."""

    spec_key: str
    aggs: Dict[str, Aggregator]
    mapper: Any
    shape: Tuple
    n_stages: int


def _tree_shape(parsed: Dict[str, Aggregator]) -> Optional[Tuple]:
    """Lowerability walk: the tree's (kind, field, sub-shape) signature,
    or None when any node falls outside the fused fragment."""
    out = []
    for _name, agg in sorted(parsed.items()):
        if type(agg) not in _LOWERABLE:
            return None
        if type(agg) is TopHitsAgg:
            # the fused dispatch computes masks, not per-doc scores:
            # only field-sorted top_hits is score-independent
            if not agg._sorts or any(f == "_score"
                                     for f, _, _ in agg._sorts):
                return None
        subs = getattr(agg, "subs", None) or {}
        sub_shape: Optional[Tuple] = ()
        if subs:
            sub_shape = _tree_shape(subs)
            if sub_shape is None:
                return None
        out.append((agg.kind, getattr(agg, "field", None), sub_shape))
    return tuple(out)


def _count_nodes(parsed: Dict[str, Aggregator]) -> int:
    n = 0
    for agg in parsed.values():
        n += 1
        subs = getattr(agg, "subs", None)
        if subs:
            n += _count_nodes(subs)
    return n


def fused_aggs_enabled() -> bool:
    """The agg-lowering on/off env gate (bisection knob): default on."""
    import os
    return os.environ.get("ES_TPU_FUSED_AGGS", "1").lower() \
        not in ("0", "false")


def lower_aggs(spec, mapper) -> Optional[AggPlan]:
    """``aggs`` body → :class:`AggPlan`, or None when the tree is not
    fully lowerable (the caller then keeps the legacy path — including
    for malformed specs, so parse errors surface where they always
    did)."""
    if not isinstance(spec, dict) or not spec:
        return None
    try:
        parsed = parse_aggs(spec)
    except Exception:                    # noqa: BLE001
        return None
    shape = _tree_shape(parsed)
    if shape is None:
        return None
    return AggPlan(
        spec_key=json.dumps(spec, sort_keys=True, default=str),
        aggs=parsed, mapper=mapper, shape=shape,
        n_stages=_count_nodes(parsed))


def _plan_bytes(aggs: Dict[str, Aggregator], seg) -> int:
    """Per-segment model bytes of one agg tree (the ROOFLINE agg-stage
    bytes model): every node streams its field's doc-values pairs, the
    mask, and its output rows; cardinality's HLL regime adds the
    register array."""
    from ..common.roofline import model_bytes_agg
    total = 0
    for agg in aggs.values():
        f = getattr(agg, "field", None)
        pairs = 0
        out_vals = 1
        if f is not None:
            kf = getattr(seg, "keyword_fields", {}).get(f)
            nf = getattr(seg, "numeric_fields", {}).get(f)
            if kf is not None and kf.dv_docs_host.shape[0] > 0:
                pairs = int(kf.dv_docs_host.shape[0])
                out_vals = len(kf.ord_terms)
            elif nf is not None:
                pairs = int(nf.docs_host.shape[0])
        if isinstance(agg, CardinalityAgg) and pairs:
            out_vals = 1 << ops_aggs.HLL_P
        total += model_bytes_agg(pairs, seg.n_pad, out_vals)
        subs = getattr(agg, "subs", None)
        if subs:
            total += _plan_bytes(subs, seg)
    return total


def serve_agg_stages(runner, items: Sequence[dict], *, view,
                     stages: Optional[dict] = None
                     ) -> List[Optional[dict]]:
    """Run the aggregation stages of one fused dispatch.

    For each item carrying an :class:`AggPlan`, the query's doc mask per
    view segment is pooled from the planes' host CSR — the base tier via
    ``_host_csr`` and delta segments via the eager delta twin's CSR,
    positions resolved exactly like the rescore stage — then the shared
    collect/reduce tree produces the item's aggregations dict. Returns a
    list aligned with ``items`` (None for agg-free/pad slots)."""
    t0 = time.perf_counter()
    from ..parallel.dist_search import (bool_clause_rows,
                                        bool_csr_doc_mask, bool_role_masks)
    gen = runner.text_gen
    base = runner._text_base()
    delta, base_pos = gen._delta_for_view(view) \
        if hasattr(gen, "_delta_for_view") \
        else (None, list(range(base.n_shards)))
    pos2base = {vp: bi for bi, vp in enumerate(base_pos)}
    pos2delta: Dict[int, int] = {}
    if delta is not None:
        for di, vp in enumerate(delta.seg_positions):
            pos2delta[vp] = di
    out: List[Optional[dict]] = []
    total_stages = 0
    total_bytes = 0
    for it in items:
        plan = it.get("aggs")
        if plan is None:
            out.append(None)
            continue
        req, neg, shd = bool_role_masks(it["clauses"])
        per_clause = bool_clause_rows(it["clauses"], lambda t: 1.0)
        ctx = AggregationContext(plan.mapper)
        triples = []
        for si, seg in enumerate(view):
            if si in pos2base:
                bi = pos2base[si]
                csr = base._host_csr[bi]
                tids = base.shards[bi]["term_ids"]
            elif si in pos2delta:
                csr = delta._csr[pos2delta[si]]
                tids = csr["term_ids"]
            else:                        # empty segment: nothing matches
                triples.append((ctx, seg, np.zeros(seg.n_pad, bool)))
                continue
            mask = bool_csr_doc_mask(tids, csr, per_clause, req, neg,
                                     shd, it["msm"], seg.n_pad)
            live = getattr(seg, "live", None)
            if live is not None and not bool(live.all()):
                mask[: seg.n_docs] &= live[: seg.n_docs]
            triples.append((ctx, seg, mask))
            total_bytes += _plan_bytes(plan.aggs, seg)
        out.append(run_aggregations_multi(plan.aggs, triples))
        total_stages += plan.n_stages
    if total_stages:
        from ..common import telemetry as _tm
        _tm.record_agg_dispatch(total_stages)
    if stages is not None:
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        stages["agg_ms"] = stages.get("agg_ms", 0.0) + elapsed_ms
        if "dispatch_ms" in stages:
            # the retrieval stages stamped their own refined wall — the
            # agg stages ran in the same dispatch, so their time (and
            # their model bytes below) joins the roofline-audited wall
            stages["dispatch_ms"] += elapsed_ms
        if total_bytes:
            stages["model_bytes"] = int(stages.get("model_bytes") or 0) \
                + total_bytes
    return out
