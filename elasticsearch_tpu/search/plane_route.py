"""Serving route onto the tiered TPU search plane.

The flagship distributed kernel (``parallel/dist_search.py``: tiered BM25 —
dense Zipf-head streaming matmuls + sparse sorted-merge — with the ICI
all_gather/top_k reduce) must serve PRODUCT traffic, not just the bench:
the reference executes every eligible query through its one production
scorer (``action/search/AbstractSearchAsyncAction.java:70`` →
``search/internal/ContextIndexSearcher.java:210-224``). This module is the
bridge from the REST/cluster search path into the plane:

- :func:`extract_bag_of_terms` recognizes request bodies whose query
  reduces to a weighted bag of terms over ONE text field — ``match``
  (OR operator), ``term`` on a text field, and ``bool``/``dis_max``-free
  pure-``should`` disjunctions of those — exactly the shapes whose scoring
  model (sum of per-term BM25 over shard-level stats) the plane computes.
- :class:`ServingPlaneCache` owns one :class:`DistributedSearchPlane` per
  (shard, field), built lazily from the live segment list (one SEGMENT per
  plane shard, so the plane's shard-ascending tie order equals the
  per-segment path's (segment, doc) order) and invalidated on refresh /
  merge / delete. Segments with deletes or nested docs disable the route
  (plane postings would score hidden/dead docs).

Score parity with ``query_dsl._score_text_terms``: idf uses the identical
``idf_weight`` over summed dfs and total docs; impacts are normalized by
the cross-segment shard avgdl (``avgdl`` override); the exact per-query
match counts come back from the same dispatch (``with_totals``), so
``track_total_hits`` needs no second pass.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..index.mapping import MapperService, TextFieldType
from ..index.segment import Segment

#: plane construction is O(postings); don't bother below this many docs
#: unless a test forces it (ENV knob in ServingPlaneCache)
_MIN_DOCS_DEFAULT = 0


def _match_terms(field: str, spec, mapper: MapperService) \
        -> Optional[Tuple[str, List[str]]]:
    """One match clause → (concrete text field, analyzed terms)."""
    if isinstance(spec, dict):
        if set(spec) - {"query", "operator", "boost",
                        "minimum_should_match"}:
            return None
        if str(spec.get("operator", "or")).lower() != "or":
            return None
        if spec.get("boost", 1.0) != 1.0:
            return None
        msm = spec.get("minimum_should_match")
        if msm is not None and msm != 1:
            return None
        text = spec.get("query")
    else:
        text = spec
    if text is None or isinstance(text, (dict, list)):
        return None
    ft = mapper.field_type(field)
    if not isinstance(ft, TextFieldType):
        return None
    terms = ft.search_analyzer.terms(str(text))
    return (ft.name, terms) if terms else None


def _term_terms(field: str, spec, mapper: MapperService) \
        -> Optional[Tuple[str, List[str]]]:
    """One term clause on a TEXT field → single unanalyzed term."""
    if isinstance(spec, dict):
        if set(spec) - {"value", "boost"}:
            return None
        if spec.get("boost", 1.0) != 1.0:
            return None
        value = spec.get("value")
    else:
        value = spec
    if value is None or isinstance(value, (dict, list)):
        return None
    ft = mapper.field_type(field)
    if not isinstance(ft, TextFieldType):
        return None
    return ft.name, [str(value)]


def extract_bag_of_terms(query_spec, mapper: MapperService) \
        -> Optional[Tuple[str, List[str]]]:
    """Request query → (field, bag of terms with duplicates) when the query
    is plane-eligible, else None. Duplicate terms encode weight (the plane
    counts repeats into idfw, matching the per-segment path's weights)."""
    if not isinstance(query_spec, dict) or len(query_spec) != 1:
        return None
    (kind, body), = query_spec.items()
    if kind == "match":
        if not isinstance(body, dict) or len(body) != 1:
            return None
        (field, spec), = body.items()
        return _match_terms(field, spec, mapper)
    if kind == "term":
        if not isinstance(body, dict) or len(body) != 1:
            return None
        (field, spec), = body.items()
        return _term_terms(field, spec, mapper)
    if kind == "bool":
        if not isinstance(body, dict):
            return None
        if set(body) - {"should", "minimum_should_match", "boost"}:
            return None           # must/filter/must_not change semantics
        if body.get("boost", 1.0) != 1.0:
            return None
        msm = body.get("minimum_should_match")
        if msm is not None and msm != 1:
            return None
        should = body.get("should")
        if isinstance(should, dict):
            should = [should]
        if not should:
            return None
        field = None
        terms: List[str] = []
        for clause in should:
            sub = extract_bag_of_terms(clause, mapper)
            if sub is None:
                return None
            f, ts = sub
            if field is None:
                field = f
            elif field != f:
                return None       # cross-field disjunction: scores differ
            terms.extend(ts)
        return (field, terms) if field is not None and terms else None
    return None


#: request-body features the plane cannot serve (need per-doc masks or
#: post-hoc reordering); shared by the single-shard and pooled dist
#: routes. ``profile`` is NOT here: profiled plane queries ride the real
#: serving path and report a ``serving`` profile section (stage timings,
#: compile-cache) — the Profile API must reflect production execution.
#: (Profiled bodies still never enter the request cache:
#: ``IndexService._plane_cache_key`` checks ``profile`` separately.)
_PLANE_INCOMPATIBLE = ("aggs", "aggregations", "sort", "knn", "rescore",
                       "collapse", "suggest", "search_after", "min_score",
                       "rank")


def body_eligible(body: dict) -> bool:
    """True when the request body's FEATURE set allows the plane route
    (the query shape itself is judged by :func:`extract_bag_of_terms`)."""
    if any(body.get(k) for k in _PLANE_INCOMPATIBLE):
        return False
    return int(body.get("size", 10)) + int(body.get("from", 0)) > 0


class ServingPlaneCache:
    """Per-(shard, field) plane registry for the product search path."""

    def __init__(self, mesh_factory=None, min_docs: int = _MIN_DOCS_DEFAULT):
        self._mesh_factory = mesh_factory
        self._mesh = None
        self._planes: Dict[str, Tuple[tuple, object]] = {}
        # kNN planes key on (field, segment signature): the distributed
        # searcher probes one plane per index shard (distinct segment
        # lists), and field-only keying would rebuild on every alternating
        # probe. LRU-capped; evicted planes release their breaker bytes.
        from collections import OrderedDict
        self._knn_planes: "OrderedDict[tuple, object]" = OrderedDict()
        #: consecutive plane builds without a cache hit — when more
        #: distinct (field, sig) combinations are in flight than the
        #: cache holds, packing a corpus per probe would thrash; the
        #: route bows out to the per-segment path instead
        self._knn_build_streak = 0
        self.min_docs = min_docs

    #: max cached kNN planes (each is one packed f32 corpus copy)
    KNN_PLANE_CACHE_MAX = 32

    @staticmethod
    def _attach_batcher(plane, knn: bool = False):
        """Pre-create the plane's micro-batcher at plane-build time and
        kick off its serving-shape-lattice warmup (background thread; see
        ``microbatch.PlaneMicroBatcher.warmup``) — a first-hit XLA
        compile landing mid-traffic is the multi-second serving-p99
        signature. Host-serving (CPU) planes compile nothing so warmup
        returns immediately. ``ES_TPU_SERVING_WARMUP=0`` disables."""
        import os
        from .microbatch import KnnPlaneMicroBatcher, PlaneMicroBatcher
        cls = KnnPlaneMicroBatcher if knn else PlaneMicroBatcher
        batcher = cls(plane)
        plane._microbatcher = batcher
        if os.environ.get("ES_TPU_SERVING_WARMUP", "1").lower() \
                not in ("0", "false"):
            batcher.warmup()
        return batcher

    @staticmethod
    def _retire(plane) -> None:
        """Stop a superseded/evicted plane's in-flight warmup so rebuild
        storms (refresh-heavy indices) don't stack background compile
        threads each pinning an orphaned corpus copy."""
        b = getattr(plane, "_microbatcher", None)
        if b is not None:
            b.retire()

    def _get_mesh(self):
        if self._mesh is None:
            if self._mesh_factory is not None:
                self._mesh = self._mesh_factory()
            else:
                # serving default: the local device. Multi-chip serving uses
                # a factory wired by the node (mesh over its chips).
                import jax
                from .. import parallel as par
                self._mesh = par.make_search_mesh(
                    n_shards=1, n_replicas=1, devices=jax.devices()[:1])
        return self._mesh

    @staticmethod
    def _signature(segments: Sequence[Segment], field: str) -> Optional[tuple]:
        """Cache key over the segment list; None → route ineligible."""
        sig = []
        any_field = False
        for s in segments:
            if s.has_nested or not bool(s.live.all()):
                return None
            if field in s.text_fields:
                any_field = True
            sig.append((s.seg_id, s.n_docs))
        return tuple(sig) if any_field else None

    def plane_for(self, segments: Sequence[Segment], mapper: MapperService,
                  field: str):
        """The serving plane for this segment list, or None when the route
        is ineligible (deletes, nested docs, absent field)."""
        segments = [s for s in segments if s.n_docs > 0]
        if not segments:
            return None
        if sum(s.n_docs for s in segments) < self.min_docs:
            return None
        sig = self._signature(segments, field)
        if sig is None:
            return None
        cached = self._planes.get(field)
        if cached is not None and cached[0] == sig:
            return cached[1]
        from ..parallel.dist_search import DistributedSearchPlane
        # shard-level (cross-segment) avgdl, same as ShardContext.field_avgdl
        sum_dl = 0.0
        doc_count = 0
        for s in segments:
            sdl, dc = s.field_stats(field)
            sum_dl += sdl
            doc_count += dc
        avgdl = sum_dl / doc_count if doc_count else 1.0
        shards = []
        for seg in segments:
            f = seg.text_fields.get(field)
            if f is None:
                n = seg.n_docs
                shards.append(dict(
                    term_ids={}, df=np.zeros(0, np.int32),
                    offsets=np.zeros(1, np.int64),
                    docs=np.zeros(0, np.int32), tf=np.zeros(0, np.float32),
                    doc_len=np.zeros(n, np.float32), avgdl=avgdl))
            else:
                shards.append(dict(
                    term_ids=f.term_ids, df=f.df, offsets=f.offsets,
                    docs=f.docs_host, tf=f.tf_host,
                    doc_len=f.doc_len_host, avgdl=avgdl))
        # the dense tier is the big persistent allocation (T_pad × n_pad
        # bf16 per shard): reserve its estimate against the accounting
        # breaker BEFORE building, so an overfull node 429s instead of
        # OOMing inside the constructor
        from ..common.breakers import DEFAULT as _breakers
        from ..parallel.dist_search import DistributedSearchPlane as _P
        from ..utils.shapes import round_up_multiple, round_up_pow2
        acct = _breakers.breaker("accounting")
        n_pad = round_up_pow2(max(
            max(s["doc_len"].shape[0] for s in shards), 1))
        threshold = max(n_pad // 256, 4096)
        t_est = max((min(int((np.asarray(s["df"]) > threshold).sum()),
                         _P.MAX_DENSE_TERMS) for s in shards),
                    default=0)
        nbytes = round_up_multiple(max(t_est, 1), 16) * n_pad * 2 * \
            len(shards) if t_est else 0
        acct.add_estimate(nbytes, f"<serving plane [{field}]>")
        try:
            plane = DistributedSearchPlane(self._get_mesh(), shards,
                                           field)
        except Exception:
            acct.release(nbytes)
            raise
        old = self._planes.get(field)
        if old is not None:
            acct.release(getattr(old[1], "_acct_bytes", 0))
            self._retire(old[1])
        plane._acct_bytes = nbytes
        self._attach_batcher(plane)
        self._planes[field] = (sig, plane)
        return plane

    @staticmethod
    def _knn_signature(segments: Sequence[Segment],
                       field: str) -> Optional[tuple]:
        """Cache key for the kNN plane; None → route ineligible (deletes,
        nested docs, or the field has no vectors anywhere — the plane
        packs exists-masked rows but per-doc liveness/parent masks stay on
        the per-segment path)."""
        sig = []
        any_field = False
        for s in segments:
            if s.has_nested or not bool(s.live.all()):
                return None
            if field in s.vector_fields:
                any_field = True
            sig.append((s.seg_id, s.n_docs))
        return tuple(sig) if any_field else None

    def knn_plane_for(self, segments: Sequence[Segment],
                      mapper: MapperService, field: str):
        """The kNN serving plane (``DistributedKnnPlane`` — pack-time
        corpus invariants + blocked running-top-k) for this segment list,
        or None when the route is ineligible. One SEGMENT per plane shard,
        same as the lexical plane, so tie order matches the per-segment
        path."""
        from ..index.mapping import DenseVectorFieldType
        segments = [s for s in segments if s.n_docs > 0]
        if not segments:
            return None
        ft = mapper.field_type(field)
        if not isinstance(ft, DenseVectorFieldType):
            return None
        sig = self._knn_signature(segments, field)
        if sig is None:
            return None
        key = (field, sig)
        cached = self._knn_planes.get(key)
        if cached is not None:
            self._knn_planes.move_to_end(key)
            self._knn_build_streak = 0
            return cached
        if self._knn_build_streak >= self.KNN_PLANE_CACHE_MAX:
            # every recent probe missed: building would evict entries the
            # same request needs again (O(corpus) repack per query) — the
            # per-segment fallback is the cheaper correct path
            return None
        from ..parallel.dist_search import DistributedKnnPlane
        # step similarity: ranking by raw dot is order-equivalent for
        # max_inner_product (its _score transform is monotone); unknown
        # similarity strings keep the per-segment path's quirks
        similarity = {"cosine": "cosine", "dot_product": "dot_product",
                      "l2_norm": "l2_norm",
                      "max_inner_product": "dot_product"}.get(
                          getattr(ft, "similarity", "cosine"))
        if similarity is None:
            return None
        shards = []
        for seg in segments:
            f = seg.vector_fields.get(field)
            if f is None:
                shards.append(dict(
                    vectors=np.zeros((seg.n_docs, 1), np.float32),
                    exists=np.zeros(seg.n_docs, bool)))
            else:
                ex = np.zeros(seg.n_docs, bool)
                ex[: f.exists.shape[0]] = f.exists
                shards.append(dict(vectors=f.matrix_host, exists=ex))
        dims = {s["vectors"].shape[1] for s in shards if s["exists"].any()}
        if len(dims) > 1:
            return None
        dim = dims.pop() if dims else 1
        for s in shards:
            if not s["exists"].any():
                s["vectors"] = np.zeros((s["exists"].shape[0], dim),
                                        np.float32)
        # the packed corpus (f32[S, n_pad, dim] + invariants) is the big
        # persistent allocation: reserve it against the accounting breaker
        # before building, like the lexical plane's dense tier
        from ..common.breakers import DEFAULT as _breakers
        from ..utils.shapes import round_up_pow2
        acct = _breakers.breaker("accounting")
        n_pad = round_up_pow2(max(max(s["exists"].shape[0]
                                      for s in shards), 1))
        nbytes = len(shards) * n_pad * (dim * 4 + 5)
        # make room BEFORE reserving: drop superseded generations of this
        # field (a refresh/merge kept part of the segment list, so the
        # old signature shares seg_ids with the new one — planes for
        # OTHER shards of the same field are disjoint and survive) and
        # any LRU overflow
        new_ids = {sid for sid, _ in sig}
        for old_key in [ok for ok in self._knn_planes
                        if ok[0] == field and ok[1] != sig
                        and any(sid in new_ids for sid, _ in ok[1])]:
            old = self._knn_planes.pop(old_key)
            acct.release(getattr(old, "_acct_bytes", 0))
            self._retire(old)
        while len(self._knn_planes) >= self.KNN_PLANE_CACHE_MAX:
            _, old = self._knn_planes.popitem(last=False)
            acct.release(getattr(old, "_acct_bytes", 0))
            self._retire(old)
        acct.add_estimate(nbytes, f"<knn serving plane [{field}]>")
        try:
            plane = DistributedKnnPlane(self._get_mesh(), shards,
                                        similarity=similarity)
        except Exception:
            acct.release(nbytes)
            raise
        plane._acct_bytes = nbytes
        raced = self._knn_planes.get(key)
        if raced is not None:
            # another thread built the same plane meanwhile: keep the
            # winner, release this copy's reservation
            acct.release(nbytes)
            self._knn_planes.move_to_end(key)
            return raced
        self._attach_batcher(plane, knn=True)
        self._knn_planes[key] = plane
        self._knn_build_streak += 1
        return plane

    def release(self) -> None:
        """Release every plane's breaker reservation (the owning index is
        closing or being deleted)."""
        from ..common.breakers import DEFAULT as _breakers
        acct = _breakers.breaker("accounting")
        for _sig, plane in self._planes.values():
            acct.release(getattr(plane, "_acct_bytes", 0))
            self._retire(plane)
        for plane in self._knn_planes.values():
            acct.release(getattr(plane, "_acct_bytes", 0))
            self._retire(plane)
        self._planes.clear()
        self._knn_planes.clear()
