"""Serving route onto the tiered TPU search plane.

The flagship distributed kernel (``parallel/dist_search.py``: tiered BM25 —
dense Zipf-head streaming matmuls + sparse sorted-merge — with the ICI
all_gather/top_k reduce) must serve PRODUCT traffic, not just the bench:
the reference executes every eligible query through its one production
scorer (``action/search/AbstractSearchAsyncAction.java:70`` →
``search/internal/ContextIndexSearcher.java:210-224``). This module is the
bridge from the REST/cluster search path into the plane:

- :func:`extract_bag_of_terms` recognizes request bodies whose query
  reduces to a weighted bag of terms over ONE text field — ``match``
  (OR operator), ``term`` on a text field, and ``bool``/``dis_max``-free
  pure-``should`` disjunctions of those — exactly the shapes whose scoring
  model (sum of per-term BM25 over shard-level stats) the plane computes.
- :class:`ServingPlaneCache` owns one serving GENERATION per (shard,
  field): a packed base plane (:class:`DistributedSearchPlane` /
  :class:`DistributedKnnPlane` over the segment list as of the last
  repack) plus an append-only DELTA tier (segments created since),
  scored eagerly per query and merged into the base dispatch's top-k.
  Segments with deletes or nested docs disable the route (plane postings
  would score hidden/dead docs).

Incremental maintenance (the NRT-refresh problem): under live indexing a
refresh appends a segment every second while a full plane repack — CSR
pack, dense tier, device upload, warmup lattice — costs far more. The old
design repacked EVERY segment synchronously on the first request to
notice the signature change, collapsing search throughput into rebuild
storms. Generations fix this the way Lucene-tier systems do (segment
-tiered serving + background merges — the Anserini/HNSW line):

- an append-only refresh never invalidates the base: the new segments
  form the delta tier (``parallel/dist_search.EagerDeltaScorer`` /
  ``KnnDeltaScorer``), and the request thread at most packs the delta's
  CSR — O(delta), no device work;
- a background repack thread folds the delta into a new base generation
  once the delta exceeds :attr:`ServingPlaneCache.REPACK_DELTA_FRACTION`
  of the base doc count, builds and warms the new plane OFF the request
  thread, then atomically swaps generations (double-buffering: the old
  generation serves until the new one is ready; its warmup is retired as
  before);
- a merge/delete restructures the base segment list, which the old base
  cannot serve (its hit coordinates decode against segments that no
  longer exist): the repack still happens in the background while the
  per-segment path serves the gap.

Score parity with ``query_dsl._score_text_terms``: idf uses the identical
``idf_weight`` over summed dfs and total docs — the delta tier's df/doc
mass is folded into every base dispatch (``extra_df``/``extra_docs``), so
base and delta docs score under ONE stat set. The generation's length
norm (avgdl) is FROZEN at base-pack time (base impacts bake it); the
delta scores under the same frozen value, so base+delta serving is
bit-equal to a full repack pinned to that avgdl, and drifts from the
live per-segment path only by the delta window's avgdl movement — the
repack threshold bounds the window, and the swap restores exactness.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import racedep
from ..index.mapping import MapperService, TextFieldType
from ..index.segment import Segment

#: plane construction is O(postings); don't bother below this many docs
#: unless a test forces it (ENV knob in ServingPlaneCache)
_MIN_DOCS_DEFAULT = 0


def _match_terms(field: str, spec, mapper: MapperService) \
        -> Optional[Tuple[str, List[str]]]:
    """One match clause → (concrete text field, analyzed terms)."""
    if isinstance(spec, dict):
        if set(spec) - {"query", "operator", "boost",
                        "minimum_should_match"}:
            return None
        if str(spec.get("operator", "or")).lower() != "or":
            return None
        if spec.get("boost", 1.0) != 1.0:
            return None
        msm = spec.get("minimum_should_match")
        if msm is not None and msm != 1:
            return None
        text = spec.get("query")
    else:
        text = spec
    if text is None or isinstance(text, (dict, list)):
        return None
    ft = mapper.field_type(field)
    if not isinstance(ft, TextFieldType):
        return None
    terms = ft.search_analyzer.terms(str(text))
    return (ft.name, terms) if terms else None


def _term_terms(field: str, spec, mapper: MapperService) \
        -> Optional[Tuple[str, List[str]]]:
    """One term clause on a TEXT field → single unanalyzed term."""
    if isinstance(spec, dict):
        if set(spec) - {"value", "boost"}:
            return None
        if spec.get("boost", 1.0) != 1.0:
            return None
        value = spec.get("value")
    else:
        value = spec
    if value is None or isinstance(value, (dict, list)):
        return None
    ft = mapper.field_type(field)
    if not isinstance(ft, TextFieldType):
        return None
    return ft.name, [str(value)]


def extract_bag_of_terms(query_spec, mapper: MapperService) \
        -> Optional[Tuple[str, List[str]]]:
    """Request query → (field, bag of terms with duplicates) when the query
    is plane-eligible, else None. Duplicate terms encode weight (the plane
    counts repeats into idfw, matching the per-segment path's weights)."""
    if not isinstance(query_spec, dict) or len(query_spec) != 1:
        return None
    (kind, body), = query_spec.items()
    if kind == "match":
        if not isinstance(body, dict) or len(body) != 1:
            return None
        (field, spec), = body.items()
        return _match_terms(field, spec, mapper)
    if kind == "term":
        if not isinstance(body, dict) or len(body) != 1:
            return None
        (field, spec), = body.items()
        return _term_terms(field, spec, mapper)
    if kind == "bool":
        if not isinstance(body, dict):
            return None
        if set(body) - {"should", "minimum_should_match", "boost"}:
            return None           # must/filter/must_not change semantics
        if body.get("boost", 1.0) != 1.0:
            return None
        msm = body.get("minimum_should_match")
        if msm is not None and msm != 1:
            return None
        should = body.get("should")
        if isinstance(should, dict):
            should = [should]
        if not should:
            return None
        field = None
        terms: List[str] = []
        for clause in should:
            sub = extract_bag_of_terms(clause, mapper)
            if sub is None:
                return None
            f, ts = sub
            if field is None:
                field = f
            elif field != f:
                return None       # cross-field disjunction: scores differ
            terms.extend(ts)
        return (field, terms) if field is not None and terms else None
    return None


#: request-body features the plane cannot serve (need per-doc masks or
#: post-hoc reordering); shared by the single-shard and pooled dist
#: routes. ``profile`` is NOT here: profiled plane queries ride the real
#: serving path and report a ``serving`` profile section (stage timings,
#: compile-cache) — the Profile API must reflect production execution.
#: (Profiled bodies still never enter the request cache:
#: ``IndexService._plane_cache_key`` checks ``profile`` separately.)
_PLANE_INCOMPATIBLE = ("aggs", "aggregations", "sort", "knn", "rescore",
                       "collapse", "suggest", "search_after", "min_score",
                       "rank")


def body_eligible(body: dict) -> bool:
    """True when the request body's FEATURE set allows the plane route
    (the query shape itself is judged by :func:`extract_bag_of_terms`)."""
    if any(body.get(k) for k in _PLANE_INCOMPATIBLE):
        return False
    return int(body.get("size", 10)) + int(body.get("from", 0)) > 0


# ---------------------------------------------------------------------------
# Serving generations: packed base plane + append-only delta tier
# ---------------------------------------------------------------------------


class _ServingGeneration:
    """One serving generation: a packed base plane over an immutable
    snapshot of the segment list, plus a swappable delta tier covering
    segments appended since. Unknown attributes delegate to the base
    plane (``n_dispatches``, ``_host_csr``/``_host_pack``, ladder/warmup
    surface), so the micro-batcher and the stats layer treat a
    generation exactly like a bare plane."""

    kind = "plane"

    #: per-view delta-scorer memo entries kept besides the live one
    VIEW_MEMO_MAX = 4

    def __init__(self, base, base_segments: Sequence[Segment], cache):
        self.base = base
        #: strong refs — identity (``is``) anchors for delta matching;
        #: kept alive until the generation is released
        self.base_segments = list(base_segments)
        self.base_docs = sum(s.n_docs for s in base_segments)
        self._cache = cache
        self.delta = None
        self._base_positions: List[int] = list(range(len(base_segments)))
        self._delta_key: Optional[tuple] = None
        self._delta_ver = -1
        self._delta_lock = threading.Lock()
        #: view key → (scorer, base_positions) for views that are not
        #: the live delta (a dispatch racing a refresh serves its own
        #: older view; see :meth:`_delta_for_view`)
        self._view_memo: "OrderedDict[tuple, tuple]" = OrderedDict()

    def __getattr__(self, name):
        base = self.__dict__.get("base")
        if base is None:
            raise AttributeError(name)
        return getattr(base, name)

    # -- delta bookkeeping ---------------------------------------------------

    def match(self, segments: Sequence[Segment]):
        """Identity-subsequence match of this generation's base against
        the CURRENT segment list. Returns (delta_segments,
        delta_positions, base_positions) when every base segment appears
        unchanged and in order (append-only refreshes, including
        interleaved appends from other index shards), else None (a
        merge/delete restructured the base — repack required)."""
        base = self.base_segments
        bi = 0
        delta: List[Segment] = []
        dpos: List[int] = []
        bpos: List[int] = []
        for pos, seg in enumerate(segments):
            if bi < len(base) and seg is base[bi]:
                bpos.append(pos)
                bi += 1
            else:
                delta.append(seg)
                dpos.append(pos)
        if bi != len(base):
            return None
        return delta, dpos, bpos

    def clear_delta(self, base_positions: Optional[List[int]] = None,
                    ver: int = -1) -> None:
        with self._delta_lock:
            if ver >= 0 and ver < self._delta_ver:
                return
            self.delta = None
            self._delta_key = None
            self._delta_ver = max(self._delta_ver, ver)
            if base_positions is not None:
                self._base_positions = base_positions

    def _swap_delta(self, scorer, key: tuple, base_positions: List[int],
                    ver: int) -> None:
        with self._delta_lock:
            racedep.note_write("generation.delta", self)
            if ver < self._delta_ver:
                return          # a newer segment list already swapped in
            self.delta = scorer
            self._delta_key = key
            self._delta_ver = ver
            self._base_positions = base_positions

    def delta_docs(self) -> int:
        # under _delta_lock: the repack thread swaps (delta, positions)
        # as a pair, and a torn read here would size the repack
        # threshold off a half-swapped generation (ESTP-R01)
        with self._delta_lock:
            d = self.delta
        return d.n_docs if d is not None else 0

    def _snapshot(self):
        with self._delta_lock:
            racedep.note_read("generation.delta", self)
            return self.delta, self._base_positions

    def _build_delta(self, delta_segs: Sequence[Segment],
                     delta_pos: List[int]):
        raise NotImplementedError

    def _delta_for_view(self, view: Sequence[Segment]):
        """(delta scorer | None, base positions) for EXACTLY the given
        segment list — the dispatch-time resolution that keeps hit
        coordinates in the caller's NRT snapshot space. A refresh landing
        between the caller's ``plane_for`` and the micro-batch dispatch
        mutates the generation's live delta, so serving that newer delta
        would emit coordinates past (or shifted within) the caller's
        list; resolving per view instead makes the race harmless. The
        live delta is the common-case hit; other views pay one O(delta)
        pack memoized per view key."""
        key = tuple(id(s) for s in view)
        with self._delta_lock:
            if self._delta_key == key:
                return self.delta, self._base_positions
            memo = self._view_memo.get(key)
            if memo is not None:
                self._view_memo.move_to_end(key)
                return memo
        m = self.match(view)
        if m is None:
            # unreachable for views that obtained this generation from
            # plane_for (the base is immutable), but a stale caller must
            # fail loudly rather than decode foreign coordinates
            raise RuntimeError(
                "serving view no longer contains this generation's base")
        delta_segs, delta_pos, base_pos = m
        scorer = self._build_delta(delta_segs, delta_pos) \
            if delta_segs else None
        with self._delta_lock:
            self._view_memo[key] = (scorer, base_pos)
            while len(self._view_memo) > self.VIEW_MEMO_MAX:
                self._view_memo.popitem(last=False)
        return scorer, base_pos


class TextServingGeneration(_ServingGeneration):
    """Lexical generation: ``DistributedSearchPlane`` base + eager CSR
    delta (``parallel/dist_search.EagerDeltaScorer``)."""

    kind = "text"

    def __init__(self, base, base_segments, field: str, avgdl: float,
                 cache):
        super().__init__(base, base_segments, cache)
        self.field = field
        #: the generation's frozen length norm (baked into base impacts)
        self.avgdl = avgdl

    def _build_delta(self, delta_segs: Sequence[Segment],
                     delta_pos: List[int]):
        """Pack a delta scorer — O(delta postings), the only
        serving-path cost a refresh adds."""
        from ..parallel.dist_search import EagerDeltaScorer
        shards = []
        for seg in delta_segs:
            f = seg.text_fields.get(self.field)
            if f is None:
                shards.append(dict(
                    term_ids={}, df=np.zeros(0, np.int32),
                    offsets=np.zeros(1, np.int64),
                    docs=np.zeros(0, np.int32),
                    tf=np.zeros(0, np.float32),
                    doc_len=np.zeros(seg.n_docs, np.float32)))
            else:
                shards.append(dict(
                    term_ids=f.term_ids, df=f.df, offsets=f.offsets,
                    docs=f.docs_host, tf=f.tf_host,
                    doc_len=f.doc_len_host))
        return EagerDeltaScorer(shards, delta_pos, avgdl=self.avgdl)

    def update_delta(self, segments: Sequence[Segment],
                     delta_segs: Sequence[Segment], delta_pos: List[int],
                     base_pos: List[int], ver: int) -> None:
        """Pack (or reuse) the LIVE delta scorer for the current segment
        list (the common serving view; dispatches for other views resolve
        through :meth:`_delta_for_view`)."""
        key = tuple(id(s) for s in segments)
        with self._delta_lock:
            if self._delta_key == key:
                self._base_positions = base_pos
                return
        scorer = self._build_delta(delta_segs, delta_pos)
        self._swap_delta(scorer, key, base_pos, ver)

    def serve_view(self, queries, k: int = 10, *, view,
                   with_totals: bool = False,
                   stages: Optional[dict] = None,
                   prune: Optional[bool] = None):
        """Micro-batcher dispatch hook: base dispatch (idf widened by the
        delta's df/doc mass) + eager delta scan + host top-k merge, with
        the delta resolved for the batch's exact segment view. The BASE
        dispatch may be block-max pruned (``prune``); the delta tier
        always scores eagerly — appended segments are small and
        exactness there keeps the merge honest for fresh docs."""
        delta, base_pos = self._delta_for_view(view)
        return self._serve_merged(queries, k, delta, base_pos,
                                  with_totals=with_totals, stages=stages,
                                  prune=prune)

    def serve(self, queries, k: int = 10, *, with_totals: bool = False,
              stages: Optional[dict] = None,
              prune: Optional[bool] = None):
        """Viewless entry (tests / direct callers): serve against the
        generation's CURRENT delta snapshot."""
        delta, base_pos = self._snapshot()
        return self._serve_merged(queries, k, delta, base_pos,
                                  with_totals=with_totals, stages=stages,
                                  prune=prune)

    def _serve_merged(self, queries, k, delta, base_pos, *,
                      with_totals: bool = False,
                      stages: Optional[dict] = None,
                      prune: Optional[bool] = None):
        # tier bookkeeping BEFORE the dispatch (outside every lock):
        # recency for the budget sweep, warm-hit hysteresis → promotion
        self._cache.tiers.note_dispatch(self)
        if delta is None:
            return self.base.serve(queries, k=k, with_totals=with_totals,
                                   stages=stages, prune=prune)
        # one shared stat set: the delta's term dfs fold into the base
        # dispatch's idf weights, and the delta scores under the same
        # combined idf — parity with a full repack at the frozen avgdl
        extra_df: Dict[str, int] = {}
        for q in queries:
            for t in set(q):
                if t not in extra_df:
                    extra_df[t] = delta.df(t)
        vals, hits, totals = self.base.serve(
            queries, k=k, with_totals=True, stages=stages,
            extra_docs=delta.n_docs, extra_df=extra_df, prune=prune)
        t1 = time.perf_counter()
        from ..ops.bm25 import idf_weight
        n_total = self.base.n_docs_total + delta.n_docs
        idf_cache: Dict[str, float] = {}

        def idf_of(t: str) -> float:
            v = idf_cache.get(t)
            if v is None:
                gdf = self.base.global_df(t) + extra_df.get(t, 0)
                v = float(idf_weight(n_total, np.int64(gdf))) if gdf \
                    else 0.0
                idf_cache[t] = v
            return v

        from ..parallel.dist_search import (merge_topk_rows,
                                            total_is_lower_bound,
                                            total_value)
        drows, dtotals = delta.score(queries, k, idf_of, with_totals=True)
        vals_out, hits_out, totals_out = [], [], []
        for bi in range(len(queries)):
            base_rows = [(float(v), base_pos[si], int(d))
                         for v, (si, d) in zip(vals[bi], hits[bi])]
            merged = merge_topk_rows(base_rows, drows[bi], k)
            vals_out.append(np.asarray([r[0] for r in merged], np.float32))
            hits_out.append([(r[1], r[2]) for r in merged])
            # a pruned base dispatch reports (value, "gte") lower-bound
            # totals — the delta's exact count adds on, relation sticks
            tv = total_value(totals[bi]) + int(dtotals[bi])
            totals_out.append((tv, "gte")
                              if total_is_lower_bound(totals[bi]) else tv)
        delta_ms = (time.perf_counter() - t1) * 1e3
        if stages is not None:
            stages["dispatch_ms"] = stages.get("dispatch_ms", 0.0) \
                + delta_ms
            stages["delta_ms"] = delta_ms
            stages["delta_docs"] = delta.n_docs
        self._cache._record_delta_serve("text", len(queries))
        if with_totals:
            return vals_out, hits_out, totals_out
        return vals_out, hits_out


class KnnServingGeneration(_ServingGeneration):
    """Vector generation: ``DistributedKnnPlane`` base + BLAS delta
    (``parallel/dist_search.KnnDeltaScorer``). No corpus-wide stats, so
    delta serving is exactly exact."""

    kind = "knn"

    def __init__(self, base, base_segments, field: str, cache):
        super().__init__(base, base_segments, cache)
        self.field = field

    def _build_delta(self, delta_segs: Sequence[Segment],
                     delta_pos: List[int]):
        from ..parallel.dist_search import KnnDeltaScorer
        shards = []
        for seg in delta_segs:
            f = seg.vector_fields.get(self.field)
            if f is None:
                shards.append(dict(
                    vectors=np.zeros((seg.n_docs, max(self.base.dim, 1)),
                                     np.float32),
                    exists=np.zeros(seg.n_docs, bool)))
            else:
                ex = np.zeros(seg.n_docs, bool)
                ex[: f.exists.shape[0]] = f.exists
                shards.append(dict(vectors=f.matrix_host, exists=ex))
        return KnnDeltaScorer(shards, delta_pos,
                              similarity=self.base.similarity)

    def update_delta(self, segments: Sequence[Segment],
                     delta_segs: Sequence[Segment], delta_pos: List[int],
                     base_pos: List[int], ver: int) -> None:
        key = tuple(id(s) for s in segments)
        with self._delta_lock:
            if self._delta_key == key:
                self._base_positions = base_pos
                return
        scorer = self._build_delta(delta_segs, delta_pos)
        self._swap_delta(scorer, key, base_pos, ver)

    def serve_view(self, query_vectors, k: int = 10, *, view,
                   stages: Optional[dict] = None,
                   nprobe: Optional[int] = None,
                   rerank: Optional[int] = None):
        delta, base_pos = self._delta_for_view(view)
        return self._serve_merged(query_vectors, k, delta, base_pos,
                                  stages=stages, nprobe=nprobe,
                                  rerank=rerank)

    def serve(self, query_vectors, k: int = 10,
              stages: Optional[dict] = None,
              nprobe: Optional[int] = None,
              rerank: Optional[int] = None):
        delta, base_pos = self._snapshot()
        return self._serve_merged(query_vectors, k, delta, base_pos,
                                  stages=stages, nprobe=nprobe,
                                  rerank=rerank)

    def _serve_merged(self, query_vectors, k, delta, base_pos, *,
                      stages: Optional[dict] = None,
                      nprobe: Optional[int] = None,
                      rerank: Optional[int] = None):
        self._cache.tiers.note_dispatch(self)
        # the base dispatch may be cluster-pruned (IVF tier at the
        # resolved nprobe/rerank); the DELTA tier always scores exact
        # brute-force — appended segments are small, and exactness there
        # keeps the merge's top-k honest for fresh docs
        vals, hits = self.base.serve(query_vectors, k=k, stages=stages,
                                     nprobe=nprobe, rerank=rerank)
        if delta is None:
            return vals, hits
        t1 = time.perf_counter()
        from ..parallel.dist_search import NEG_INF, merge_topk_rows
        drows = delta.score(query_vectors, k)
        B = len(hits)
        vals_out = np.full((B, k), NEG_INF, np.float32)
        hits_out = []
        for bi in range(B):
            base_rows = [(float(v), base_pos[si], int(d))
                         for v, (si, d) in zip(vals[bi], hits[bi])]
            merged = merge_topk_rows(base_rows, drows[bi], k)
            for j, r in enumerate(merged):
                vals_out[bi, j] = r[0]
            hits_out.append([(r[1], r[2]) for r in merged])
        delta_ms = (time.perf_counter() - t1) * 1e3
        if stages is not None:
            stages["dispatch_ms"] = stages.get("dispatch_ms", 0.0) \
                + delta_ms
            stages["delta_ms"] = delta_ms
            stages["delta_docs"] = delta.n_docs
        self._cache._record_delta_serve("knn", B)
        return vals_out, hits_out


# ---------------------------------------------------------------------------
# ServingPlaneCache: generation registry + background repack
# ---------------------------------------------------------------------------


class ServingPlaneCache:
    """Per-(shard, field) serving-generation registry for the product
    search path. Request threads only ever (a) hit a generation, (b)
    pack an O(delta) delta scorer, or (c) pay the one cold build per
    field; full repacks run on a background thread and swap atomically
    (see the module docstring)."""

    #: max cached kNN generations (each base is one packed f32 corpus)
    KNN_PLANE_CACHE_MAX = 32

    #: delta-tier doc fraction (of the base generation's docs) above
    #: which a background repack folds the delta into a new base
    REPACK_DELTA_FRACTION = float(os.environ.get(
        "ES_TPU_PLANE_DELTA_FRACTION", "0.125"))

    #: corpus size above which a kNN base pack also builds the IVF tier
    #: (k-means + cluster-contiguous int8 quantized rows — cluster-pruned
    #: approximate serving with exact re-rank). Below it the plane stays
    #: exact brute force: the pruned scan only wins once the corpus
    #: outgrows what one blocked f32 scan streams comfortably.
    KNN_IVF_MIN_DOCS = int(os.environ.get(
        "ES_TPU_KNN_IVF_MIN_DOCS", str(1 << 16)))

    #: corpus size above which a text base pack also builds the
    #: block-max pruning tier (impact-ordered int8 blocks + bound
    #: table — rank-safe WAND-as-a-scan serving via the ``prune``
    #: knob). Below it eager scoring wins outright (the BM25S bet) and
    #: the tier would only cost pack time and bytes.
    LEX_PRUNE_MIN_DOCS = int(os.environ.get(
        "ES_TPU_LEX_PRUNE_MIN_DOCS", str(1 << 17)))

    #: max cached fused-plan runners (generation pairs; runners hold no
    #: corpus bytes of their own — only batcher state)
    FUSED_RUNNER_CACHE_MAX = 8

    def __init__(self, mesh_factory=None, min_docs: int = _MIN_DOCS_DEFAULT):
        self._mesh_factory = mesh_factory
        self._mesh = None
        self._planes: Dict[str, TextServingGeneration] = {}
        #: (text gen id, knn gen id) → query_planner.FusedPlanRunner —
        #: the one-dispatch planner's executor per generation pair;
        #: entries die with either generation (see _release_gen)
        self._fused_runners: "OrderedDict[tuple, object]" = OrderedDict()
        # kNN generations key on (field, base segment identity): the
        # distributed searcher probes one plane per index shard (distinct
        # segment lists), and field-only keying would rebuild on every
        # alternating probe. LRU-capped; evicted generations release
        # their breaker bytes.
        self._knn_planes: "OrderedDict[tuple, KnnServingGeneration]" = \
            OrderedDict()
        #: consecutive plane builds without a cache hit — when more
        #: distinct (field, segment-list) combinations are in flight than
        #: the cache holds, packing a corpus per probe would thrash; the
        #: route bows out to the per-segment path instead
        self._knn_build_streak = 0
        self.min_docs = min_docs
        #: instance override of :attr:`KNN_IVF_MIN_DOCS` (tests force
        #: IVF on tiny corpora by lowering it)
        self.knn_ivf_min_docs = self.KNN_IVF_MIN_DOCS
        #: instance override of :attr:`LEX_PRUNE_MIN_DOCS` (tests force
        #: the block-max tier on tiny corpora by lowering it)
        self.lex_prune_min_docs = self.LEX_PRUNE_MIN_DOCS
        #: delta-tier serving on/off (off = the old rebuild-every-refresh
        #: behavior; the live-indexing bench uses this as its baseline)
        self.delta_enabled = os.environ.get(
            "ES_TPU_PLANE_DELTA", "1").lower() not in ("0", "false")
        #: "background" (production) or "sync" (deterministic tests /
        #: callers that need the swap visible before the call returns)
        self.repack_mode = os.environ.get(
            "ES_TPU_PLANE_REPACK_MODE", "background")
        self._gen_lock = threading.RLock()
        #: guards the lazy mesh singleton — its OWN leaf lock, not
        #: _gen_lock: the cold build (jax import + device enumeration,
        #: or an arbitrary user factory) can take seconds and must not
        #: stall stats scrapes / refresh reconciles on the registry lock
        self._mesh_lock = threading.Lock()
        self._gen_ver = 0
        self._repacking: set = set()
        self._repack_threads: List[threading.Thread] = []
        self._closed = False
        # plane.rebuild / plane.delta_serve / plane.swap_ms metrics:
        # instance-owned (fresh per cache — exact per-index counts) and
        # exposed through the process telemetry registry via a weakref
        # collector, like every other node-scoped producer
        from ..common import telemetry as _tm
        self._metric_lock = threading.Lock()
        self._rebuild_counts: Dict[Tuple[str, str, str], _tm.Counter] = {}
        self._delta_serve_counts: Dict[str, _tm.Counter] = {}
        # per-kind swap histograms (pre-created so the family's label
        # space is stable for the telemetry lint): a kNN repack packs a
        # full f32 corpus while a text repack packs CSR+dense tiers —
        # their swap costs must be distinguishable
        self._swap_ms: Dict[str, _tm.Histogram] = {
            "text": _tm.Histogram(), "knn": _tm.Histogram()}
        #: device ids that ever reported plane bytes — the gauge emits
        #: explicit 0 samples for them once their planes demote/release
        #: (a vanished sample reads as "last value" to most scrapers:
        #: the PR 15 es_batcher_queue_depth stale-gauge class)
        self._hbm_devices: set = set()
        _tm.DEFAULT.register_object_collector(
            f"plane_cache_{id(self):x}", self,
            ServingPlaneCache._metrics_doc)
        #: storage-tier policy (hot/warm/cold budgets + demand
        #: promotion); budgets default to 0 = unlimited, every plane hot
        from .plane_tiers import PlaneTierManager
        self.tiers = PlaneTierManager(self)

    # -- telemetry -----------------------------------------------------------

    def _metrics_doc(self):
        with self._metric_lock:
            rb = [({"kind": k, "trigger": t, "mode": m}, c.value)
                  for (k, t, m), c in self._rebuild_counts.items()]
            ds = [({"kind": k}, c.value)
                  for k, c in self._delta_serve_counts.items()]
        # per-device resident plane bytes: every generation's base plane
        # reports its per-chip share (shard-axis sharding divides the
        # corpus; replica rows hold full copies), summed per device id —
        # the HBM-budget view of multichip serving. Outside _metric_lock
        # (generations() takes _gen_lock; keep the two independent).
        per_dev: Dict[int, int] = {}
        for gen in self.generations():
            base = gen.__dict__.get("base", gen)
            try:
                # warm/cold planes hold no HBM: device_corpus_bytes()
                # reports 0 once demoted, so the gauge decrements on
                # every demotion without tier-specific cases here
                share = int(base.device_corpus_bytes())
                devices = list(base.mesh.devices.flat)
            except Exception:   # noqa: BLE001 — foreign/legacy planes
                continue
            for d in devices:
                did = int(getattr(d, "id", 0))
                per_dev[did] = per_dev.get(did, 0) + share
        # devices whose planes all demoted/released still emit explicit
        # 0 samples (under _metric_lock: scrapes race each other)
        with self._metric_lock:
            self._hbm_devices |= set(per_dev)
            hbm_devices = sorted(self._hbm_devices)
        return {
            "es_plane_rebuild_total": {
                "type": "counter",
                "help": "serving plane (re)builds by kind/trigger/mode",
                "samples": rb},
            "es_plane_delta_serve_total": {
                "type": "counter",
                "help": "queries served through base+delta merge",
                "samples": ds},
            "es_plane_swap_ms": {
                "type": "histogram",
                "help": "background repack build+swap wall ms by kind",
                "samples": [({"kind": k}, h.snapshot())
                            for k, h in self._swap_ms.items()]},
            "es_plane_hbm_bytes": {
                "type": "gauge",
                "help": "packed serving-plane bytes resident per device "
                        "(estimate; shard-sharded corpus / replica "
                        "copies)",
                "samples": [({"device": str(did)}, per_dev.get(did, 0))
                            for did in hbm_devices]},
        }

    def _record_rebuild(self, kind: str, trigger: str, mode: str) -> None:
        from ..common import telemetry as _tm
        with self._metric_lock:
            c = self._rebuild_counts.get((kind, trigger, mode))
            if c is None:
                c = self._rebuild_counts[(kind, trigger, mode)] = \
                    _tm.Counter()
        c.inc()
        # flight-recorder journal: every generation install (cold pack,
        # threshold/structural repack, warm-handoff import) is a durable
        # event — emitted outside every cache lock (ESTP-L02)
        from ..common import flightrec as _fr
        _fr.record("plane_rebuild", kind=kind, trigger=trigger, mode=mode)

    def _record_delta_serve(self, kind: str, n: int) -> None:
        from ..common import telemetry as _tm
        with self._metric_lock:
            c = self._delta_serve_counts.get(kind)
            if c is None:
                c = self._delta_serve_counts[kind] = _tm.Counter()
        c.inc(n)

    def rebuild_stats(self) -> Dict[str, int]:
        """Rollup for benches/tests: rebuild counts by mode and trigger,
        plus delta-served query count."""
        with self._metric_lock:
            out: Dict[str, int] = {"sync": 0, "background": 0,
                                   "cold": 0, "threshold": 0,
                                   "structure": 0, "delta_serves": 0}
            for (kind, trigger, mode), c in self._rebuild_counts.items():
                out[mode] = out.get(mode, 0) + int(c.value)
                out[trigger] = out.get(trigger, 0) + int(c.value)
            for c in self._delta_serve_counts.values():
                out["delta_serves"] += int(c.value)
        return out

    # -- shared plumbing -----------------------------------------------------

    def generations(self) -> list:
        """Locked snapshot of every live serving generation (lexical +
        kNN). Stats/health surfaces iterate THIS, never the raw dicts —
        a nodes-stats scrape racing the repack thread's swap would
        otherwise walk a dict mid-mutation (ESTP-R01, found by the
        first full scan)."""
        with self._gen_lock:
            racedep.note_read("plane_cache.generations", self)
            return list(self._planes.values()) + \
                list(self._knn_planes.values())

    def serving_batchers(self) -> list:
        """The micro-batchers of every live generation AND fused-plan
        runner (stats rollup)."""
        with self._gen_lock:
            runners = list(self._fused_runners.values())
        out = []
        for gen in self.generations() + runners:
            b = getattr(gen, "_microbatcher", None)
            if b is not None:
                out.append(b)
        return out

    def fused_runner_for(self, segments: Sequence[Segment],
                         mapper: MapperService, text_field: str,
                         knn_field: Optional[str] = None):
        """The one-dispatch planner's executor for this segment list —
        a ``query_planner.FusedPlanRunner`` over the (text, knn)
        serving-generation pair — or None when either generation is
        unavailable (route ineligible / mid-repack): the caller falls
        back to the legacy two-dispatch path."""
        segments = [s for s in segments if s.n_docs > 0]
        if not segments:
            return None
        tgen = self.plane_for(segments, mapper, text_field)
        if tgen is None:
            return None
        kgen = None
        if knn_field is not None:
            kgen = self.knn_plane_for(segments, mapper, knn_field)
            if kgen is None:
                return None
        key = (id(tgen), id(kgen) if kgen is not None else None)
        with self._gen_lock:
            r = self._fused_runners.get(key)
            if r is not None:
                self._fused_runners.move_to_end(key)
                return r
        from .query_planner import FusedPlanRunner
        r = FusedPlanRunner(tgen, kgen, cache=self)
        doomed = []
        with self._gen_lock:
            raced = self._fused_runners.get(key)
            if raced is not None:
                return raced
            if self._closed:
                return None
            self._fused_runners[key] = r
            while len(self._fused_runners) > self.FUSED_RUNNER_CACHE_MAX:
                _k, old = self._fused_runners.popitem(last=False)
                doomed.append(old)
        for old in doomed:
            self._retire(old)
        return r

    @staticmethod
    def _attach_batcher(plane, knn: bool = False):
        """Pre-create the plane's micro-batcher at plane-build time and
        kick off its serving-shape-lattice warmup (background thread; see
        ``microbatch.PlaneMicroBatcher.warmup``) — a first-hit XLA
        compile landing mid-traffic is the multi-second serving-p99
        signature. Host-serving (CPU) planes compile nothing so warmup
        returns immediately. ``ES_TPU_SERVING_WARMUP=0`` disables."""
        import os
        from .microbatch import KnnPlaneMicroBatcher, PlaneMicroBatcher
        cls = KnnPlaneMicroBatcher if knn else PlaneMicroBatcher
        batcher = cls(plane)
        plane._microbatcher = batcher
        if os.environ.get("ES_TPU_SERVING_WARMUP", "1").lower() \
                not in ("0", "false"):
            batcher.warmup()
        return batcher

    @staticmethod
    def _retire(plane) -> None:
        """Stop a superseded/evicted plane's in-flight warmup so rebuild
        storms (refresh-heavy indices) don't stack background compile
        threads each pinning an orphaned corpus copy."""
        b = plane.__dict__.get("_microbatcher") \
            if isinstance(plane, _ServingGeneration) \
            else getattr(plane, "_microbatcher", None)
        if b is not None:
            b.retire()

    def _release_gen(self, gen) -> None:
        """Release a generation's (or bare plane's) breaker reservation
        and retire its batcher — plus any fused-plan runner built over
        it (a stale runner would pin the superseded corpus). Both tier
        ledgers drain: a hot generation holds ``accounting`` (device)
        bytes, a warm one ``host_tier`` bytes."""
        from ..common.breakers import DEFAULT as _breakers
        acct = _breakers.breaker("accounting")
        acct.release(getattr(gen, "_acct_bytes", 0))
        _breakers.breaker("host_tier").release(
            getattr(gen, "_host_acct_bytes", 0))
        self._retire(gen)
        with self._gen_lock:
            doomed = [k for k, r in self._fused_runners.items()
                      if r.text_gen is gen or r.knn_gen is gen]
            runners = [self._fused_runners.pop(k) for k in doomed]
        for r in runners:
            self._retire(r)

    def _get_mesh(self):
        # every read goes through _mesh_lock — a lock-free fast path
        # would empty the static lockset intersection (ESTP-R01), and
        # one uncontended acquire is noise next to a plane build. Leaf
        # lock: nothing inside takes _gen_lock, so build paths holding
        # _gen_lock nest safely (gen -> mesh only).
        with self._mesh_lock:
            mesh = self._mesh
        if mesh is not None:
            return mesh
        # build OUTSIDE the lock: the cold build (jax import + device
        # enumeration + the es_mesh_devices gauge registration, or an
        # arbitrary user factory) can take seconds and must not stall
        # stats scrapes on the lock — and telemetry must never run
        # under a serving lock (ESTP-L02). Concurrent cold builders
        # race benignly: the first swap wins, the loser's mesh is
        # dropped (meshes hold no device memory).
        if self._mesh_factory is not None:
            mesh = self._mesh_factory()
            # the factory mesh IS the serving mesh: own the idle-device
            # health gauge the same way mesh_from_env does for the
            # default path (auxiliary make_search_mesh builds don't)
            import jax
            from ..parallel.mesh import record_mesh_devices
            used = int(mesh.devices.size)
            record_mesh_devices(used,
                                max(len(jax.devices()) - used, 0))
        else:
            # serving default: the (replica, shard) mesh over EVERY
            # available device — all devices on the shard axis unless
            # ES_TPU_MESH_SHARDS / ES_TPU_MESH_REPLICAS say otherwise
            # (parallel/mesh.mesh_from_env) — so per-device corpus
            # bytes scale ~1/n_shards out of the box.
            from .. import parallel as par
            mesh = par.mesh_from_env()
        with self._mesh_lock:
            if self._mesh is None:
                self._mesh = mesh
            return self._mesh

    def _mesh_fanout(self):
        """(shard-axis devices, replica-axis devices) of the serving
        mesh — pack paths pad shard lists to a shard-axis multiple and
        scale breaker estimates by the replica fan-out."""
        from ..parallel.mesh import AXIS_REPLICA, AXIS_SHARD
        mesh = self._get_mesh()
        return mesh.shape[AXIS_SHARD], mesh.shape[AXIS_REPLICA]

    def _next_ver(self) -> int:
        with self._gen_lock:
            self._gen_ver += 1
            return self._gen_ver

    # -- repack scheduling ---------------------------------------------------

    def _delta_over_threshold(self, gen) -> bool:
        d = gen.delta_docs()
        return d > max(1, int(gen.base_docs * self.REPACK_DELTA_FRACTION))

    def _schedule_repack(self, kind: str, field: str,
                         segments: Sequence[Segment],
                         mapper: MapperService, trigger: str) -> None:
        """Fold the current segment list into a new base generation off
        the request thread, then swap. One in-flight repack per (kind,
        field); ``repack_mode == "sync"`` runs inline (tests)."""
        with self._gen_lock:
            if self._closed or (kind, field) in self._repacking:
                return
            self._repacking.add((kind, field))
            self._repack_threads = [t for t in self._repack_threads
                                    if t.is_alive()]
        segments = list(segments)

        def _run():
            t0 = time.perf_counter()
            try:
                if kind == "text":
                    self._build_text_generation(segments, mapper, field,
                                                trigger=trigger,
                                                mode="background")
                else:
                    self._build_knn_generation(segments, mapper, field,
                                               trigger=trigger,
                                               mode="background")
                swap_ms = (time.perf_counter() - t0) * 1e3
                self._swap_ms[kind].observe(swap_ms)
                from ..common import flightrec as _fr
                _fr.record("plane_swap", kind=kind, field=field,
                           trigger=trigger, ms=round(swap_ms, 3))
            except Exception:   # noqa: BLE001 — a failed repack must
                pass            # never take down serving; retried later
            finally:
                with self._gen_lock:
                    self._repacking.discard((kind, field))

        if self.repack_mode == "sync":
            _run()
            return
        t = threading.Thread(target=_run, daemon=True,
                             name=f"es-repack-{kind}-{field}")
        with self._gen_lock:
            self._repack_threads.append(t)
        t.start()

    def drain_repacks(self, timeout: float = 30.0) -> None:
        """Join in-flight background repacks (tests / orderly shutdown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._gen_lock:
                threads = [t for t in self._repack_threads if t.is_alive()]
                busy = bool(self._repacking)
            if not threads and not busy:
                return
            for t in threads:
                t.join(max(0.01, deadline - time.monotonic()))

    def notify_refresh(self, segments: Sequence[Segment],
                       mapper: MapperService,
                       knn_lists: Optional[Sequence[Sequence[Segment]]]
                       = None) -> None:
        """Engine refresh/merge hook (``index/engine.py`` →
        ``IndexService``): reconcile every cached generation against the
        new segment list NOW — delta packs and repack scheduling happen
        at refresh time on the indexing thread, not on the first search
        to notice the signature change. Never builds cold planes.

        ``segments`` is the POOLED (cross-shard) list — the space text
        generations serve in. ``knn_lists`` are the candidate views kNN
        generations may be keyed by (per-index-shard lists from the
        distributed searcher, plus the pooled list): each kNN generation
        reconciles against the candidate matching it with the SMALLEST
        delta, so another shard's corpus is never mistaken for this
        generation's delta tier (which would schedule repacks onto a
        pooled list no per-shard probe can ever match)."""
        segments = [s for s in segments if s.n_docs > 0]
        if not segments:
            return
        with self._gen_lock:
            # _closed is read under the lock it is written under —
            # release() racing a refresh listener must not see a torn
            # view of (closed, registry) (ESTP-R01)
            if self._closed:
                return
            text_fields = list(self._planes)
        for field in text_fields:
            sig = self._signature(segments, field)
            if sig is None:
                continue
            self._text_generation(segments, mapper, field,
                                  allow_sync_build=False)
        self._knn_reconcile(knn_lists or [segments], mapper)

    def _knn_reconcile(self, lists: Sequence[Sequence[Segment]],
                       mapper: MapperService) -> None:
        with self._gen_lock:
            items = list(self._knn_planes.items())
        for key, gen in items:
            field = key[0]
            best = None           # (delta_count, filtered_list, match)
            for lst in lists:
                lstf = [s for s in lst if s.n_docs > 0]
                if not lstf or \
                        self._knn_signature(lstf, field) is None:
                    continue
                m = gen.match(lstf)
                if m is None:
                    continue
                if best is None or len(m[0]) < best[0]:
                    best = (len(m[0]), lstf, m)
            if best is None:
                continue
            _, lstf, (delta_segs, delta_pos, base_pos) = best
            ver = self._next_ver()
            if not delta_segs:
                gen.clear_delta(base_pos, ver)
                continue
            if not self.delta_enabled:
                continue
            gen.update_delta(lstf, delta_segs, delta_pos, base_pos, ver)
            if self._delta_over_threshold(gen):
                self._schedule_repack("knn", field, lstf, mapper,
                                      "threshold")

    # -- lexical plane -------------------------------------------------------

    @staticmethod
    def _signature(segments: Sequence[Segment], field: str) -> Optional[tuple]:
        """Route-eligibility key over the segment list; None → route
        ineligible (deletes, nested docs, absent field)."""
        sig = []
        any_field = False
        for s in segments:
            if s.has_nested or not bool(s.live.all()):
                return None
            if field in s.text_fields:
                any_field = True
            sig.append((s.seg_id, s.n_docs))
        return tuple(sig) if any_field else None

    def _pack_text_shards(self, segments: Sequence[Segment], field: str):
        """(plane shard dicts, cross-segment avgdl) for a base pack."""
        sum_dl = 0.0
        doc_count = 0
        for s in segments:
            sdl, dc = s.field_stats(field)
            sum_dl += sdl
            doc_count += dc
        avgdl = sum_dl / doc_count if doc_count else 1.0
        shards = []
        for seg in segments:
            f = seg.text_fields.get(field)
            if f is None:
                n = seg.n_docs
                shards.append(dict(
                    term_ids={}, df=np.zeros(0, np.int32),
                    offsets=np.zeros(1, np.int64),
                    docs=np.zeros(0, np.int32), tf=np.zeros(0, np.float32),
                    doc_len=np.zeros(n, np.float32), avgdl=avgdl))
            else:
                shards.append(dict(
                    term_ids=f.term_ids, df=f.df, offsets=f.offsets,
                    docs=f.docs_host, tf=f.tf_host,
                    doc_len=f.doc_len_host, avgdl=avgdl))
        return shards, avgdl

    def _build_text_generation(self, segments: Sequence[Segment],
                               mapper: MapperService, field: str, *,
                               trigger: str, mode: str
                               ) -> TextServingGeneration:
        """Full base pack: breaker reservation, plane construction,
        batcher + warmup, atomic swap (releasing the old generation)."""
        from ..parallel.dist_search import DistributedSearchPlane as _P
        shards, avgdl = self._pack_text_shards(segments, field)
        # pad the shard list to a shard-axis multiple with empty shards
        # (no postings, no docs): the mesh partitions the leading corpus
        # dim over the shard axis, and a segment count that doesn't
        # divide it must not bounce the route back to the per-segment
        # path. Padding shards score nothing (no postings) and never
        # emit hits, so base_pos decoding only ever sees real shards.
        s_dev, n_repl = self._mesh_fanout()
        for _ in range((-len(shards)) % s_dev):
            shards.append(_P.empty_pad_shard(avgdl))
        # the dense tier is the big persistent allocation (T_pad × n_pad
        # bf16 per shard): reserve its estimate against the accounting
        # breaker BEFORE building, so an overfull node 429s instead of
        # OOMing inside the constructor
        from ..common.breakers import DEFAULT as _breakers
        from ..utils.shapes import round_up_multiple, round_up_pow2
        acct = _breakers.breaker("accounting")
        n_pad = round_up_pow2(max(
            max(s["doc_len"].shape[0] for s in shards), 1))
        threshold = max(n_pad // 256, 4096)
        t_est = max((min(int((np.asarray(s["df"]) > threshold).sum()),
                         _P.MAX_DENSE_TERMS) for s in shards),
                    default=0)
        nbytes = round_up_multiple(max(t_est, 1), 16) * n_pad * 2 * \
            len(shards) if t_est else 0
        # past the prune threshold the pack also builds the block-max
        # tier (impact-ordered int8 blocks ≈ docs i32 + codes i8 +
        # 12 B/block of bound metadata) and serves the rank-safe pruned
        # scan by default; the delta tier stays eager
        total_docs = sum(int(s["doc_len"].shape[0]) for s in shards)
        n_postings = sum(int(np.asarray(s["docs"]).shape[0])
                         for s in shards)
        bmx_kw = None
        if total_docs >= max(self.lex_prune_min_docs, 1):
            bmx_kw = {}
            nbytes += int(n_postings * 5.2) + 4096
        # device arrays replicate across the replica axis (each replica
        # group holds a full corpus copy), so the reservation scales by
        # the replica fan-out; the label records the per-DEVICE share
        # (shard-axis partitioning divides the bytes each chip holds)
        nbytes *= max(n_repl, 1)
        acct.add_estimate(
            nbytes, f"<serving plane [{field}] mesh {n_repl}x{s_dev}, "
                    f"~{nbytes // max(s_dev * n_repl, 1)} B/device>")
        try:
            plane = _P(self._get_mesh(), shards, field, blockmax=bmx_kw)
        except Exception:
            acct.release(nbytes)
            raise
        plane._acct_bytes = nbytes
        gen = TextServingGeneration(plane, segments, field, avgdl, self)
        return self._install_text_generation(gen, field, trigger, mode)

    def _install_text_generation(self, gen: TextServingGeneration,
                                 field: str, trigger: str,
                                 mode: str) -> TextServingGeneration:
        """Batcher + atomic swap, shared by the pack path and the
        warm-handoff import."""
        self._attach_batcher(gen)
        with self._gen_lock:
            racedep.note_write("plane_cache.generations", self)
            if self._closed:
                self._release_gen(gen)
                return gen
            old = self._planes.get(field)
            self._planes[field] = gen
        if old is not None:
            # double-buffering: the old generation served until this
            # swap; drop its reservation and stop its warmup now
            self._release_gen(old)
        self._record_rebuild("text", trigger, mode)
        # tier sweep OUTSIDE _gen_lock: the new resident plane may push
        # the node past its HBM budget — spill the LRU ones
        self.tiers.touch(gen)
        self.tiers.enforce_budget()
        return gen

    def plane_for(self, segments: Sequence[Segment], mapper: MapperService,
                  field: str):
        """The serving generation for this segment list, or None when the
        route is ineligible (deletes, nested docs, absent field) or the
        base is mid-repack after a structural change (the per-segment
        path serves the gap)."""
        segments = [s for s in segments if s.n_docs > 0]
        if not segments:
            return None
        if sum(s.n_docs for s in segments) < self.min_docs:
            return None
        if self._signature(segments, field) is None:
            return None
        return self._text_generation(segments, mapper, field,
                                     allow_sync_build=True)

    def _text_generation(self, segments, mapper, field: str,
                         allow_sync_build: bool):
        with self._gen_lock:
            gen = self._planes.get(field)
        if gen is not None:
            m = gen.match(segments)
            if m is not None:
                delta_segs, delta_pos, base_pos = m
                ver = self._next_ver()
                if not delta_segs:
                    gen.clear_delta(base_pos, ver)
                    return gen
                if self.delta_enabled:
                    gen.update_delta(segments, delta_segs, delta_pos,
                                     base_pos, ver)
                    if self._delta_over_threshold(gen):
                        self._schedule_repack("text", field, segments,
                                              mapper, "threshold")
                        if self.repack_mode == "sync":
                            with self._gen_lock:
                                return self._planes.get(field)
                    return gen
            elif self.delta_enabled:
                # merge/delete restructured the base: the old plane's hit
                # coordinates no longer decode against this list — repack
                # in the background, per-segment path serves meanwhile
                self._schedule_repack("text", field, segments, mapper,
                                      "structure")
                if self.repack_mode == "sync":
                    with self._gen_lock:
                        return self._planes.get(field)
                return None
        if not allow_sync_build:
            return None
        # the cold TIER beats a cold PACK: a demoted pack file matching
        # this list promotes through the handoff import (chunked local
        # read + device upload — no O(postings) re-pack)
        promoted = self._promote_from_cold("text", field, segments,
                                           mapper)
        if promoted is not None:
            return promoted
        # cold start (first build for this field) or legacy mode
        # (delta_enabled=False: rebuild-every-refresh, the pre-generation
        # behavior the live-indexing bench measures as its baseline)
        return self._build_text_generation(
            segments, mapper, field,
            trigger="cold" if gen is None else "structure", mode="sync")

    # -- kNN plane -----------------------------------------------------------

    @staticmethod
    def _knn_signature(segments: Sequence[Segment],
                       field: str) -> Optional[tuple]:
        """Route-eligibility key for the kNN plane; None → ineligible
        (deletes, nested docs, or the field has no vectors anywhere — the
        plane packs exists-masked rows but per-doc liveness/parent masks
        stay on the per-segment path)."""
        sig = []
        any_field = False
        for s in segments:
            if s.has_nested or not bool(s.live.all()):
                return None
            if field in s.vector_fields:
                any_field = True
            sig.append((s.seg_id, s.n_docs))
        return tuple(sig) if any_field else None

    def knn_plane_for(self, segments: Sequence[Segment],
                      mapper: MapperService, field: str):
        """The kNN serving generation (``DistributedKnnPlane`` base —
        pack-time corpus invariants + blocked running-top-k — plus a BLAS
        delta tier) for this segment list, or None when the route is
        ineligible. One SEGMENT per plane shard, same as the lexical
        plane, so tie order matches the per-segment path."""
        from ..index.mapping import DenseVectorFieldType
        segments = [s for s in segments if s.n_docs > 0]
        if not segments:
            return None
        ft = mapper.field_type(field)
        if not isinstance(ft, DenseVectorFieldType):
            return None
        if self._knn_signature(segments, field) is None:
            return None
        return self._knn_generation(segments, mapper, field,
                                    allow_build=True)

    def _knn_generation(self, segments, mapper, field: str,
                        allow_build: bool):
        with self._gen_lock:
            items = list(self._knn_planes.items())
        # pick the generation whose base covers this list with the
        # SMALLEST delta (a pooled probe must prefer a pooled base over
        # eagerly scanning every other shard's corpus as "delta")
        best = None                   # (delta_count, key, gen, match)
        for key, gen in items:
            if key[0] != field:
                continue
            m = gen.match(segments)
            if m is None:
                continue
            if best is None or len(m[0]) < best[0]:
                best = (len(m[0]), key, gen, m)
        if best is not None:
            _, key, gen, (delta_segs, delta_pos, base_pos) = best
            with self._gen_lock:
                if key in self._knn_planes:
                    self._knn_planes.move_to_end(key)
                self._knn_build_streak = 0
            ver = self._next_ver()
            if not delta_segs:
                gen.clear_delta(base_pos, ver)
                return gen
            if self.delta_enabled:
                gen.update_delta(segments, delta_segs, delta_pos,
                                 base_pos, ver)
                if self._delta_over_threshold(gen):
                    self._schedule_repack("knn", field, segments, mapper,
                                          "threshold")
                return gen
            # legacy mode: fall through to a full rebuild
        if not allow_build:
            return None
        # cold-tier probe before any build-vs-thrash reasoning: a
        # spilled plane of this exact base is this probe's own data
        promoted = self._promote_from_cold("knn", field, segments,
                                           mapper)
        if promoted is not None:
            return promoted
        with self._gen_lock:
            # read under the lock: the streak is reset/bumped under it,
            # and an off-lock read races the repack thread (ESTP-R01)
            build_streak = self._knn_build_streak
        if build_streak >= self.KNN_PLANE_CACHE_MAX:
            # every recent probe missed: building would evict entries the
            # same request needs again (O(corpus) repack per query) — the
            # per-segment fallback is the cheaper correct path
            return None
        gen = self._build_knn_generation(segments, mapper, field,
                                         trigger="cold", mode="sync")
        if gen is not None:
            with self._gen_lock:
                self._knn_build_streak += 1
        return gen

    @staticmethod
    def _pack_knn_shards(segments: Sequence[Segment], field: str):
        """(plane shard dicts, dim) for a kNN base pack, or None when the
        field's dims disagree across segments — shared by the build path
        and the warm-handoff bundle export."""
        shards = []
        for seg in segments:
            f = seg.vector_fields.get(field)
            if f is None:
                shards.append(dict(
                    vectors=np.zeros((seg.n_docs, 1), np.float32),
                    exists=np.zeros(seg.n_docs, bool)))
            else:
                ex = np.zeros(seg.n_docs, bool)
                ex[: f.exists.shape[0]] = f.exists
                shards.append(dict(vectors=f.matrix_host, exists=ex))
        dims = {s["vectors"].shape[1] for s in shards if s["exists"].any()}
        if len(dims) > 1:
            return None
        dim = dims.pop() if dims else 1
        for s in shards:
            if not s["exists"].any():
                s["vectors"] = np.zeros((s["exists"].shape[0], dim),
                                        np.float32)
        return shards, dim

    def _build_knn_generation(self, segments, mapper, field: str, *,
                              trigger: str, mode: str):
        """Full kNN base pack + atomic swap into the LRU (superseded
        generations of the same field sharing base segments are
        released first — a repack kept part of the list, so identity
        overlap marks the predecessors; generations for OTHER index
        shards of the same field are disjoint and survive)."""
        from ..index.mapping import DenseVectorFieldType
        ft = mapper.field_type(field)
        if not isinstance(ft, DenseVectorFieldType):
            return None
        from ..parallel.dist_search import DistributedKnnPlane
        # step similarity: ranking by raw dot is order-equivalent for
        # max_inner_product (its _score transform is monotone); unknown
        # similarity strings keep the per-segment path's quirks
        similarity = {"cosine": "cosine", "dot_product": "dot_product",
                      "l2_norm": "l2_norm",
                      "max_inner_product": "dot_product"}.get(
                          getattr(ft, "similarity", "cosine"))
        if similarity is None:
            return None
        got = self._pack_knn_shards(segments, field)
        if got is None:
            return None
        shards, dim = got
        # pad the shard list to a shard-axis multiple with empty shards
        # (exists all-False — they score NEG_INF and never emit hits),
        # same as the lexical pack: the corpus dim must divide the mesh
        s_dev, n_repl = self._mesh_fanout()
        for _ in range((-len(shards)) % s_dev):
            shards.append(DistributedKnnPlane.empty_pad_shard(dim))
        # the packed corpus (f32[S, n_pad, dim] + invariants) is the big
        # persistent allocation: reserve it against the accounting breaker
        # before building, like the lexical plane's dense tier
        from ..common.breakers import DEFAULT as _breakers
        from ..utils.shapes import round_up_pow2
        acct = _breakers.breaker("accounting")
        n_pad = round_up_pow2(max(max(s["exists"].shape[0]
                                      for s in shards), 1))
        nbytes = len(shards) * n_pad * (dim * 4 + 5)
        # past the IVF threshold the pack also builds the quantized tier
        # (int8 codes + scale/off/row maps ≈ dim+12 B/row) and serves
        # cluster-pruned by default; the delta tier stays exact
        total_docs = sum(int(s["exists"].shape[0]) for s in shards)
        ivf_kw = None
        if total_docs >= max(self.knn_ivf_min_docs, 1):
            ivf_kw = {}
            nbytes += len(shards) * n_pad * (dim + 12)
        key = (field, tuple(id(s) for s in segments))
        # replica groups hold full corpus copies (see the lexical pack)
        nbytes *= max(n_repl, 1)
        acct.add_estimate(
            nbytes, f"<knn serving plane [{field}] mesh {n_repl}x{s_dev},"
                    f" ~{nbytes // max(s_dev * n_repl, 1)} B/device>")
        try:
            plane = DistributedKnnPlane(self._get_mesh(), shards,
                                        similarity=similarity,
                                        ivf=ivf_kw)
        except Exception:
            acct.release(nbytes)
            raise
        plane._acct_bytes = nbytes
        gen = KnnServingGeneration(plane, segments, field, self)
        return self._install_knn_generation(gen, key, nbytes, trigger,
                                            mode)

    def _install_knn_generation(self, gen: KnnServingGeneration,
                                key: tuple, nbytes: int, trigger: str,
                                mode: str):
        """Atomic swap into the kNN LRU + batcher, shared by the pack
        path and the warm-handoff import. Evicts ONLY at swap time,
        never before the build: the predecessor generations keep
        serving for the whole pack window (double-buffering — a
        pre-build eviction would leave a gap that concurrent probes
        fill with synchronous request-thread cold builds, the exact
        storm this module eliminates). The breaker transiently holds
        old+new, same as the lexical path."""
        from ..common.breakers import DEFAULT as _breakers
        acct = _breakers.breaker("accounting")
        field = key[0]
        new_ids = set(key[1])
        with self._gen_lock:
            racedep.note_write("plane_cache.generations", self)
            raced = self._knn_planes.get(key)
            if raced is not None:
                # another thread built the same base meanwhile: keep the
                # winner, release this copy's reservation
                acct.release(nbytes)
                self._knn_planes.move_to_end(key)
                return raced
            if self._closed:
                acct.release(nbytes)
                return None
            # superseded generations of this field (identity overlap
            # with the new base — a repack kept part of their list) +
            # any LRU overflow go out as the new generation goes in
            doomed = [ok for ok in self._knn_planes
                      if ok[0] == field and ok != key
                      and any(sid in new_ids for sid in ok[1])]
            old_gens = [self._knn_planes.pop(ok) for ok in doomed]
            while len(self._knn_planes) >= self.KNN_PLANE_CACHE_MAX:
                _, g = self._knn_planes.popitem(last=False)
                old_gens.append(g)
            self._knn_planes[key] = gen
        for g in old_gens:
            self._release_gen(g)
        self._attach_batcher(gen, knn=True)
        self._record_rebuild("knn", trigger, mode)
        self.tiers.touch(gen)
        self.tiers.enforce_budget()
        return gen

    # -- warm handoff: plane-bundle export / import --------------------------
    #
    # The packed base plane is a self-contained tensor bundle (CSR
    # postings + frozen avgdl for text, vector matrices + similarity for
    # kNN) keyed by the (seg_id, n_docs) signature of its base segment
    # list. A recovering/rejoining node whose copies carry the same
    # signature (file-based recovery ships the store wholesale;
    # kill-and-rejoin reloads it) can install the donor's bundle as a
    # live serving generation and serve warm immediately — no segment
    # re-extraction, no request-thread cold pack (the rebuild-storm
    # signature). Serialization is the data-only wire codec
    # (common/datacodec): tensors in, tensors out, nothing executable.

    def _bundle_for(self, gen) -> Optional[dict]:
        """One generation → its self-contained handoff bundle (also the
        cold-tier pack-file payload), or None for a foreign/legacy plane
        that cannot export. ``export_packed`` is warm-safe: a demoted
        plane serializes from its host copies without re-upload."""
        try:
            packed = gen.base.export_packed()
        except Exception:   # noqa: BLE001 — foreign/legacy plane
            return None
        doc = {"kind": gen.kind, "field": gen.field,
               "signature": [(s.seg_id, int(s.n_docs))
                             for s in gen.base_segments],
               "packed": packed}
        if gen.kind == "text":
            doc["avgdl"] = float(gen.avgdl)
        return doc

    def export_bundles(self) -> List[dict]:
        """One handoff bundle per live serving generation, carrying the
        plane's POST-pack tensors (``export_packed``: sorted-merge
        tables, dense tier, block-max/IVF tiers, host-CSR) plus the
        frozen invariants (avgdl) and the base segment signature — the
        importer reconstructs bit-identical serving with zero pack
        work."""
        out: List[dict] = []
        for gen in self.generations():
            bundle = self._bundle_for(gen)
            if bundle is not None:
                out.append(bundle)
        return out

    def export_bundle_blobs(self) -> List[dict]:
        """Pre-serialized handoff payloads (``{kind, field, blob}``):
        live generations serialize now; COLD-tier planes ship their
        pack file's text as-is — a spilled plane is its own handoff
        artifact, no re-serialization on the donor offer."""
        from ..common.datacodec import dumps_b64
        out: List[dict] = []
        for bundle in self.export_bundles():
            out.append({"kind": bundle["kind"], "field": bundle["field"],
                        "blob": dumps_b64(bundle)})
        for rec in self.tiers.cold_records():
            try:
                out.append({"kind": rec.kind, "field": rec.field,
                            "blob": self.tiers.cold_blob(rec)})
            except Exception:   # noqa: BLE001 — spill file vanished
                continue
        return out

    def _evict_generation(self, gen) -> bool:
        """Remove ONE generation from the serving registry (cold
        demotion): registry pop under ``_gen_lock``, breaker release +
        batcher retire outside it. False → the generation was no longer
        registered (a racing swap/release already owns its teardown)."""
        found = False
        with self._gen_lock:
            racedep.note_write("plane_cache.generations", self)
            field = getattr(gen, "field", None)
            if self._planes.get(field) is gen:
                self._planes.pop(field)
                found = True
            else:
                for k, g in list(self._knn_planes.items()):
                    if g is gen:
                        self._knn_planes.pop(k)
                        found = True
                        break
        if not found:
            return False
        self._release_gen(gen)
        return True

    def _promote_from_cold(self, kind: str, field: str,
                           segments: Sequence[Segment],
                           mapper: MapperService):
        """Probe the cold tier before a cold pack: a spilled plane whose
        base signature still matches the local segment list promotes
        through the SAME import path warm handoff uses (chunked mmap
        read of the pack file → ``import_bundle``) — device upload only,
        no re-pack. Returns the installed generation or None."""
        for rec in self.tiers.cold_records(kind, field):
            if self._match_signature(segments, rec.signature) is None:
                continue
            try:
                bundle = self.tiers.cold_bundle(rec)
            except Exception:   # noqa: BLE001 — unreadable pack file:
                continue        # fall back to the ordinary cold build
            if not self.import_bundle(bundle, segments, mapper):
                continue
            sig = [(str(a), int(b)) for a, b in rec.signature]
            with self._gen_lock:
                if kind == "text":
                    gen = self._planes.get(field)
                else:
                    gen = next(
                        (g for (f, _k), g in self._knn_planes.items()
                         if f == field and [(s.seg_id, int(s.n_docs))
                                            for s in g.base_segments]
                         == sig), None)
            self.tiers.on_cold_promoted(rec, gen)
            return gen
        return None

    def _match_signature(self, segments: Sequence[Segment],
                         signature) -> Optional[List[Segment]]:
        """Ordered-subsequence match of a bundle's base signature
        against LOCAL segments by (seg_id, n_docs) — identity across
        processes. None → the local copies diverged (ops-based recovery
        re-segmented differently); the caller falls back to a repack."""
        matched: List[Segment] = []
        pos = 0
        for want in signature or ():
            wid, wnd = str(want[0]), int(want[1])
            nxt = next((i for i in range(pos, len(segments))
                        if segments[i].seg_id == wid
                        and int(segments[i].n_docs) == wnd), None)
            if nxt is None:
                return None
            matched.append(segments[nxt])
            pos = nxt + 1
        return matched if matched else None

    def import_bundle(self, bundle: dict, segments: Sequence[Segment],
                      mapper: MapperService) -> bool:
        """Install one handoff bundle as a live serving generation over
        the LOCAL segments matching its base signature. Returns False
        (never raises) when the bundle cannot be adopted — signature
        mismatch, route-ineligible local copies (deletes/nested), or a
        failed build — so recovery degrades to the ordinary cold pack
        instead of failing."""
        try:
            segments = [s for s in segments if s.n_docs > 0]
            matched = self._match_signature(segments,
                                            bundle.get("signature"))
            if matched is None:
                return False
            field = str(bundle["field"])
            if self._have_same_base(bundle.get("kind"), field,
                                    bundle.get("signature")):
                # idempotent: per-shard recovery offers and the
                # replica-wiring trigger race duplicate pulls of the
                # same bundles — a second import of an identical base
                # would only churn generations (and retire the batcher
                # a concurrent probe is using)
                return True
            if bundle.get("kind") == "text":
                if self._signature(matched, field) is None:
                    return False
                return self._import_text_generation(
                    matched, field, float(bundle["avgdl"]),
                    bundle["packed"]) is not None
            if bundle.get("kind") == "knn":
                if self._knn_signature(matched, field) is None:
                    return False
                return self._import_knn_generation(
                    matched, field, bundle["packed"]) is not None
            return False
        except Exception:   # noqa: BLE001 — a bad bundle must degrade
            return False    # to the repack path, never break recovery

    def _have_same_base(self, kind, field: str, signature) -> bool:
        """True when a live generation of (kind, field) already covers
        exactly this base signature."""
        want = [(str(a), int(b)) for a, b in (signature or ())]
        if kind == "text":
            with self._gen_lock:
                gen = self._planes.get(field)
            gens = [gen] if gen is not None else []
        else:
            with self._gen_lock:
                gens = [g for (f, _k), g in self._knn_planes.items()
                        if f == field]
        return any(
            [(s.seg_id, int(s.n_docs)) for s in g.base_segments] == want
            for g in gens)

    def _import_text_generation(self, segments: Sequence[Segment],
                                field: str, avgdl: float, packed: dict):
        """Install a shipped text plane: breaker reservation from the
        bundle's real tensor sizes, ``from_packed`` reconstruction
        (device upload only — no pack), then the shared swap."""
        from ..common.breakers import DEFAULT as _breakers
        from ..parallel.dist_search import DistributedSearchPlane as _P
        acct = _breakers.breaker("accounting")
        nbytes = int(np.asarray(packed["docs"]).nbytes
                     + np.asarray(packed["impacts"]).nbytes)
        if packed.get("dense") is not None:
            # shipped as exact f32; resident as bf16 (half)
            nbytes += int(np.asarray(packed["dense"]).nbytes) // 2
        acct.add_estimate(
            nbytes, f"<serving plane [{field}] warm-handoff import, "
                    f"{nbytes} B>")
        try:
            plane = _P.from_packed(self._get_mesh(), packed)
        except Exception:
            acct.release(nbytes)
            raise
        plane._acct_bytes = nbytes
        gen = TextServingGeneration(plane, segments, field, avgdl, self)
        return self._install_text_generation(gen, field, "handoff",
                                             "import")

    def _import_knn_generation(self, segments: Sequence[Segment],
                               field: str, packed: dict):
        from ..common.breakers import DEFAULT as _breakers
        from ..parallel.dist_search import DistributedKnnPlane
        acct = _breakers.breaker("accounting")
        nbytes = int(packed.get("nbytes") or 0) or (
            int(np.asarray(packed["vecs"]).nbytes)
            + int(np.asarray(packed["vnorm2"]).nbytes)
            + int(np.asarray(packed["exists"]).nbytes))
        acct.add_estimate(
            nbytes, f"<knn serving plane [{field}] warm-handoff "
                    f"import, {nbytes} B>")
        try:
            plane = DistributedKnnPlane.from_packed(self._get_mesh(),
                                                    packed)
        except Exception:
            acct.release(nbytes)
            raise
        plane._acct_bytes = nbytes
        gen = KnnServingGeneration(plane, segments, field, self)
        key = (field, tuple(id(s) for s in segments))
        return self._install_knn_generation(gen, key, nbytes, "handoff",
                                            "import")

    # -- lifecycle -----------------------------------------------------------

    def release(self) -> None:
        """Release every generation's breaker reservation (the owning
        index is closing or being deleted); in-flight repacks see
        ``_closed`` and drop their build instead of swapping it in,
        and are then JOINED so no repack thread outlives its cache
        (ESTP-T01 lifecycle discipline: a late swap into a released
        registry would leak the new plane's breaker bytes)."""
        with self._gen_lock:
            self._closed = True
            racedep.note_write("plane_cache.generations", self)
            gens = list(self._planes.values()) + \
                list(self._knn_planes.values())
            self._planes.clear()
            self._knn_planes.clear()
            runners = list(self._fused_runners.values())
            self._fused_runners.clear()
        for r in runners:
            self._retire(r)
        for gen in gens:
            self._release_gen(gen)
        self.drain_repacks(timeout=5.0)
        # drop the cold tier's pack files; the next _metrics_doc scrape
        # reports explicit per-device zeros (every generation is gone)
        self.tiers.release()
