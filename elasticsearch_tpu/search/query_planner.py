"""One-dispatch query planner: lower a request's bool tree + knn clause
+ rescore window into ONE serving dispatch over both planes.

A hybrid RRF request historically cost two serving dispatches (text
plane, knn plane) plus host-side Python fusion, and bool trees were
scored clause-by-clause on the per-segment path — the opposite of the
"Lucene is all you need" single-engine retrieval story (arxiv
2308.14963; Anserini's dense+sparse integration, arxiv 2304.12139).
This module is the small query compiler that closes that gap:

- :func:`lower_body` recognizes request bodies whose retrieval pipeline
  the planes can run END TO END — a bool tree of bag-of-terms clauses
  over one text field (must/should/filter/must_not + resolved
  minimum_should_match), at most one filter-free knn clause, RRF or
  linear rank fusion, and a rescore window whose rescore_query is a bag
  over the same field — and compiles it into a :class:`FusedPlan`.
- :class:`FusedPlanRunner` executes a plan batch through the serving
  GENERATIONS (``plane_route.py``) recast as providers of scoring
  *stages*: the lexical bool scan, the kNN blocked scan, rank fusion
  and the rescore-window reorder. On an accelerator backend the whole
  pipeline is one jitted program over both planes' tensors
  (``parallel/dist_search.build_fused_hybrid_step``), bucketed into the
  same (B, k, L, params) shape lattice as every other serving step —
  it compiles per request SHAPE, never per query. On the CPU backend
  the stages are the planes' host-native scorers, with the lexical and
  kNN stages running concurrently inside the one dispatch (the BLAS
  kNN scan releases the GIL under the lexical scatter-adds).

Non-lowerable bodies — and lowerable ones whose runner cannot serve
them (dense-tier terms on a jitted bool slice, mis-aligned base
generations) — fall back to the existing two-dispatch + host-fusion
path unchanged; ``es_planner_lowered_total{outcome}`` counts both
verdicts. ``ES_TPU_FUSED_PLANNER=0`` disables the planner outright
(the bisection knob)."""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..index.mapping import DenseVectorFieldType, MapperService
from ..ops.fused_query import MAX_BOOL_CLAUSES
from .plane_route import extract_bag_of_terms

#: body features the fused path cannot serve (same set the plane route
#: excludes, minus the three the planner exists to fuse; ``aggs`` left
#: this list in PR 16 — agg trees lower via ``agg_planner.lower_aggs``)
_FUSED_INCOMPATIBLE = ("sort", "collapse", "suggest", "search_after",
                       "min_score")

_RESCORE_MODES = ("total", "multiply", "avg", "max", "min")


def planner_enabled() -> bool:
    """The fused on/off env gate (bisection knob): default on."""
    return os.environ.get("ES_TPU_FUSED_PLANNER", "1").lower() \
        not in ("0", "false")


@dataclass
class KnnPlan:
    field: str
    query_vector: np.ndarray
    k: int
    num_candidates: int
    boost: float = 1.0
    nprobe: Optional[int] = None
    rerank: Optional[int] = None


@dataclass
class RescorePlan:
    terms: List[str]
    qw: float = 1.0
    rw: float = 1.0
    mode: str = "total"
    window: int = 10


@dataclass
class FusedPlan:
    """A lowered request: the planner's IR. ``bag`` is set (and
    ``clauses`` holds the single should clause) when the query is a
    plain bag of terms — the lexical stage then rides the existing
    ``serve`` path with its pruning tier; real bool trees use the
    clause-bit bool stage."""
    field: str
    clauses: List[Tuple[str, List[str]]]
    msm: int
    bag: Optional[List[str]] = None
    knn: Optional[KnnPlan] = None
    fusion: Optional[str] = None          # "rrf" | "sum" | None
    rank_constant: int = 60
    rank_window: int = 10
    rescore: Optional[RescorePlan] = None
    k: int = 10                           # size + from
    window_text: int = 10                 # lexical stage dispatch width
    aggs: Optional[object] = None         # agg_planner.AggPlan
    lower_ms: float = 0.0

    def n_stages(self) -> int:
        """Stages this plan fuses into one dispatch (the
        ``es_planner_stages_per_dispatch`` observation)."""
        n = 1                              # lexical scan
        if self.knn is not None:
            n += 2                         # knn scan + rank fusion
        if self.rescore is not None:
            n += 1
        if self.aggs is not None:
            n += self.aggs.n_stages        # one stage per tree node
        return n


def _lower_bool_tree(query_spec, mapper: MapperService):
    """Query spec → (field, clauses, msm, bag|None) when every clause is
    a bag of terms over ONE text field, else None. ``bag`` is the merged
    single-clause form when :func:`extract_bag_of_terms` recognizes the
    whole query (pure-should shapes)."""
    ext = extract_bag_of_terms(query_spec, mapper)
    if ext is not None:
        field, terms = ext
        return field, [("should", list(terms))], 1, list(terms)
    if not isinstance(query_spec, dict) or len(query_spec) != 1:
        return None
    (kind, body), = query_spec.items()
    if kind != "bool" or not isinstance(body, dict):
        return None
    if set(body) - {"must", "should", "filter", "must_not",
                    "minimum_should_match", "boost"}:
        return None
    if body.get("boost", 1.0) != 1.0:
        return None
    field = None
    clauses: List[Tuple[str, List[str]]] = []
    n_should = n_positive = 0
    for role in ("must", "should", "filter", "must_not"):
        members = body.get(role)
        if members is None:
            continue
        if isinstance(members, dict):
            members = [members]
        if not isinstance(members, list):
            return None
        for member in members:
            sub = extract_bag_of_terms(member, mapper)
            if sub is None:
                return None
            f, terms = sub
            if field is None:
                field = f
            elif field != f:
                return None       # cross-field: scores/stats differ
            clauses.append((role, list(terms)))
            if role == "should":
                n_should += 1
            if role in ("must", "should", "filter"):
                n_positive += 1
    if field is None or not clauses or n_positive == 0:
        # a pure must_not tree matches "everything else" — the plane
        # only sees docs its candidate runs touch, so it cannot serve it
        return None
    if len(clauses) > MAX_BOOL_CLAUSES:
        return None
    msm = body.get("minimum_should_match")
    if msm is None:
        msm_eff = 0 if any(r in ("must", "filter")
                           for r, _ in clauses) else (1 if n_should
                                                     else 0)
    else:
        if not isinstance(msm, int) or isinstance(msm, bool) or msm < 0:
            return None           # percent / negative forms: fall back
        msm_eff = msm
    return field, clauses, msm_eff, None


def _lower_knn(knn_spec, mapper: MapperService) -> Optional[KnnPlan]:
    if isinstance(knn_spec, list):
        if len(knn_spec) != 1:
            return None
        knn_spec = knn_spec[0]
    if not isinstance(knn_spec, dict):
        return None
    if set(knn_spec) - {"field", "query_vector", "k", "num_candidates",
                        "boost", "nprobe", "rerank"}:
        return None               # filter / similarity override etc.
    field = knn_spec.get("field")
    qv = knn_spec.get("query_vector")
    if field is None or qv is None:
        return None
    if not isinstance(mapper.field_type(field), DenseVectorFieldType):
        return None
    try:
        k = int(knn_spec.get("k", 10))
        num_candidates = int(knn_spec.get("num_candidates", max(k, 10)))
        boost = float(knn_spec.get("boost", 1.0))
    except (TypeError, ValueError):
        return None
    if k < 1 or num_candidates < k:
        return None
    nprobe = knn_spec.get("nprobe")
    rerank = knn_spec.get("rerank")
    if nprobe is not None:
        nprobe = int(nprobe)
        if nprobe < 0:
            return None
    if rerank is not None:
        rerank = int(rerank)
        if rerank < 1:
            return None
    return KnnPlan(field=field,
                   query_vector=np.asarray(qv, np.float32), k=k,
                   num_candidates=num_candidates, boost=boost,
                   nprobe=nprobe, rerank=rerank)


def _lower_rescore(rescore_spec, field: str,
                   mapper: MapperService) -> Optional[RescorePlan]:
    if isinstance(rescore_spec, list):
        if len(rescore_spec) != 1:
            return None
        rescore_spec = rescore_spec[0]
    if not isinstance(rescore_spec, dict) or \
            set(rescore_spec) - {"window_size", "query"}:
        return None
    q = rescore_spec.get("query") or {}
    if set(q) - {"rescore_query", "query_weight",
                 "rescore_query_weight", "score_mode"}:
        return None
    rq = q.get("rescore_query")
    if rq is None:
        return None
    sub = extract_bag_of_terms(rq, mapper)
    if sub is None or sub[0] != field:
        return None
    mode = q.get("score_mode", "total")
    if mode not in _RESCORE_MODES:
        return None
    try:
        return RescorePlan(terms=list(sub[1]),
                           qw=float(q.get("query_weight", 1.0)),
                           rw=float(q.get("rescore_query_weight", 1.0)),
                           mode=mode,
                           window=int(rescore_spec.get("window_size",
                                                       10)))
    except (TypeError, ValueError):
        return None


def lower_body(body: dict, mapper: MapperService) -> Optional[FusedPlan]:
    """Request body → :class:`FusedPlan`, or None when any part of the
    pipeline is outside the planner's fragment (the caller then takes
    the existing path unchanged). Plain bag queries WITHOUT knn or
    rescore are deliberately not lowered — the existing plane route
    already serves them (request cache, pruning tier and all)."""
    t0 = time.perf_counter()
    if any(body.get(k) for k in _FUSED_INCOMPATIBLE):
        return None
    agg_plan = None
    agg_spec = body.get("aggs") or body.get("aggregations")
    if agg_spec is not None:
        from .agg_planner import fused_aggs_enabled, lower_aggs
        if not fused_aggs_enabled():
            return None
        agg_plan = lower_aggs(agg_spec, mapper)
        if agg_plan is None:
            return None           # tree outside the fused fragment
    k = int(body.get("size", 10)) + int(body.get("from", 0))
    if k <= 0:
        if agg_plan is None:
            return None
        k = 0                     # size:0 analytics — agg stages only
    query_spec = body.get("query")
    knn_spec = body.get("knn")
    rank_spec = body.get("rank")
    rescore_spec = body.get("rescore")
    if query_spec is None:
        return None               # knn-only: the knn route serves it
    if agg_plan is not None and knn_spec is not None:
        # top-level knn widens the match set the aggs run over
        # (hybrid hits participate in aggregations) — the agg stages
        # pool text masks only, so hybrid analytics keeps the legacy
        # path
        return None
    lowered = _lower_bool_tree(query_spec, mapper)
    if lowered is None:
        return None
    field, clauses, msm, bag = lowered
    knn = None
    fusion = None
    rank_constant, rank_window = 60, max(k, 10)
    if knn_spec is not None:
        knn = _lower_knn(knn_spec, mapper)
        if knn is None:
            return None
        if rank_spec is not None:
            if not isinstance(rank_spec, dict) or \
                    set(rank_spec) != {"rrf"}:
                return None
            rrf = rank_spec.get("rrf") or {}
            if not isinstance(rrf, dict) or \
                    set(rrf) - {"rank_constant", "rank_window_size"}:
                return None
            try:
                rank_constant = int(rrf.get("rank_constant", 60))
                rank_window = int(rrf.get("rank_window_size",
                                          max(k, 10)))
            except (TypeError, ValueError):
                return None
            if rank_constant < 1 or rank_window < 1:
                return None
            fusion = "rrf"
        else:
            fusion = "sum"
    elif rank_spec is not None:
        return None               # rank without knn: nothing to fuse
    rescore = None
    if rescore_spec is not None:
        rescore = _lower_rescore(rescore_spec, field, mapper)
        if rescore is None:
            return None
    if knn is None and rescore is None and bag is not None and \
            agg_plan is None:
        return None               # plain bag: existing plane route
    window_text = max(k, rank_window) if fusion == "rrf" else k
    if rescore is not None:
        window_text = max(window_text, rescore.window)
    plan = FusedPlan(field=field, clauses=clauses, msm=msm, bag=bag,
                     knn=knn, fusion=fusion,
                     rank_constant=rank_constant,
                     rank_window=rank_window, rescore=rescore, k=k,
                     window_text=window_text, aggs=agg_plan)
    plan.lower_ms = (time.perf_counter() - t0) * 1e3
    return plan


# ---------------------------------------------------------------------------
# Plan execution: the serving generations as stage providers
# ---------------------------------------------------------------------------


class FusedFallback(Exception):
    """The runner cannot serve this dispatch after all (dense-tier
    terms on a jitted slice, delta+rescore on a device backend, …):
    the caller re-serves through the legacy two-dispatch path."""


def knn_raw_to_score_host(similarity: str, raw: float) -> float:
    """Host scalar twin of ``ops/fused_query.knn_raw_to_score`` —
    identical formulas to ``ShardSearcher._knn_score_from_raw`` so the
    fused path's knn _scores match the legacy knn section bit-for-bit."""
    if similarity in ("cosine", "cos", "dot_product"):
        return (1.0 + raw) / 2.0
    if similarity == "max_inner_product":
        return 1.0 / (1.0 - raw) if raw < 0 else raw + 1.0
    return 1.0 / (1.0 + max(0.0, -raw))


def rrf_fuse_rows(rankings, rc: int):
    """THE host RRF fusion (float64 dict, rankings in list order,
    (score desc, shard asc, doc asc) sort) — one copy shared by the
    legacy knn section (``shard_search.py``) and the fused runner, so
    fused-vs-two-dispatch parity is bitwise BY SHARED CODE, not by
    keeping two loops in sync. ``rankings``: ranked
    ``[(score, shard, doc), ...]`` lists."""
    rrf: Dict[Tuple[int, int], float] = {}
    for ranking in rankings:
        for rank_i, row in enumerate(ranking):
            si, d = row[1], row[2]
            rrf[(si, d)] = rrf.get((si, d), 0.0) + 1.0 / (rc + rank_i
                                                          + 1)
    return sorted(((sc, si, d) for (si, d), sc in rrf.items()),
                  key=lambda c: (-c[0], c[1], c[2]))


def sum_fuse_rows(rankings):
    """THE host linear (hybrid-sum) fusion — docs in several rankings
    sum their scores in list order; shared by the legacy knn section
    and the fused runner (see :func:`rrf_fuse_rows`)."""
    combined: Dict[Tuple[int, int], float] = {}
    for ranking in rankings:
        for sc, si, d in ranking:
            combined[(si, d)] = combined.get((si, d), 0.0) + sc
    return sorted(((sc, si, d) for (si, d), sc in combined.items()),
                  key=lambda c: (-c[0], c[1], c[2]))


class FusedPlanRunner:
    """Executes plan batches over a (text generation, knn generation)
    pair — the two planes recast as stage providers the planner
    composes. One runner per generation pair, owned by
    ``plane_route.ServingPlaneCache``; its micro-batcher co-batches
    concurrent fused requests exactly like the per-plane batchers."""

    kind = "fused"

    def __init__(self, text_gen, knn_gen=None, cache=None):
        self.text_gen = text_gen
        self.knn_gen = knn_gen
        self._cache = cache
        # the micro-batcher hangs off the runner like off a plane
        self._microbatcher = None

    # -- capability probes ---------------------------------------------------

    def _text_base(self):
        return self.text_gen.__dict__.get("base", self.text_gen)

    def _knn_base(self):
        return self.knn_gen.__dict__.get("base", self.knn_gen) \
            if self.knn_gen is not None else None

    def serves_host(self) -> bool:
        return self._text_base()._host_csr is not None

    def _bases_aligned(self) -> bool:
        """Device fused step unifies candidates by SHARD INDEX — valid
        only when both generations packed the same base segment list."""
        if self.knn_gen is None:
            return True
        tb = getattr(self.text_gen, "base_segments", None)
        kb = getattr(self.knn_gen, "base_segments", None)
        if tb is None or kb is None:
            return True           # bare planes (tests) — caller aligned
        return len(tb) == len(kb) and \
            all(a is b for a, b in zip(tb, kb))

    def can_serve(self, plan: FusedPlan) -> bool:
        if plan.knn is not None and self.knn_gen is None:
            return False
        if plan.aggs is not None and not self.serves_host():
            # agg stages pool masks from the host CSR tier; a jitted-
            # only plane keeps the legacy two-pass analytics path
            return False
        if self.serves_host():
            return True
        # jitted path: the bool/fused steps slice only the sparse tier
        base = self._text_base()
        terms = [t for _r, ts in plan.clauses for t in ts]
        if plan.rescore is not None:
            terms += list(plan.rescore.terms)
        if base.has_dense_terms(terms):
            return False
        kb = self._knn_base()
        if kb is not None:
            if base.mesh is not kb.mesh or \
                    base.n_shards != kb.n_shards:
                return False
            if not self._bases_aligned():
                return False
            # the fused scan is the exact brute-force stage; a plane
            # whose IVF tier would prune changes results vs two-dispatch
            if kb.resolve_ann(plan.knn.nprobe, plan.knn.rerank) \
                    is not None:
                return False
        return True

    # -- dispatch ------------------------------------------------------------

    def serve_view(self, items: Sequence[dict], *, view,
                   stages: Optional[dict] = None,
                   prune: Optional[bool] = None):
        """One fused dispatch over a co-batched item list (see
        ``microbatch.FusedPlaneMicroBatcher``). Each item carries the
        plan-derived per-request data (``make_item``). Returns
        (vals, hits, totals) aligned with ``items``: ``vals[i]`` the
        fused scores np.f32[k_i], ``hits[i]`` the [(shard, doc)] rows
        in VIEW space, ``totals[i]`` the lexical total (possibly
        ``(value, "gte")``). When any item carries agg stages the
        return grows a fourth element: per-item aggregations dicts
        (None on agg-free slots)."""
        t0 = time.perf_counter()
        has_aggs = any(it.get("aggs") is not None for it in items)
        if has_aggs and (view is None or not self.serves_host()):
            raise FusedFallback("agg stages need a host CSR view")
        if self.serves_host():
            out = self._serve_host(items, view=view, stages=stages,
                                   prune=prune)
        else:
            out = self._serve_device(items, view=view, stages=stages)
        if has_aggs:
            from .agg_planner import serve_agg_stages
            out = out + (serve_agg_stages(self, items, view=view,
                                          stages=stages),)
        if stages is not None:
            stages.setdefault("dispatch_ms",
                              (time.perf_counter() - t0) * 1e3)
        from ..common import telemetry as _tm
        _tm.record_planner_dispatch(max(
            (it.get("n_stages", 1) for it in items), default=1))
        return out

    # -- host path: generation stages + legacy-arithmetic fusion -------------

    def _serve_host(self, items, *, view, stages, prune):
        gen = self.text_gen
        all_bags = all(it.get("bag") is not None for it in items)
        wt = max(max((it["wt"] for it in items), default=1), 1)
        text_res: dict = {}
        knn_res: dict = {}
        t_stages: dict = {}
        k_stages: dict = {}

        def run_text():
            if all_bags:
                bags = [it["bag"] for it in items]
                text_res["out"] = gen.serve_view(
                    bags, k=wt, view=view, with_totals=True,
                    stages=t_stages, prune=prune) \
                    if hasattr(gen, "serve_view") else gen.serve(
                        bags, k=wt, with_totals=True, stages=t_stages,
                        prune=prune)
            else:
                bqs = [{"clauses": it["clauses"], "msm": it["msm"]}
                       for it in items]
                text_res["out"] = self._text_bool_view(
                    bqs, k=wt, view=view, stages=t_stages)

        def run_knn():
            if self.knn_gen is None or not any(
                    it.get("qv") is not None for it in items):
                return
            kbase = self._knn_base()
            dim = max(kbase.dim, 1)
            qvs = np.stack([
                np.asarray(it["qv"], np.float32)
                if it.get("qv") is not None
                else np.zeros(dim, np.float32) for it in items])
            wk = max(max((it["knn_nc"] for it in items), default=1), 1)
            kg = self.knn_gen
            # the SAME pow2-bucketed IVF knobs the legacy dispatch path
            # resolves (microbatch.knn_dispatch_params): co-batched
            # items share one bucket by construction, and raw values
            # here would probe fewer clusters than planner-off serving
            from .microbatch import knn_dispatch_params
            kp = knn_dispatch_params(kbase, items[0].get("nprobe"),
                                     items[0].get("rerank"))
            nprobe, rerank = kp if kp is not None else (None, None)
            if hasattr(kg, "serve_view"):
                knn_res["out"] = kg.serve_view(
                    qvs, k=wk, view=view, stages=k_stages,
                    nprobe=nprobe, rerank=rerank)
            else:
                knn_res["out"] = kg.serve(qvs, k=wk, stages=k_stages,
                                          nprobe=nprobe, rerank=rerank)

        def run_knn_guarded():
            try:
                run_knn()
            except BaseException as e:   # noqa: BLE001 — re-raised on
                knn_res["error"] = e     # the dispatcher thread below

        # the two retrieval stages run concurrently inside the ONE
        # dispatch: the kNN stage is BLAS-bound (releases the GIL), so
        # it overlaps the lexical scatter-adds — the fused path's
        # latency win on the host backend, in place of XLA overlapping
        # the two pipelines on device
        if self.knn_gen is not None and len(items) > 0 and any(
                it.get("qv") is not None for it in items):
            kt = threading.Thread(target=run_knn_guarded,
                                  name="es-dispatcher-knn-stage")
            kt.start()
            run_text()
            kt.join()
            if "error" in knn_res:
                # a failed kNN stage must fail the request like the
                # legacy knn section would — never silently degrade a
                # hybrid request to text-only results
                raise knn_res["error"]
        else:
            run_text()
        tvals, thits, ttotals = text_res["out"]
        vals_out, hits_out, totals_out = [], [], []
        for bi, it in enumerate(items):
            text_rows = [(float(v), si, d)
                         for v, (si, d) in zip(tvals[bi], thits[bi])
                         ][: it["wt"]]
            rows = text_rows
            if knn_res.get("out") is not None and \
                    it.get("qv") is not None:
                kvals, khits = knn_res["out"]
                sim = self._knn_base().similarity
                kr = [(knn_raw_to_score_host(sim, float(v))
                       * it["kboost"], si, d)
                      for v, (si, d) in zip(kvals[bi], khits[bi])]
                # monotone transform preserves plane order; re-sort for
                # boost safety (the legacy knn section's exact step)
                kr.sort(key=lambda c: (-c[0], c[1], c[2]))
                knn_rows = kr[: it["knn_k"]]
                if it["fusion"] == "rrf":
                    rows = rrf_fuse_rows([text_rows, knn_rows],
                                         it["rc"])
                else:
                    rows = sum_fuse_rows([text_rows, knn_rows])
            if it.get("rescore") is not None:
                rows = self._rescore_rows_host(it["rescore"], rows,
                                               view)
            rows = rows[: it["k"]]
            # float64 on purpose: the legacy host fusion/rescore work in
            # python floats, and fused-vs-two-dispatch parity is BITWISE
            vals_out.append(np.asarray([r[0] for r in rows]))
            hits_out.append([(r[1], r[2]) for r in rows])
            totals_out.append(ttotals[bi])
        if stages is not None:
            for src in (t_stages, k_stages):
                for key, ms in src.items():
                    if key.endswith("_ms"):
                        stages[key] = stages.get(key, 0.0) + ms
            stages["compile_cache"] = "host"
            if "docs_scanned" in t_stages:
                stages["docs_scanned"] = t_stages["docs_scanned"]
            # roofline audit: the fused dispatch's model bytes are the
            # sum of its component stages' stamped models (the text
            # side may be pruned — the coarse fused fallback would
            # overcharge it a full eager scan)
            mb = int(t_stages.get("model_bytes") or 0) + \
                int(k_stages.get("model_bytes") or 0)
            if mb:
                stages["model_bytes"] = mb
        return vals_out, hits_out, totals_out

    def _text_bool_view(self, bqs, *, k, view, stages):
        """Bool-tree lexical stage through the text generation: base
        bool dispatch with the delta's df/doc mass folded into idf +
        delta bool scan + host top-k merge (the bool twin of
        ``TextServingGeneration._serve_merged``)."""
        gen = self.text_gen
        base = self._text_base()
        if not hasattr(gen, "_delta_for_view"):
            vals, hits, totals = base.serve_bool(
                bqs, k=k, with_totals=True, stages=stages)
            return vals, hits, totals
        delta, base_pos = gen._delta_for_view(view)
        if delta is None:
            vals, hits, totals = base.serve_bool(
                bqs, k=k, with_totals=True, stages=stages)
            rows = [[(base_pos[si], d) for (si, d) in h] for h in hits]
            return vals, rows, totals
        extra_df: Dict[str, int] = {}
        for bq in bqs:
            for _role, terms in bq["clauses"]:
                for t in set(terms):
                    if t not in extra_df:
                        extra_df[t] = delta.df(t)
        vals, hits, totals = base.serve_bool(
            bqs, k=k, with_totals=True, stages=stages,
            extra_docs=delta.n_docs, extra_df=extra_df)
        from ..ops.bm25 import idf_weight
        from ..parallel.dist_search import (merge_topk_rows,
                                            total_is_lower_bound,
                                            total_value)
        n_total = base.n_docs_total + delta.n_docs
        idf_cache: Dict[str, float] = {}

        def idf_of(t: str) -> float:
            v = idf_cache.get(t)
            if v is None:
                gdf = base.global_df(t) + extra_df.get(t, 0)
                v = float(idf_weight(n_total, np.int64(gdf))) if gdf \
                    else 0.0
                idf_cache[t] = v
            return v

        drows, dtotals = delta.score_bool(bqs, k, idf_of,
                                          with_totals=True)
        vals_out, hits_out, totals_out = [], [], []
        for bi in range(len(bqs)):
            base_rows = [(float(v), base_pos[si], int(d))
                         for v, (si, d) in zip(vals[bi], hits[bi])]
            merged = merge_topk_rows(base_rows, drows[bi], k)
            vals_out.append(np.asarray([r[0] for r in merged],
                                       np.float32))
            hits_out.append([(r[1], r[2]) for r in merged])
            tv = total_value(totals[bi]) + int(dtotals[bi])
            totals_out.append((tv, "gte")
                              if total_is_lower_bound(totals[bi])
                              else tv)
        if self._cache is not None:
            self._cache._record_delta_serve("text", len(bqs))
        return vals_out, hits_out, totals_out

    def _rescore_rows_host(self, rs: dict, rows, view):
        """Fused rescore stage (host): exact secondary scores from the
        base plane's CSR (and the delta segments' CSR for delta docs)
        under the combined base+delta stats, then the QueryRescorer
        window combine/reorder."""
        base = self._text_base()
        gen = self.text_gen
        delta, base_pos = gen._delta_for_view(view) \
            if hasattr(gen, "_delta_for_view") \
            else (None, list(range(base.n_shards)))
        pos2base = {vp: bi for bi, vp in enumerate(base_pos)}
        pos2delta = {}
        if delta is not None:
            for di, vp in enumerate(delta.seg_positions):
                pos2delta[vp] = di
        terms = rs["terms"]
        weights: Dict[str, float] = {}
        for t in terms:
            weights[t] = weights.get(t, 0.0) + 1.0
        from ..ops.bm25 import idf_weight
        extra_docs = delta.n_docs if delta is not None else 0
        idfw_of: Dict[str, float] = {}
        for t, w in weights.items():
            gdf = base.global_df(t) + (delta.df(t) if delta is not None
                                       else 0)
            if gdf:
                idfw_of[t] = float(idf_weight(
                    base.n_docs_total + extra_docs, np.int64(gdf))) * w
        slot_terms = list(idfw_of)

        def secondary(si: int, d: int):
            # accumulate in REVERSED slot order — the device kernel's
            # highest-slot-first f32 summation (bisect_exact_scores)
            if si in pos2base:
                csr = base._host_csr[pos2base[si]]
                sh = base.shards[pos2base[si]]
                tids = sh["term_ids"]
            else:
                csr = delta._csr[pos2delta[si]]
                tids = csr["term_ids"]
            s = np.float32(0.0)
            fnd = False
            for t in reversed(slot_terms):
                tid = tids.get(t)
                if tid is None:
                    continue
                st = int(csr["offsets"][tid])
                en = int(csr["offsets"][tid + 1])
                if en <= st:
                    continue
                run = csr["docs"][st:en]
                p = int(np.searchsorted(run, d))
                if p < en - st and run[p] == d:
                    s = np.float32(s + np.float32(
                        idfw_of[t] * csr["impacts"][st + p]))
                    fnd = True
            return float(s), fnd

        qw, rw, mode = rs["qw"], rs["rw"], rs["mode"]
        window = min(rs["window"], len(rows))
        rescored = []
        for sc, si, d in rows[:window]:
            rsec, fnd = secondary(si, d)
            if fnd:
                if mode == "total":
                    ns = qw * sc + rw * rsec
                elif mode == "multiply":
                    ns = (qw * sc) * (rw * rsec)
                elif mode == "avg":
                    ns = (qw * sc + rw * rsec) / 2.0
                elif mode == "max":
                    ns = max(qw * sc, rw * rsec)
                else:                          # "min"
                    ns = min(qw * sc, rw * rsec)
            else:
                ns = qw * sc
            rescored.append((ns, si, d))
        rescored.sort(key=lambda c: (-c[0], c[1], c[2]))
        tail = [(qw * sc, si, d) for sc, si, d in rows[window:]]
        return rescored + tail

    # -- device path: ONE jitted program over both planes --------------------

    def _serve_device(self, items, *, view, stages):
        from ..parallel.dist_search import fused_search_device
        gen = self.text_gen
        base = self._text_base()
        kbase = self._knn_base()
        tdelta, tbase_pos = gen._delta_for_view(view) \
            if hasattr(gen, "_delta_for_view") \
            else (None, list(range(base.n_shards)))
        if self.knn_gen is not None and \
                hasattr(self.knn_gen, "_delta_for_view"):
            kdelta, _kpos = self.knn_gen._delta_for_view(view)
        else:
            kdelta = None
        has_delta = (tdelta is not None) or (kdelta is not None)
        if has_delta and any(it.get("rescore") is not None
                             for it in items):
            # base-doc secondaries live in-kernel but delta docs would
            # need a host CSR the device backend does not retain
            raise FusedFallback("delta tier + rescore on device")
        if kbase is None:
            return self._serve_device_lexical(items, base, tdelta,
                                              tbase_pos, stages)
        extra_df: Dict[str, int] = {}
        if tdelta is not None:
            for it in items:
                for _role, terms in it["clauses"]:
                    for t in set(terms):
                        if t not in extra_df:
                            extra_df[t] = tdelta.df(t)
        fusion = next(it["fusion"] for it in items
                      if it["fusion"] is not None)
        rescore_mode = next(
            (it["rescore"]["mode"] for it in items
             if it.get("rescore") is not None), None)
        pad_rs = {"terms": [], "qw": 1.0, "rw": 1.0, "window": 0}
        dim = max(kbase.dim, 1)
        fqs = []
        for it in items:
            fqs.append({
                "clauses": it["clauses"], "msm": it["msm"],
                "qv": (it["qv"] if it.get("qv") is not None
                       else np.zeros(dim, np.float32)),
                "kboost": it["kboost"],
                "rc": float(it["rc"]), "wt": it["wt"],
                "wk": it["knn_k"], "k": it["k"],
                "rescore": (it.get("rescore") or pad_rs)
                if rescore_mode is not None else None})
        try:
            rows, totals, text_rows, knn_rows = fused_search_device(
                base, kbase, fqs, fusion=fusion,
                rescore_mode=rescore_mode, stages=stages,
                extra_docs=tdelta.n_docs if tdelta is not None else 0,
                extra_df=extra_df or None)
        except ValueError as e:
            raise FusedFallback(str(e))
        if not has_delta:
            vals_out = [np.asarray([r[0] for r in rows[bi]], np.float32)
                        for bi in range(len(items))]
            hits_out = [[(tbase_pos[r[1]], r[2]) for r in rows[bi]]
                        for bi in range(len(items))]
            return vals_out, hits_out, totals
        # a live delta tier: the one dispatch still produced both raw
        # rankings — merge the delta scans on the host and re-run the
        # (tiny) fusion over the merged lists
        return self._merge_delta_and_fuse(items, text_rows, knn_rows,
                                          totals, tdelta, kdelta,
                                          tbase_pos, extra_df)

    def _serve_device_lexical(self, items, base, tdelta, tbase_pos,
                              stages):
        bqs = [{"clauses": it["clauses"], "msm": it["msm"]}
               for it in items]
        wt = max(max((it["wt"] for it in items), default=1), 1)
        if any(it.get("rescore") is not None for it in items):
            # lexical + rescore fused program (bool step's Q2 stage)
            rs0 = items[0]["rescore"]
            try:
                vals, hits, totals = self._bool_rescore_device(
                    base, bqs, items, wt, rs0["mode"], stages)
            except ValueError as e:
                raise FusedFallback(str(e))
        else:
            try:
                vals, hits, totals = base.serve_bool(
                    bqs, k=wt, with_totals=True, stages=stages)
            except ValueError as e:
                raise FusedFallback(str(e))
        if tdelta is None:
            out_v, out_h, out_t = [], [], []
            for bi, it in enumerate(items):
                out_v.append(np.asarray(vals[bi][: it["k"]],
                                        np.float32))
                out_h.append([(tbase_pos[si], d)
                              for (si, d) in hits[bi][: it["k"]]])
                out_t.append(totals[bi])
            return out_v, out_h, out_t
        raise FusedFallback("delta tier on the device lexical path")

    def _bool_rescore_device(self, base, bqs, items, wt, mode, stages):
        from ..parallel.dist_search import (NEG_INF, _run_step,
                                            build_bool_bm25_step)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.mesh import AXIS_REPLICA, AXIS_SHARD
        from ..utils.shapes import round_up_pow2
        mesh = base.mesh
        B = len(bqs)
        n_repl = mesh.shape[AXIS_REPLICA]
        B_pad = -(-B // n_repl) * n_repl
        bqs = list(bqs) + [{"clauses": [], "msm": 0}] * (B_pad - B)
        pad_rs = {"terms": [], "qw": 1.0, "rw": 1.0, "window": 0}
        rss = [it.get("rescore") or pad_rs for it in items] \
            + [pad_rs] * (B_pad - B)
        Q = max(base.SERVING_Q_MIN,
                round_up_pow2(base.bool_slot_count(bqs)))
        (starts, lengths, idfw, cbits, req, neg, shd, msm, max_len,
         any_dense) = base.bool_inputs(bqs, Q)
        if any_dense:
            raise ValueError("bool batch touches dense-tier terms")
        L = min(base.ladder_L(max_len), base.L_cap)
        np.minimum(lengths, L, out=lengths)
        bags2 = [list(rs["terms"]) for rs in rss]
        Q2 = max(8, round_up_pow2(max(
            max((len(set(b)) for b in bags2), default=1), 1)))
        (st2, ln2, iw2, _dr, _dh, _ml2, dense2) = base._lookup(bags2, Q2)
        if dense2:
            raise ValueError("rescore touches dense-tier terms")
        qw = np.asarray([rs["qw"] for rs in rss], np.float32)
        rw = np.asarray([rs["rw"] for rs in rss], np.float32)
        rwin = np.asarray([rs["window"] for rs in rss], np.int32)
        from ..ops.fused_query import MAX_BOOL_CLAUSES as NC
        step = base.cached_step(
            ("bool", Q, L, wt, True, NC, Q2, mode),
            lambda: build_bool_bm25_step(
                mesh, n_pad=base.n_pad, Q=Q, L=L, k=wt, nc=NC,
                n_shards=base.n_shards, with_count=True, Q2=Q2,
                rescore_mode=mode),
            "text_plane_bool")
        repl = NamedSharding(mesh, P(AXIS_REPLICA, None))
        repl1 = NamedSharding(mesh, P(AXIS_REPLICA))
        repl3 = NamedSharding(mesh, P(AXIS_REPLICA, AXIS_SHARD, None))
        out = _run_step(
            base._serial_dispatch, step, base.docs_dev,
            base.impacts_dev,
            jax.device_put(starts, repl3), jax.device_put(lengths, repl3),
            jax.device_put(idfw, repl), jax.device_put(cbits, repl),
            jax.device_put(req, repl1), jax.device_put(neg, repl1),
            jax.device_put(shd, repl1), jax.device_put(msm, repl1),
            jax.device_put(st2, repl3), jax.device_put(ln2, repl3),
            jax.device_put(iw2, repl), jax.device_put(qw, repl1),
            jax.device_put(rw, repl1), jax.device_put(rwin, repl1))
        if stages is not None:
            jax.block_until_ready(out)
        base.n_dispatches += 1
        from ..common import telemetry as _tm
        _tm.record_mesh_dispatch(mesh.shape[AXIS_SHARD],
                                 mesh.shape[AXIS_REPLICA])
        if stages is not None:
            stages["compile_cache"] = \
                "miss" if _tm.last_call_compiled() else "hit"
        vals = np.asarray(out[0])[:B]
        gdocs = np.asarray(out[1])[:B]
        counts = np.asarray(out[2])[:B]
        pad_id = base.n_shards * base.n_pad
        hits = []
        for bi in range(B):
            row = []
            for v, g in zip(vals[bi], gdocs[bi]):
                if v == NEG_INF or g >= pad_id:
                    break
                row.append((int(g) // base.n_pad,
                            int(g) % base.n_pad))
            hits.append(row)
        return vals, hits, [int(c) for c in counts]

    def _merge_delta_and_fuse(self, items, text_rows, knn_rows, totals,
                              tdelta, kdelta, base_pos, extra_df):
        from ..ops.bm25 import idf_weight
        from ..parallel.dist_search import merge_topk_rows
        base = self._text_base()
        kbase = self._knn_base()
        vals_out, hits_out, totals_out = [], [], []
        idf_cache: Dict[str, float] = {}
        n_total = base.n_docs_total + (tdelta.n_docs
                                       if tdelta is not None else 0)

        def idf_of(t: str) -> float:
            v = idf_cache.get(t)
            if v is None:
                gdf = base.global_df(t) + extra_df.get(t, 0)
                v = float(idf_weight(n_total, np.int64(gdf))) if gdf \
                    else 0.0
                idf_cache[t] = v
            return v

        bqs = [{"clauses": it["clauses"], "msm": it["msm"]}
               for it in items]
        drows, dtotals = tdelta.score_bool(
            bqs, max(it["wt"] for it in items), idf_of,
            with_totals=True) if tdelta is not None \
            else ([[] for _ in items], [0] * len(items))
        if kdelta is not None:
            dim = max(kbase.dim, 1)
            qvs = np.stack([np.asarray(it["qv"], np.float32)
                            if it.get("qv") is not None
                            else np.zeros(dim, np.float32)
                            for it in items])
            kd_rows = kdelta.score(qvs, max(it["knn_nc"]
                                            for it in items))
        else:
            kd_rows = [[] for _ in items]
        sim = kbase.similarity
        for bi, it in enumerate(items):
            t_base = [(v, base_pos[si], d)
                      for (v, si, d) in text_rows[bi]]
            t_merged = merge_topk_rows(t_base, drows[bi],
                                       it["wt"])
            k_base = [(v, base_pos[si], d)
                      for (v, si, d) in knn_rows[bi]]
            k_merged = merge_topk_rows(k_base, kd_rows[bi],
                                       it["knn_nc"])
            kr = [(knn_raw_to_score_host(sim, float(v))
                   * it["kboost"], si, d) for v, si, d in k_merged]
            kr.sort(key=lambda c: (-c[0], c[1], c[2]))
            knn_ranked = kr[: it["knn_k"]]
            if it["fusion"] == "rrf":
                rows = rrf_fuse_rows([t_merged, knn_ranked], it["rc"])
            else:
                rows = sum_fuse_rows([t_merged, knn_ranked])
            rows = rows[: it["k"]]
            vals_out.append(np.asarray([r[0] for r in rows]))
            hits_out.append([(r[1], r[2]) for r in rows])
            tv = totals[bi] + int(dtotals[bi])
            totals_out.append(tv)
        if self._cache is not None:
            self._cache._record_delta_serve("text", len(items))
        return vals_out, hits_out, totals_out


def make_item(plan: FusedPlan, *, prune_param=None) -> dict:
    """Plan → the per-request dispatch item the runner consumes (plain
    data, hashable key for in-flight dedup)."""
    rescore = None
    if plan.rescore is not None:
        rescore = {"terms": list(plan.rescore.terms),
                   "qw": plan.rescore.qw, "rw": plan.rescore.rw,
                   "mode": plan.rescore.mode,
                   "window": plan.rescore.window}
    item = {
        "bag": list(plan.bag) if plan.bag is not None else None,
        "clauses": [(r, list(ts)) for r, ts in plan.clauses],
        "msm": plan.msm,
        "qv": plan.knn.query_vector if plan.knn is not None else None,
        "kboost": plan.knn.boost if plan.knn is not None else 1.0,
        "knn_k": plan.knn.k if plan.knn is not None else 0,
        "knn_nc": plan.knn.num_candidates if plan.knn is not None
        else 0,
        "nprobe": plan.knn.nprobe if plan.knn is not None else None,
        "rerank": plan.knn.rerank if plan.knn is not None else None,
        "fusion": plan.fusion,
        "rc": plan.rank_constant,
        "wt": plan.window_text,
        "k": plan.k,
        "rescore": rescore,
        "aggs": plan.aggs,
        "n_stages": plan.n_stages(),
    }
    item["key"] = (
        tuple((r, tuple(ts)) for r, ts in plan.clauses), plan.msm,
        plan.knn.query_vector.tobytes() if plan.knn is not None
        else None,
        item["knn_k"], item["knn_nc"], item["kboost"], item["nprobe"],
        item["rerank"], plan.fusion, plan.rank_constant,
        plan.window_text, plan.k,
        (tuple(rescore["terms"]), rescore["qw"], rescore["rw"],
         rescore["mode"], rescore["window"]) if rescore else None,
        plan.aggs.spec_key if plan.aggs is not None else None,
        prune_param)
    return item
