"""Shard-level search execution: query phase + knn + sort + fetch.

Re-design of the reference's shard search entry
(``search/SearchService.java:378 executeQueryPhase`` →
``search/query/QueryPhase.java:132`` → per-segment collectors). Here the
"collector" is data-parallel: every segment is scored eagerly to dense
(scores, mask) arrays by the query tree (``query_dsl.py``), top-k hits are
selected on device per segment (``ops/topk.py``), and the tiny per-segment
candidate lists are merged on the host (score desc, then segment/doc id asc —
Lucene's tie-break order). Field sorting builds normalized sort-key columns
and lexsorts matched docs; ``knn`` runs the brute-force einsum per segment
and merges with the query's candidates (hybrid score sum, or reciprocal
rank fusion under ``rank.rrf``)."""

from __future__ import annotations

import json
import time as _time
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..common.errors import IllegalArgumentError, ParsingError
from ..index.mapping import (DateFieldType, DenseVectorFieldType,
                             KeywordFieldType, MapperService, NumberFieldType,
                             RuntimeFieldType)
from ..index.segment import Segment
from ..ops.topk import get_topk_kernel
from ..utils.shapes import round_up_pow2
from .aggregations import (AggregationContext, BucketAggregator, TopHitsAgg,
                           parse_aggs, run_aggregations)
from .fetch import docvalue_fields, filter_source, highlight
from .query_dsl import (MatchAllQuery, ShardContext, _vector_similarity,
                        parse_query)

_MISSING_LAST = float("inf")


def _attribute_dispatch(stages: Optional[dict],
                        info: Optional[dict]) -> None:
    """Charge one micro-batch dispatch to the request's task ledger
    (``node/task_manager.TaskResources``, contextvars-bound at the REST
    edge): host CPU since the last boundary, the dispatch's device
    wall-ms, its transfer-byte share and the docs it scanned (base
    corpus + delta tier). O(1) per dispatch; no-op outside any task."""
    from ..node.task_manager import current_resources
    res = current_resources()
    if res is None:
        return
    res.cpu_checkpoint()
    stages = stages or {}
    info = info or {}
    res.add(device_ms=float(stages.get("dispatch", 0.0)),
            h2d_bytes=int(info.get("h2d_bytes", 0)),
            d2h_bytes=int(info.get("d2h_bytes", 0)),
            docs_scanned=int(info.get("docs_scanned", 0)),
            delta_docs_scanned=int(info.get("delta_docs", 0)),
            dispatches=1)


def _attribute_segment_scan(segments) -> None:
    """Per-segment (non-plane) query phase: the docs the eager scorers
    covered, plus a CPU boundary checkpoint."""
    from ..node.task_manager import current_resources
    res = current_resources()
    if res is None:
        return
    res.cpu_checkpoint()
    res.add(docs_scanned=sum(s.n_docs for s in segments))


def _collect_nested_inner_specs(spec, out: list,
                                join_out: Optional[list] = None) -> None:
    """Walk a raw query spec for nested / has_child / has_parent clauses
    carrying ``inner_hits`` (reference:
    ``InnerHitContextBuilder.extractInnerHits``)."""
    if isinstance(spec, dict):
        n = spec.get("nested")
        if isinstance(n, dict) and "inner_hits" in n:
            out.append(n)
        if join_out is not None:
            for kind in ("has_child", "has_parent"):
                j = spec.get(kind)
                if isinstance(j, dict) and "inner_hits" in j:
                    join_out.append((kind, j))
        for v in spec.values():
            _collect_nested_inner_specs(v, out, join_out)
    elif isinstance(spec, list):
        for v in spec:
            _collect_nested_inner_specs(v, out, join_out)


def _tree_needs_scores(aggs: dict) -> bool:
    for a in aggs.values():
        if isinstance(a, TopHitsAgg):
            return True
        if isinstance(a, BucketAggregator) and _tree_needs_scores(a.subs):
            return True
    return False


@dataclass
class ShardHit:
    doc_id: str
    score: Optional[float]
    seg_idx: int
    local_doc: int
    source: Optional[dict]
    sort_values: Optional[List[Any]] = None
    seq_no: Optional[int] = None
    fields: Optional[Dict[str, List[Any]]] = None
    highlight: Optional[Dict[str, List[str]]] = None
    ignored: Optional[List[str]] = None
    inner_hits: Optional[Dict[str, dict]] = None


@dataclass
class ShardSearchResult:
    total: int
    total_relation: str
    hits: List[ShardHit]
    max_score: Optional[float]
    aggregations: Optional[Dict[str, Any]] = None
    profile: Optional[dict] = None
    suggest: Optional[Dict[str, list]] = None
    #: (segment, host mask, host scores | None) per segment — returned
    #: instead of reduced aggregations when the caller (the distributed
    #: coordinator) wants ONE global reduce across shards
    agg_inputs: Optional[List[Tuple[Segment, np.ndarray,
                                    Optional[np.ndarray]]]] = None
    #: per-shard partial failures (aggs that errored on one shard — the
    #: reference's ShardSearchFailure list; hits of failed shards are
    #: excluded, the rest of the response stands)
    shard_failures: Optional[List[dict]] = None
    #: per-stage serving-pipeline ms for plane-served queries (queue wait /
    #: host prep / device dispatch / fetch — microbatch.STAGES); None when
    #: the per-segment path served. Slow-log entries carry this so a slow
    #: query is attributable to a stage.
    serving_stages: Optional[Dict[str, float]] = None
    #: one-dispatch planner verdict for this request ({outcome,
    #: lower_ms, stages_per_dispatch}); None when the planner was never
    #: consulted. Slow-log entries carry this so a slow fused query
    #: names its route without re-running with profile:true.
    planner: Optional[dict] = None


def _knn_score_transform(similarity: str, sim):
    """Raw similarity → ES _score (reference: DenseVectorFieldMapper docs /
    KnnVectorQuery score translation)."""
    if similarity in ("cosine", "cos"):
        return (1.0 + sim) / 2.0
    if similarity == "dot_product":
        return (1.0 + sim) / 2.0
    if similarity == "max_inner_product":
        return jnp.where(sim < 0, 1.0 / (1.0 - sim), sim + 1.0)
    # l2_norm: sim here is the distance
    return 1.0 / (1.0 + sim * sim)


class ShardSearcher:
    """Executes one search request against one shard's segment list.

    ``plane_provider``: optional ``field -> DistributedSearchPlane | None``
    hook (``plane_route.ServingPlaneCache``). When set, eligible bag-of-
    terms queries execute through the tiered TPU plane — the production
    scorer — instead of the per-segment eager path; everything else
    (fetch, error shapes, pagination) is shared."""

    def __init__(self, segments: List[Segment], mapper: MapperService,
                 plane_provider=None, knn_plane_provider=None,
                 fused_provider=None):
        self.segments = [s for s in segments if s.n_docs > 0]
        self.mapper = mapper
        self.ctx = ShardContext(self.segments, mapper)
        self.plane_provider = plane_provider
        #: optional ``(segments, field) -> DistributedKnnPlane | None``
        #: hook: eligible knn clauses run through the blocked device plane
        #: (pack-time invariants + streaming top-k) with query_vector
        #: micro-batching across concurrent requests
        self.knn_plane_provider = knn_plane_provider
        #: optional ``(segments, text_field, knn_field|None) ->
        #: FusedPlanRunner | None`` hook
        #: (``plane_route.ServingPlaneCache.fused_runner_for``): bodies
        #: the query planner can lower (bool tree + knn + rescore) run
        #: as ONE fused dispatch over both serving generations instead
        #: of two dispatches + host fusion
        self.fused_provider = fused_provider

    # ------------------------------------------------------------------
    # knn
    # ------------------------------------------------------------------

    @staticmethod
    def _knn_score_from_raw(similarity: str, raw: float) -> float:
        """Plane raw similarity → ES _score (host-side scalar form of
        :func:`_knn_score_transform`; the plane's l2 raw is ``-‖q-v‖²``,
        clamped at 0 for float cancellation)."""
        if similarity in ("cosine", "cos", "dot_product"):
            return (1.0 + raw) / 2.0
        if similarity == "max_inner_product":
            return 1.0 / (1.0 - raw) if raw < 0 else raw + 1.0
        return 1.0 / (1.0 + max(0.0, -raw))        # l2_norm

    def _knn_candidates(self, spec: dict) -> List[Tuple[float, int, int]]:
        """Brute-force kNN for one knn clause: einsum per segment + top-k
        (reference: the 8.x ``_knn_search``/``knn`` section; scoring per
        ``x-pack/plugin/vectors`` brute force, but one matmul per segment
        instead of a per-doc script loop)."""
        field = spec.get("field")
        qv = spec.get("query_vector")
        if field is None or qv is None:
            raise ParsingError("knn requires [field] and [query_vector]")
        k = int(spec.get("k", 10))
        num_candidates = int(spec.get("num_candidates", max(k, 10)))
        boost = float(spec.get("boost", 1.0))
        # ANN accuracy knobs (num_candidates-style): nprobe = IVF
        # clusters visited per query (0 forces the exact scan), rerank =
        # exact-re-scoring window factor. Inert on the per-segment path
        # and on planes below the IVF corpus threshold (brute force).
        nprobe = spec.get("nprobe")
        if nprobe is not None:
            nprobe = int(nprobe)
            if nprobe < 0:
                raise IllegalArgumentError(
                    f"[knn] [nprobe] must be non-negative, got [{nprobe}]")
        rerank = spec.get("rerank")
        if rerank is not None:
            rerank = int(rerank)
            if rerank < 1:
                raise IllegalArgumentError(
                    f"[knn] [rerank] must be positive, got [{rerank}]")
        ft = self.mapper.field_type(field)
        if not isinstance(ft, DenseVectorFieldType):
            raise IllegalArgumentError(
                f"[knn] field [{field}] is not a dense_vector field")
        sim_kind = {"cosine": "cosineSimilarity", "dot_product": "dotProduct",
                    "l2_norm": "l2norm",
                    "max_inner_product": "dotProduct"}[ft.similarity] \
            if ft.similarity in ("cosine", "dot_product", "l2_norm",
                                 "max_inner_product") else "cosineSimilarity"
        filt = spec.get("filter")
        filter_q = parse_query(filt) if filt else None
        qv = np.asarray(qv, np.float32)

        # --- knn plane route (the production vector kernel) ---------------
        # Filter-free clauses over clean segments (no deletes / nested)
        # run through the DistributedKnnPlane: corpus invariants packed
        # once, blocked streaming top-k, and concurrent requests coalesce
        # their query_vector batches into one dispatch (microbatch.py).
        if (self.knn_plane_provider is not None and filter_q is None
                and num_candidates >= k):
            plane = self.knn_plane_provider(self.segments, field)
            if plane is not None:
                from .microbatch import batched_knn_search
                knn_stages: Dict[str, float] = {}
                knn_info: Dict[str, object] = {}
                raw, phits = batched_knn_search(plane, qv,
                                                k=num_candidates,
                                                view=self.segments,
                                                stages=knn_stages,
                                                info=knn_info,
                                                nprobe=nprobe,
                                                rerank=rerank)
                _attribute_dispatch(knn_stages, knn_info)
                cands = [
                    (self._knn_score_from_raw(ft.similarity, float(v))
                     * boost, si, d)
                    for v, (si, d) in zip(raw, phits)]
                # monotone transforms preserve the plane's (score desc,
                # shard asc, doc asc) order; re-sort for boost safety
                cands.sort(key=lambda c: (-c[0], c[1], c[2]))
                return cands[:k]

        pending = []
        for seg_idx, seg in enumerate(self.segments):
            sim, exists = _vector_similarity(sim_kind, qv, seg, field)
            scores = _knn_score_transform(ft.similarity, sim)
            mask = exists & seg.live_dev
            if seg.has_nested:
                mask = mask & seg.parent_mask_dev
            if filter_q is not None:
                _, fm = filter_q.execute(self.ctx, seg)
                mask = mask & fm
            kk = min(num_candidates, seg.n_pad)
            topk = get_topk_kernel(seg.n_pad, kk)
            vals_dev, idx_dev = topk(jnp.asarray(scores, jnp.float32), mask)
            pending.append((seg_idx, vals_dev, idx_dev))
        cands: List[Tuple[float, int, int]] = []
        for seg_idx, vals_dev, idx_dev in pending:
            vals = np.asarray(vals_dev)
            idx = np.asarray(idx_dev)
            ok = vals > float("-inf")
            for v, d in zip(vals[ok], idx[ok]):
                cands.append((float(v) * boost, seg_idx, int(d)))
        cands.sort(key=lambda c: (-c[0], c[1], c[2]))
        return cands[:k]

    # ------------------------------------------------------------------
    # sort keys
    # ------------------------------------------------------------------

    def _normalize_sort(self, sort_spec) -> List[dict]:
        return normalize_sort(sort_spec)

    def _sort_raw_for(self, clause: dict, seg_idx: int, seg: Segment,
                      docs: np.ndarray, scores: Optional[np.ndarray]):
        """Raw (un-normalized) sort values for matched docs of one segment:
        float64 array for numeric/_score/_doc, object array (str | None)
        for keyword fields."""
        field = clause["field"]
        if field not in ("_score", "_doc", "_shard_doc"):
            self.mapper.fielddata_loaded.add(field)
        if field == "_score":
            sc = scores[docs] if scores is not None else np.zeros(len(docs))
            return sc.astype(np.float64)
        if field == "_doc":
            return ((np.int64(seg_idx) << 32) +
                    docs.astype(np.int64)).astype(np.float64)
        ft = self.mapper.field_type(field)
        if isinstance(ft, RuntimeFieldType):
            return ft.column(seg)[docs]
        if isinstance(ft, DateFieldType) and ft.nanos:
            if clause.get("numeric_type") == "date":
                # unified ms domain requested: the float column suffices
                return seg.numeric_first_value_column(field)[docs]
            i64 = getattr(seg, "int64_fields", {}).get(
                ft.name if ft.name else field)
            vals = np.full(len(docs), None, dtype=object)
            if i64 is not None:
                idocs, ivals = i64
                first: Dict[int, int] = {}
                for d_, v_ in zip(idocs.tolist()[::-1],
                                  ivals.tolist()[::-1]):
                    first[d_] = v_
                for i, d_ in enumerate(docs):
                    vals[i] = first.get(int(d_))
            # exact ns longs as an object column: float64 loses the
            # bottom bits of ns-resolution epochs
            return vals
        nf = seg.numeric_fields.get(field)
        if nf is not None or isinstance(ft, (NumberFieldType, DateFieldType)):
            return seg.numeric_first_value_column(field)[docs]
        kf = seg.keyword_fields.get(field)
        vals = np.full(len(docs), None, dtype=object)
        if kf is not None:
            first_term: Dict[int, str] = {}
            for d, o in zip(kf.dv_docs_host[::-1], kf.dv_ords_host[::-1]):
                first_term[int(d)] = kf.ord_terms[int(o)]
            for i, d in enumerate(docs):
                vals[i] = first_term.get(int(d))
        return vals

    @staticmethod
    def _normalize_keys(clause: dict, raw: np.ndarray) -> np.ndarray:
        """Global ascending-normalized float64 key column. String values
        factorize over the *whole* candidate set (even codes, so a
        search_after cursor of an absent string can land between codes)."""
        desc = clause["order"] == "desc"
        missing_last = clause["missing"] != "_first"
        fill = _MISSING_LAST if (missing_last != desc) else -_MISSING_LAST
        if raw.dtype == object:
            uniq = sorted({v for v in raw if v is not None})
            code_of = {v: i * 2 for i, v in enumerate(uniq)}
            keys = np.asarray([code_of[v] if v is not None else fill
                               for v in raw], np.float64)
        else:
            keys = np.where(np.isnan(raw), fill, raw)
        return -keys if desc else keys

    # ------------------------------------------------------------------
    # main entry
    # ------------------------------------------------------------------

    def search(self, body: Optional[dict] = None, *, size: int = 10,
               from_: int = 0, min_score: Optional[float] = None,
               track_total_hits=True,
               collect_agg_inputs: bool = False,
               knn_override: Optional[List[List[Tuple[float, int, int]]]]
               = None) -> ShardSearchResult:
        body = body or {}
        size = int(body.get("size", size))
        from_ = int(body.get("from", from_))
        min_score = body.get("min_score", min_score)
        track_total_hits = body.get("track_total_hits", track_total_hits)
        query_spec = body.get("query")
        knn_spec = body.get("knn")
        # block-max pruning knob (rank-safe WAND-as-a-scan on the plane
        # route): absent → pruned only when totals are already
        # approximate (Lucene disables WAND under exact total tracking);
        # true → force pruned (totals become "gte" lower bounds under an
        # early exit); false → force the eager scan
        prune_opt = body.get("prune")
        if prune_opt is not None and not isinstance(prune_opt, bool):
            raise IllegalArgumentError(
                f"[prune] must be a boolean, got [{prune_opt}]")
        query = parse_query(query_spec) if query_spec else MatchAllQuery()
        aggs_spec = body.get("aggs") or body.get("aggregations")
        aggs = parse_aggs(aggs_spec) if aggs_spec else None
        sort_spec = body.get("sort")
        search_after = body.get("search_after")
        rank_spec = body.get("rank")
        rescore_spec = body.get("rescore")
        collapse_spec = body.get("collapse")
        profile_on = bool(body.get("profile"))
        suggest_spec = body.get("suggest")
        t_query0 = _time.perf_counter() if profile_on else 0.0

        use_field_sort = bool(sort_spec) and self._normalize_sort(
            sort_spec)[0]["field"] != "_score"

        k = size + from_
        # window widened for search_after-less deep pagination handled by
        # caller; knn/rrf need their own candidate windows
        window = k
        if rank_spec and "rrf" in rank_spec:
            window = max(window, int(rank_spec["rrf"].get(
                "rank_window_size", max(k, 10))))
        if rescore_spec:
            if use_field_sort:
                raise IllegalArgumentError(
                    "Cannot use [sort] option in conjunction with "
                    "[rescore].")
            for rs in (rescore_spec if isinstance(rescore_spec, list)
                       else [rescore_spec]):
                mode = (rs.get("query") or {}).get("score_mode", "total")
                if mode not in ("total", "multiply", "avg", "max", "min"):
                    # parse-time validation, not data-dependent
                    raise IllegalArgumentError(
                        f"[rescore] illegal score_mode [{mode}]")
                window = max(window, int(rs.get("window_size", 10)))
        if collapse_spec:
            # exact collapse needs the full ranking: every group's best hit
            # must be visible (the reference's grouping collector sees all
            # matches; here the per-segment top-k window opens fully)
            window = 1 << 30

        # --- plane route (the production TPU kernel) ----------------------
        # Eligible bag-of-terms queries run through the tiered distributed
        # plane: one dispatch returns top-k AND exact totals. The provider
        # hands back a serving GENERATION (packed base + append-only delta
        # tier merged per dispatch — plane_route.py), or None both when
        # the route is ineligible and while a structural change (merge/
        # delete) has the base mid-repack on the background thread — the
        # per-segment path below serves the gap. Features that need per-doc
        # masks (aggs, field sort) or reordering (rescore, collapse,
        # search_after cursors) stay on the per-segment path.
        plane_route = None
        if (self.plane_provider is not None and query_spec
                and knn_override is None and window > 0
                and min_score is None and search_after is None):
            from .plane_route import body_eligible, extract_bag_of_terms
            # body_eligible re-checks body-carried features; the kwargs
            # variants (min_score/search_after above) are checked directly
            if body_eligible(body):
                ext = extract_bag_of_terms(query_spec, self.mapper)
                if ext is not None:
                    plane = self.plane_provider(self.segments, ext[0])
                    if plane is not None:
                        plane_route = (plane, ext[1])

        # --- fused one-dispatch route (the query planner) -----------------
        # A lowerable bool tree / hybrid knn / rescore pipeline executes
        # as ONE fused dispatch over the serving generations
        # (search/query_planner.py) instead of two dispatches + host
        # fusion; anything the planner or its runner cannot serve falls
        # through to the existing paths below unchanged.
        fused_result = None
        fused_plan = None
        fused_aggs = None
        planner_consulted = False
        shape_id = None
        if (self.fused_provider is not None and query_spec
                and knn_override is None
                and (window > 0 or aggs is not None)
                and min_score is None and search_after is None
                and not use_field_sort and not collect_agg_inputs):
            from . import query_planner as qp
            if qp.planner_enabled():
                planner_consulted = True
                fused_plan = qp.lower_body(body, self.mapper)
                if fused_plan is not None:
                    # upgrade the request's ambient shape id from the
                    # structural fingerprint (bound at the index-service
                    # edge) to the plan-based one BEFORE any dispatch
                    # enqueues, so micro-batch slots and journal events
                    # carry the same id the slow log will
                    from . import query_insight as _qi
                    from ..common import flightrec as _fr
                    shape_id = _qi.shape_of(body, plan=fused_plan)
                    _fr.set_shape(shape_id)
                runner = None
                if fused_plan is not None:
                    runner = self.fused_provider(
                        self.segments, fused_plan.field,
                        fused_plan.knn.field
                        if fused_plan.knn is not None else None)
                if fused_plan is not None and runner is not None and \
                        runner.can_serve(fused_plan):
                    if prune_opt is None:
                        fprune = False if track_total_hits is True \
                            else None
                    else:
                        fprune = prune_opt
                    from .microbatch import batched_fused_search
                    fstages: Dict[str, float] = {}
                    finfo: Dict[str, object] = {}
                    try:
                        fused_result = batched_fused_search(
                            runner, qp.make_item(fused_plan),
                            view=self.segments, stages=fstages,
                            info=finfo, prune=fprune)
                    except qp.FusedFallback:
                        fused_result = None
                from ..common import telemetry as _tm
                _tm.record_planner(
                    "fused" if fused_result is not None
                    else "fallback")
        # the planner's verdict + lowering cost, shared by the Profile
        # API section below and the slow-log entry (ShardSearchResult.
        # planner): a slow fused dispatch is bisectable from its
        # slow-log line alone
        planner_doc = None
        if planner_consulted:
            planner_doc = {
                "outcome": ("fused" if fused_result is not None
                            else "fallback"),
                "lower_ms": round(fused_plan.lower_ms, 3)
                if fused_plan is not None else None,
                "stages_per_dispatch": fused_plan.n_stages()
                if fused_plan is not None else None,
                "shape": shape_id,
            }

        # --- query phase (device) -----------------------------------------
        pending = []
        agg_pending = []
        host_masks: Dict[int, np.ndarray] = {}
        host_scores: Dict[int, np.ndarray] = {}
        need_host_mask = use_field_sort
        serving_stages: Optional[Dict[str, float]] = None
        serving_info: Optional[Dict[str, object]] = None
        plane_total_gte = False
        if fused_result is not None:
            # the fused dispatch already ran the whole retrieval
            # pipeline (bool scoring, knn, fusion, rescore): its rows
            # ARE the candidates, its lexical count the total, and the
            # knn/rescore sections below must not run again
            fvals, fhits, ftotal = fused_result[:3]
            # an agg-carrying fused dispatch returns its analytics
            # stages' result as a 4th element (agg_planner.py)
            if len(fused_result) > 3:
                fused_aggs = fused_result[3]
            serving_stages = fstages
            serving_info = finfo
            from ..parallel.dist_search import (total_is_lower_bound,
                                                total_value)
            plane_total_gte = total_is_lower_bound(ftotal)
            total = total_value(ftotal)
            candidates = [(float(v), si, d)
                          for v, (si, d) in zip(fvals, fhits)]
            knn_spec = None
            rescore_spec = None
            rank_spec = None
            from ..common import tracing as _tracing
            _tracing.record_point(
                "fused_dispatch",
                took_ms=sum(v for v in serving_stages.values()
                            if isinstance(v, (int, float))),
                attrs={**{s: round(ms, 3)
                          for s, ms in serving_stages.items()
                          if isinstance(ms, (int, float))},
                       **serving_info})
            _attribute_dispatch(serving_stages, serving_info)
        elif plane_route is not None:
            plane, bag_terms = plane_route
            # concurrent eligible queries coalesce into one device dispatch
            # (search/microbatch.py — the search-thread-pool analog); the
            # batcher stamps this request's per-stage pipeline timings and
            # dispatch metadata (compile-cache hit/miss, batch size)
            from .microbatch import batched_search
            serving_stages = {}
            serving_info = {}
            # prune resolution: an explicit body knob wins; the default
            # prunes only when the request does not demand exact totals
            # (track_total_hits true = Lucene's complete-collection
            # mode, which disables WAND there too). An explicit
            # prune=false on a tier-bearing plane is benched-default
            # drift — counted for the plane_serving health indicator.
            if prune_opt is None:
                prune_eff = False if track_total_hits is True else None
            else:
                prune_eff = prune_opt
            if prune_opt is False and \
                    getattr(plane, "blockmax", None) is not None:
                from ..common.telemetry import record_lex
                record_lex(prune_off=True)
            # view=self.segments: hit coordinates must decode against
            # THIS searcher's snapshot even if a refresh mutates the
            # generation's delta while the request sits in the queue
            pvals0, phits0, ptotal0 = batched_search(
                plane, bag_terms, k=max(window, 1), stages=serving_stages,
                info=serving_info, view=self.segments, prune=prune_eff)
            from ..parallel.dist_search import (total_is_lower_bound,
                                                total_value)
            plane_total_gte = total_is_lower_bound(ptotal0)
            total = total_value(ptotal0)
            candidates = [(float(v), si, d)
                          for v, (si, d) in zip(pvals0, phits0)]
            # trace: the micro-batch dispatch as one leaf span under the
            # ambient shard span (stage timings arrive after the fact)
            from ..common import tracing as _tracing
            _tracing.record_point(
                "plane_dispatch",
                took_ms=sum(serving_stages.values()),
                attrs={**{s: round(ms, 3)
                          for s, ms in serving_stages.items()},
                       **serving_info})
            _attribute_dispatch(serving_stages, serving_info)
        else:
            for seg_idx, seg in enumerate(self.segments):
                scores, mask = query.execute(self.ctx, seg)
                mask = mask & seg.live_dev
                if seg.has_nested:
                    # hidden block-join children never surface at top level
                    mask = mask & seg.parent_mask_dev
                if min_score is not None:
                    mask = mask & (scores >= np.float32(min_score))
                count_dev = jnp.sum(mask) if track_total_hits is not False else None
                vals_dev = idx_dev = None
                # the sort path needs the query top-k only to combine with knn
                if window > 0 and (not use_field_sort or knn_spec):
                    # push the search_after cursor into the selection mask so
                    # the per-segment top-k window starts AFTER the cursor —
                    # otherwise docs tied on score beyond the global top-k are
                    # unreachable on later pages (totals/aggs keep the full mask)
                    sel_mask = mask
                    if search_after is not None and not use_field_sort \
                            and not knn_spec:
                        a_sc = jnp.float32(float(search_after[0]))
                        if len(search_after) > 1:
                            asd = int(search_after[1])
                            a_si, a_d = asd >> 32, asd & 0xFFFFFFFF
                            if seg_idx < a_si:
                                cond = scores < a_sc
                            elif seg_idx == a_si:
                                cond = (scores < a_sc) | (
                                    (scores == a_sc) &
                                    (jnp.arange(seg.n_pad) > a_d))
                            else:
                                cond = scores <= a_sc
                        else:
                            cond = scores < a_sc
                        sel_mask = mask & cond
                    kk = min(max(window, 1), seg.n_pad)
                    topk = get_topk_kernel(seg.n_pad, kk)
                    vals_dev, idx_dev = topk(scores, sel_mask)
                pending.append((seg_idx, count_dev, vals_dev, idx_dev))
                if aggs is not None:
                    agg_pending.append((seg, mask, scores))
                if need_host_mask:
                    host_masks[seg_idx] = np.asarray(mask)
                    if not use_field_sort or _sort_includes_score(sort_spec):
                        host_scores[seg_idx] = np.asarray(scores)

            total = 0
            candidates: List[Tuple[float, int, int]] = []
            for seg_idx, count_dev, vals_dev, idx_dev in pending:
                if count_dev is not None:
                    total += int(count_dev)
                if vals_dev is not None:
                    vals = np.asarray(vals_dev)
                    idx = np.asarray(idx_dev)
                    ok = vals > float("-inf")
                    for v, d in zip(vals[ok], idx[ok]):
                        candidates.append((float(v), seg_idx, int(d)))
            candidates.sort(key=lambda c: (-c[0], c[1], c[2]))
            _attribute_segment_scan(self.segments)

        # --- knn section ---------------------------------------------------
        knn_rankings: List[List[Tuple[float, int, int]]] = []
        if knn_override is not None:
            # the coordinator already reduced per-shard knn candidates to
            # the GLOBAL top-k and handed us this shard's slice
            knn_rankings = knn_override
        elif knn_spec:
            specs = knn_spec if isinstance(knn_spec, list) else [knn_spec]
            for spec in specs:
                knn_rankings.append(self._knn_candidates(spec))

        max_score: Optional[float] = None
        if knn_rankings:
            # ONE copy of the fusion arithmetic, shared with the fused
            # planner's host runner (query_planner) — the fused path's
            # bit-parity with this section holds by shared code
            from .query_planner import rrf_fuse_rows, sum_fuse_rows
            if rank_spec and "rrf" in rank_spec:
                rc = int(rank_spec["rrf"].get("rank_constant", 60))
                rankings = ([candidates[:window]] if query_spec else []) \
                    + knn_rankings
                candidates = rrf_fuse_rows(rankings, rc)
            else:
                # hybrid: sum scores for docs in both result sets
                rankings = ([candidates] if query_spec else []) \
                    + knn_rankings
                candidates = sum_fuse_rows(rankings)
            if not query_spec:
                total = len(candidates)
            if use_field_sort:
                # knn + sort: the knn/hybrid result set IS the doc set; the
                # sort only orders it (reference: knn section + sort)
                restricted: Dict[int, np.ndarray] = {}
                for _, si, d in candidates:
                    m = restricted.get(si)
                    if m is None:
                        m = restricted[si] = np.zeros(
                            self.segments[si].n_pad, bool)
                    m[d] = True
                host_masks = {si: host_masks[si] & m if si in host_masks
                              else m for si, m in restricted.items()}
                total = len(candidates)

        # --- rescore (QueryRescorer.java: reorder the top window only) -----
        if rescore_spec and candidates:
            candidates = self._apply_rescore(rescore_spec, candidates)

        # --- ranking → page ------------------------------------------------
        if use_field_sort:
            page, sort_clauses = self._field_sorted_page(
                sort_spec, search_after, host_masks, host_scores, k,
                collapse_field=(collapse_spec or {}).get("field"))
            page = page[from_:]
            if track_total_hits is not False and not knn_rankings:
                total = sum(int(m[: self.segments[si].n_docs].sum())
                            for si, m in host_masks.items())
        else:
            sort_clauses = None
            if candidates:
                max_score = candidates[0][0]
            if search_after is not None:
                # search_after on _score desc. Hits carry a [score,
                # shard_doc] composite cursor (mirroring ES's implicit
                # _shard_doc tiebreak under PIT); when the client passes it
                # back, docs tied on score paginate correctly instead of
                # being skipped by a bare strict-< filter.
                after = float(search_after[0])
                if len(search_after) > 1:
                    after_sd = int(search_after[1])
                    candidates = [
                        c for c in candidates
                        if c[0] < after or
                        (c[0] == after and self._shard_doc(c[1], c[2])
                         > after_sd)]
                else:
                    candidates = [c for c in candidates if c[0] < after]
            if collapse_spec:
                candidates = self._collapse_candidates(
                    collapse_spec["field"], candidates)
            page = [(float(sc), si, d,
                     [float(sc), self._shard_doc(si, d)])
                    for sc, si, d in candidates[from_: from_ + size]]
        total_relation = "eq"
        if track_total_hits is False:
            total = len(page) if use_field_sort else len(candidates)
            total_relation = "gte" if total >= k else "eq"
        elif isinstance(track_total_hits, int) and not isinstance(
                track_total_hits, bool) and total > track_total_hits:
            total = track_total_hits
            total_relation = "gte"
        elif plane_total_gte:
            # block-max pruned dispatch early-exited: the skipped
            # blocks' docs were never counted — the total is an honest
            # lower bound (Lucene's WAND total semantics)
            total_relation = "gte"

        # --- fetch phase ---------------------------------------------------
        source_spec = body.get("_source", True)
        stored = body.get("stored_fields")
        if stored is not None and "_source" not in body and \
                "_source" not in _as_list_(stored):
            # stored_fields [] / "_none_" / list without _source → no source
            source_spec = False
        if not self.mapper.source_enabled:
            source_spec = False
        dv_specs = body.get("docvalue_fields") or []
        field_specs = body.get("fields") or []
        hl_spec = body.get("highlight")
        hl_terms: Dict[str, set] = {}
        hl_field_terms: Dict[str, set] = {}
        if hl_spec:
            query.collect_highlight_terms(self.ctx, hl_terms)
            fs = hl_spec.get("fields", {})
            if isinstance(fs, list):
                merged_fs = {}
                for f_ in fs:
                    merged_fs.update(f_)
                fs = merged_fs
            for hf, hf_spec in fs.items():
                hq = (hf_spec or {}).get("highlight_query")
                if hq:
                    # per-field override query supplies THE terms
                    # (HighlightBuilder#highlightQuery)
                    ov: Dict[str, set] = {}
                    parse_query(hq).collect_highlight_terms(self.ctx, ov)
                    hl_field_terms[hf] = set().union(*ov.values()) \
                        if ov else set()
            hl_spec = dict(hl_spec, _field_terms=hl_field_terms,
                           _max_analyzed_offset=getattr(
                               self, "max_analyzed_offset", None))

        collapse_keyf = (self._collapse_key_fn(collapse_spec["field"])
                         if collapse_spec else None)
        hits = []
        for score, seg_idx, d, sort_values in page:
            seg = self.segments[seg_idx]
            src = seg.sources[d]
            hit = ShardHit(
                doc_id=seg.doc_uids[d], score=score, seg_idx=seg_idx,
                local_doc=d, source=filter_source(src, source_spec),
                sort_values=sort_values, seq_no=int(seg.seq_nos[d]))
            ign = seg.keyword_fields.get("_ignored")
            if ign is not None and ign.dv_docs_host.size:
                # dv pairs are doc-sorted: O(log M) slice per hit
                lo_i = int(np.searchsorted(ign.dv_docs_host, d, "left"))
                hi_i = int(np.searchsorted(ign.dv_docs_host, d, "right"))
                if hi_i > lo_i:
                    hit.ignored = [ign.ord_terms[o] for o in
                                   ign.dv_ords_host[lo_i:hi_i]]
            if dv_specs:
                hit.fields = docvalue_fields(seg, self.mapper, d, dv_specs)
            if field_specs:
                from .fetch import fetch_fields
                hit.fields = dict(fetch_fields(self.mapper, src,
                                               field_specs),
                                  **(hit.fields or {}))
            stored_list = [f for f in _as_list_(stored or [])
                           if f not in ("_none_", "_source")]
            if stored_list:
                from .fetch import fetch_fields
                hit.fields = dict(fetch_fields(self.mapper, src,
                                               stored_list),
                                  **(hit.fields or {}))
            if collapse_keyf is not None:
                kv = collapse_keyf(seg_idx, d)
                hit.fields = dict(hit.fields or {},
                                  **{collapse_spec["field"]: [kv]})
            if hl_spec:
                hit.highlight = highlight(self.mapper, src, hl_spec, hl_terms)
            hits.append(hit)

        ih_specs: List[dict] = []
        join_specs: List[tuple] = []
        _collect_nested_inner_specs(query_spec, ih_specs, join_specs)
        if ih_specs and hits:
            self._attach_nested_inner_hits(hits, ih_specs)
        if join_specs and hits:
            self._attach_join_inner_hits(hits, join_specs)

        agg_results = None
        agg_inputs = None
        if aggs is not None and collect_agg_inputs:
            need_scores = _tree_needs_scores(aggs)
            agg_inputs = [(seg, np.asarray(m),
                           np.asarray(sc) if need_scores else None)
                          for seg, m, sc in agg_pending]
        elif fused_aggs is not None:
            # the fused dispatch's agg stages already reduced this
            # shard's tree (same collect/reduce code — agg_planner.py):
            # the legacy second pass below must not run again
            agg_results = fused_aggs
        elif aggs is not None:
            seg_scores = ({seg.seg_id: np.asarray(sc)
                           for seg, _, sc in agg_pending}
                          if _tree_needs_scores(aggs) else {})
            agg_ctx = AggregationContext(self.mapper, shard_ctx=self.ctx,
                                         seg_scores=seg_scores)
            seg_masks = [(seg, np.asarray(m)) for seg, m, _ in agg_pending]
            agg_results = run_aggregations(aggs, agg_ctx, seg_masks)

        suggest_out = None
        if suggest_spec:
            from .suggest import run_suggest
            suggest_out = run_suggest(self.ctx, suggest_spec)

        profile_out = None
        if profile_on:
            # per-request query-phase timing (search/profile/Profilers.java
            # — segment-level collectors folded into one query node)
            total_nanos = int((_time.perf_counter() - t_query0) * 1e9)
            shard_prof = {
                "id": "[tpu][0]",
                "searches": [{
                    "query": [{
                        "type": type(query).__name__,
                        "description": json.dumps(query_spec or
                                                  {"match_all": {}}),
                        "time_in_nanos": total_nanos,
                        "breakdown": {
                            "segments": len(self.segments),
                            "score_mode": ("field_sort" if use_field_sort
                                           else "score"),
                        },
                    }],
                    "rewrite_time": 0,
                    "collector": [{
                        "name": ("PlaneMicroBatchCollector"
                                 if serving_stages is not None
                                 else "EagerDenseCollector"),
                        "reason": "search_top_hits",
                        "time_in_nanos": total_nanos,
                    }],
                }],
                "aggregations": build_agg_profile(
                    aggs or {}, agg_results, self.mapper, self.segments,
                    sum(int(np.asarray(m)[: seg.n_docs].sum())
                        for seg, m, _ in agg_pending)) if aggs else [],
            }
            if serving_stages is not None:
                # the real plane path: per-stage pipeline timings + this
                # dispatch's compile-cache verdict — the Profile API now
                # reflects serving, not just host-side query rewriting
                shard_prof["serving"] = {
                    "stages_ms": {s: round(ms, 3)
                                  for s, ms in serving_stages.items()},
                    **(serving_info or {})}
                # the query shape id joins this profile to its
                # /_insights/top_queries row and flight-recorder events
                from ..common import flightrec as _fr
                prof_shape = shape_id or _fr.current_shape()
                if prof_shape:
                    shard_prof["serving"]["shape"] = prof_shape
            if planner_doc is not None:
                # the one-dispatch planner's verdict + lowering cost:
                # operators bisecting a fused-path regression see which
                # route served and what the compile step of the request
                # (host-side lowering) cost
                shard_prof["planner"] = planner_doc
                if serving_stages is not None and \
                        fused_result is not None:
                    shard_prof["serving"]["planner"] = planner_doc
            profile_out = {"shards": [shard_prof]}

        return ShardSearchResult(total=total, total_relation=total_relation,
                                 hits=hits, max_score=max_score,
                                 aggregations=agg_results,
                                 agg_inputs=agg_inputs,
                                 profile=profile_out, suggest=suggest_out,
                                 serving_stages=serving_stages or None,
                                 planner=planner_doc)

    def _attach_nested_inner_hits(self, hits: List[ShardHit],
                                  ih_specs: List[dict]) -> None:
        """Per root hit, the matching CHILD rows of each nested clause
        that asked for inner_hits (reference:
        ``search/fetch/subphase/InnerHitsPhase.java`` re-running the
        child query per fetched root). The child query executes once per
        segment; per-hit work is a parent-id filter over its matches."""
        from .fetch import docvalue_fields as _dvf
        from .query_dsl import parse_query as _pq
        index_name = getattr(self.mapper, "index_name", None)
        for spec in ih_specs:
            path = spec.get("path")
            ih = spec.get("inner_hits") or {}
            name = ih.get("name") or path
            size = int(ih.get("size", 3))
            from_ = int(ih.get("from", 0))
            inner_q = _pq(spec.get("query") or {"match_all": {}})
            per_seg: Dict[int, tuple] = {}
            for hit in hits:
                si = hit.seg_idx
                seg = self.segments[si]
                if si not in per_seg:
                    pm = seg.nested_paths.get(path)
                    if pm is None:
                        per_seg[si] = None
                    else:
                        s2, m2 = inner_q.execute(self.ctx, seg)
                        cm = np.zeros(seg.n_pad, bool)
                        cm[: seg.n_docs] = pm & seg.live[: seg.n_docs]
                        cm &= np.asarray(m2)
                        per_seg[si] = (np.asarray(s2), cm, pm)
                entry = per_seg[si]
                root = hit.local_doc
                if entry is None:
                    group = {"hits": {"total": {"value": 0,
                                                "relation": "eq"},
                                      "max_score": None, "hits": []}}
                else:
                    s2, cm, pm = entry
                    par = seg.parent_of[: seg.n_docs]
                    kids = np.flatnonzero(cm[: seg.n_docs] & (par == root))
                    siblings = np.flatnonzero(pm & (par == root))
                    order = np.lexsort((kids, -s2[kids])) \
                        if kids.size else np.empty(0, np.int64)
                    sel = kids[order][from_: from_ + size]
                    ihits = []
                    for c in sel:
                        off = int(np.searchsorted(siblings, c))
                        obj = seg.sources[root]
                        try:
                            for part in path.split("."):
                                obj = obj[part]
                            child_src = obj[off] \
                                if isinstance(obj, list) else obj
                        except (KeyError, IndexError, TypeError):
                            child_src = None
                        d = {"_index": index_name,
                             "_id": seg.doc_uids[root],
                             "_nested": {"field": path, "offset": off},
                             "_score": float(s2[c])}
                        if ih.get("_source") is not False:
                            d["_source"] = child_src
                        dvf = ih.get("docvalue_fields")
                        if dvf:
                            d["fields"] = _dvf(seg, self.mapper, int(c),
                                               dvf)
                        ihits.append(d)
                    mx = float(s2[sel].max()) if sel.size else None
                    group = {"hits": {
                        "total": {"value": int(kids.size),
                                  "relation": "eq"},
                        "max_score": mx, "hits": ihits}}
                if ih.get("version"):
                    group["_want_version"] = True
                hit.inner_hits = dict(hit.inner_hits or {},
                                      **{name: group})

    def _attach_join_inner_hits(self, hits: List[ShardHit],
                                join_specs: List[tuple]) -> None:
        """Per root hit, the matching related REAL docs of each
        has_child / has_parent clause that asked for inner_hits
        (reference: parent-join's ``ParentChildInnerHitContextBuilder``).
        Related docs share the root's shard (routing contract)."""
        from .query_dsl import (_join_field, _kw_values_by_doc,
                                parse_query)
        index_name = getattr(self.mapper, "index_name", None)
        jf = _join_field(self.ctx)
        if jf is None:
            return
        for kind, spec in join_specs:
            ih = spec.get("inner_hits") or {}
            rel = spec.get("type") if kind == "has_child" \
                else spec.get("parent_type")
            name = ih.get("name") or rel
            size = int(ih.get("size", 3))
            from_ = int(ih.get("from", 0))
            inner_q = parse_query(spec.get("query") or {"match_all": {}})
            per_seg: Dict[int, tuple] = {}
            for hit in hits:
                si = hit.seg_idx
                seg = self.segments[si]
                if si not in per_seg:
                    s2, m2 = inner_q.execute(self.ctx, seg)
                    rels = _kw_values_by_doc(seg, jf.name)
                    if kind == "has_child":
                        fam = _kw_values_by_doc(
                            seg, jf.id_field_for(rel))
                    else:
                        fam = _kw_values_by_doc(seg, f"{jf.name}#{rel}")
                    per_seg[si] = (np.asarray(s2), np.asarray(m2),
                                   rels, fam)
                s2, m2, rels, fam = per_seg[si]
                seg = self.segments[hit.seg_idx]
                sel: List[int] = []
                if kind == "has_child":
                    # inner hits = matching CHILD docs of this parent
                    for d, pid in fam.items():
                        if pid == hit.doc_id and rels.get(d) == rel \
                                and m2[d] and seg.live[d]:
                            sel.append(d)
                else:
                    # inner hits = this child's matching PARENT doc
                    my_pid = _kw_values_by_doc(
                        seg, f"{jf.name}#{rel}").get(hit.local_doc)
                    pd = seg.find_doc(my_pid) if my_pid else None
                    if pd is not None and rels.get(pd) == rel and \
                            m2[pd] and seg.live[pd]:
                        sel.append(pd)
                sel.sort(key=lambda d: (-float(s2[d]), d))
                window = sel[from_: from_ + size]
                ihits = []
                for d in window:
                    doc_out = {"_index": index_name,
                               "_id": seg.doc_uids[d],
                               "_score": float(s2[d])}
                    if ih.get("_source") is not False:
                        doc_out["_source"] = seg.sources[d]
                    if ih.get("seq_no_primary_term"):
                        doc_out["_seq_no"] = int(seg.seq_nos[d])
                        doc_out["_primary_term"] = 1
                    ihits.append(doc_out)
                group = {"hits": {
                    "total": {"value": len(sel), "relation": "eq"},
                    "max_score": (float(s2[window[0]]) if window
                                  else None),
                    "hits": ihits}}
                hit.inner_hits = dict(hit.inner_hits or {},
                                      **{name: group})

    @staticmethod
    def _shard_doc(seg_idx: int, doc: int) -> int:
        """Stable tiebreak key over (segment, doc) — ES's ``_shard_doc``."""
        return (seg_idx << 32) | doc

    # ------------------------------------------------------------------
    # rescore + collapse
    # ------------------------------------------------------------------

    def _apply_rescore(self, rescore_spec, candidates):
        """Second-pass scoring of the top window
        (``search/rescore/QueryRescorer.java``): the window reorders by
        ``query_weight·orig + rescore_query_weight·secondary``; ranks
        below the window keep their original order."""
        specs = rescore_spec if isinstance(rescore_spec, list) \
            else [rescore_spec]
        for spec in specs:
            body = spec.get("query") or {}
            rq_spec = body.get("rescore_query")
            if rq_spec is None:
                raise ParsingError("rescore requires [query.rescore_query]")
            qw = float(body.get("query_weight", 1.0))
            rw = float(body.get("rescore_query_weight", 1.0))
            mode = body.get("score_mode", "total")
            window = min(int(spec.get("window_size", 10)), len(candidates))
            rq = parse_query(rq_spec)
            seg_scores: Dict[int, np.ndarray] = {}
            seg_masks: Dict[int, np.ndarray] = {}
            needed = {si for _, si, _ in candidates[:window]}
            for si in needed:
                sc, m = rq.execute(self.ctx, self.segments[si])
                seg_scores[si] = np.asarray(sc)
                seg_masks[si] = np.asarray(m)
            rescored = []
            for sc, si, d in candidates[:window]:
                if seg_masks[si][d]:
                    rs = float(seg_scores[si][d])
                    if mode == "total":
                        ns = qw * sc + rw * rs
                    elif mode == "multiply":
                        ns = (qw * sc) * (rw * rs)
                    elif mode == "avg":
                        ns = (qw * sc + rw * rs) / 2.0
                    elif mode == "max":
                        ns = max(qw * sc, rw * rs)
                    else:                    # "min" (validated at parse)
                        ns = min(qw * sc, rw * rs)
                else:
                    ns = qw * sc
                rescored.append((ns, si, d))
            rescored.sort(key=lambda c: (-c[0], c[1], c[2]))
            # below the window, ranks hold but the primary weight still
            # applies (QueryRescorer keeps score*queryWeight there)
            tail = [(qw * sc, si, d) for sc, si, d in candidates[window:]]
            candidates = rescored + tail
        return candidates

    def _collapse_key_fn(self, field: str):
        """(seg_idx, doc) → group key for the collapse field (first value;
        None groups together, like the reference's null group)."""
        ft = self.mapper.field_type(field)
        if ft is not None and ft.name != field:
            field = ft.name             # alias → concrete column

        if isinstance(ft, KeywordFieldType):
            tables: Dict[int, Dict[int, str]] = {}

            def key(si, d):
                t = tables.get(si)
                if t is None:
                    t = tables[si] = {}
                    kf = self.segments[si].keyword_fields.get(field)
                    if kf is not None:
                        for doc, o in zip(kf.dv_docs_host[::-1],
                                          kf.dv_ords_host[::-1]):
                            t[int(doc)] = kf.ord_terms[int(o)]
                return t.get(d)
            return key

        def nkey(si, d):
            v = self.segments[si].numeric_first_value_column(field)[d]
            return None if np.isnan(v) else float(v)
        return nkey

    def _collapse_candidates(self, field: str, candidates):
        keyf = self._collapse_key_fn(field)
        return collapse_first_by_key(candidates,
                                     lambda c: keyf(c[1], c[2]))

    def _field_sorted_page(self, sort_spec, search_after, host_masks,
                           host_scores, k, collapse_field=None):
        """Sorted query path: lexsort matched docs on normalized keys
        (reference: ``search/sort/SortBuilder`` → Lucene ``SortField``).

        An implicit trailing ``_doc`` tiebreak is always appended (the
        reference's PIT ``_shard_doc``): without it, docs exactly tied on
        every user sort key at a page boundary are skipped by the strict
        search_after tuple filter. Cursors may carry the tiebreak value or
        omit it (legacy strict-tuple semantics)."""
        clauses = self._normalize_sort(sort_spec)
        n_user = len(clauses)
        if clauses[-1]["field"] != "_doc":
            clauses.append({"field": "_doc", "order": "asc",
                            "missing": "_last"})
        if search_after is not None and len(search_after) == n_user \
                and len(clauses) == n_user + 1:
            # no tiebreak in the cursor: exclude all equal-prefix rows
            search_after = list(search_after) + [float("inf")]
        all_rows = []       # (seg_idx, doc)
        raw_cols = [[] for _ in clauses]
        for seg_idx, seg in enumerate(self.segments):
            m = host_masks.get(seg_idx)
            if m is None:
                continue
            docs = np.flatnonzero(m[: seg.n_docs])
            if docs.size == 0:
                continue
            scores = host_scores.get(seg_idx)
            for ci, clause in enumerate(clauses):
                raw_cols[ci].append(self._sort_raw_for(
                    clause, seg_idx, seg, docs, scores))
            all_rows.extend((seg_idx, int(d)) for d in docs)
        if not all_rows:
            return [], clauses
        raws = [np.concatenate(c) for c in raw_cols]
        keys = [self._normalize_keys(clause, raw)
                for clause, raw in zip(clauses, raws)]
        n = len(all_rows)
        keep = np.ones(n, bool)
        if search_after is not None:
            if len(search_after) != len(clauses):
                raise IllegalArgumentError(
                    f"search_after must have {len(clauses)} values")
            eq_prefix = np.ones(n, bool)
            gt_any = np.zeros(n, bool)
            for ci, clause in enumerate(clauses):
                after_key = self._after_key(clause, search_after[ci],
                                            raws[ci], keys[ci])
                gt_any |= eq_prefix & (keys[ci] > after_key)
                eq_prefix &= keys[ci] == after_key
            keep = gt_any
        idx = np.flatnonzero(keep)
        order = np.lexsort(tuple(keys[ci][idx] for ci in
                                 range(len(clauses) - 1, -1, -1)))
        if collapse_field is not None:
            keyf = self._collapse_key_fn(collapse_field)
            seen = set()
            kept = []
            for i in idx[order]:
                si, d = all_rows[i]
                kv = keyf(si, d)
                if kv in seen:
                    continue
                seen.add(kv)
                kept.append(i)
                if len(kept) >= k:
                    break
            top = np.asarray(kept, dtype=np.int64)
        else:
            top = idx[order[:k]]
        page = []
        for i in top:
            seg_idx, d = all_rows[i]
            sort_values = []
            for ci, clause in enumerate(clauses):
                v = raws[ci][i]
                if isinstance(v, float) and np.isnan(v):
                    sort_values.append(None)
                elif isinstance(v, (np.floating, np.integer)):
                    fv = float(v)
                    sort_values.append(int(fv) if fv.is_integer() else fv)
                else:
                    sort_values.append(v)
            score = None
            for ci, clause in enumerate(clauses):
                if clause["field"] == "_score":
                    score = float(raws[ci][i])
            page.append((score, seg_idx, d, sort_values))
        return page, clauses

    def _after_key(self, clause, after_value, raw_col, key_col):
        """Normalize a search_after cursor value into key space."""
        field = clause["field"]
        desc = clause["order"] == "desc"
        if after_value is None:
            # same fill + desc negation as _normalize_keys, so a null cursor
            # lands exactly on the missing block's key
            missing_last = clause["missing"] != "_first"
            fill = _MISSING_LAST if (missing_last != desc) else -_MISSING_LAST
            return -fill if desc else fill
        if raw_col.dtype != object and (
                field == "_score" or field == "_doc" or isinstance(
                    after_value, (int, float))):
            v = float(after_value)
            return -v if desc else v
        # object-column cursor (strings, exact ns longs): odd/even code
        # trick — present values have even codes; an absent cursor value
        # lands between codes
        uniq = sorted({v for v in raw_col if v is not None})
        import bisect
        i = bisect.bisect_left(uniq, after_value)
        if i < len(uniq) and uniq[i] == after_value:
            code = i * 2
        else:
            code = i * 2 - 1
        return -code if desc else code

    def count(self, body: Optional[dict] = None) -> int:
        body = body or {}
        query = (parse_query(body["query"]) if body.get("query")
                 else MatchAllQuery())
        total = 0
        for seg in self.segments:
            _, mask = query.execute(self.ctx, seg)
            mask = mask & seg.live_dev
            if seg.has_nested:
                mask = mask & seg.parent_mask_dev
            total += int(jnp.sum(mask))
        return total


def collapse_first_by_key(items, key_fn):
    """First-wins group dedupe over an already-ranked list — THE collapse
    semantics, shared by every merge tier (shard, index, cluster, REST)."""
    seen = set()
    out = []
    for it in items:
        kv = key_fn(it)
        if kv in seen:
            continue
        seen.add(kv)
        out.append(it)
    return out


def normalize_sort(sort_spec) -> List[dict]:
    """Sort spec → [{field, order, missing}] (shared by the shard searcher
    and the coordinating merges in ``dist_query.py`` / the REST layer)."""
    if isinstance(sort_spec, (str, dict)):
        sort_spec = [sort_spec]
    out = []
    for clause in sort_spec:
        if isinstance(clause, str):
            field, opts = clause, {}
        elif isinstance(clause, dict) and len(clause) == 1:
            (field, opts), = clause.items()
            if isinstance(opts, str):
                opts = {"order": opts}
        else:
            raise ParsingError(f"invalid sort clause [{clause}]")
        order = opts.get("order", "desc" if field == "_score" else "asc")
        out.append({"field": field, "order": order,
                    "missing": opts.get("missing", "_last"),
                    "numeric_type": opts.get("numeric_type")})
    return out


def _sort_includes_score(sort_spec) -> bool:
    if isinstance(sort_spec, (str, dict)):
        sort_spec = [sort_spec]
    for c in sort_spec or []:
        if c == "_score" or (isinstance(c, dict) and "_score" in c):
            return True
    return False


def _as_list_(v) -> list:
    """Shared list coercion (REST layer imports this as _as_list)."""
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def build_agg_profile(aggs: dict, results: Optional[dict], mapper,
                      segments, collect_count: int) -> List[dict]:
    """Aggregation profile entries (search/profile/aggregation/
    AggregationProfiler): ES aggregator class names + debug payloads
    mapped from this engine's aggregator classes."""
    from ..index.mapping import KeywordFieldType, NumberFieldType
    from .aggregations import (DateHistogramAgg, HistogramAgg,
                               PipelineAggregator, TermsAgg)
    out: List[dict] = []
    for name, agg in (aggs or {}).items():
        if isinstance(agg, PipelineAggregator):
            continue
        res = (results or {}).get(name, {}) or {}
        raw = getattr(agg, "_raw", {}) or {}
        entry = {"type": type(agg).__name__, "description": name,
                 "time_in_nanos": 1000,
                 "breakdown": {"initialize": 1, "initialize_count": 1,
                               "collect": 1, "collect_count": collect_count,
                               "build_aggregation": 1,
                               "build_aggregation_count": 1,
                               "build_leaf_collector": 1,
                               "build_leaf_collector_count":
                                   max(len(segments), 1),
                               "reduce": 0, "reduce_count": 0,
                               "post_collection": 1,
                               "post_collection_count": 1},
                 "debug": dict(getattr(agg, "_debug", {}) or {})}
        buckets = res.get("buckets")
        blist = list(buckets.values()) if isinstance(buckets, dict) \
            else (buckets or [])
        nonempty = sum(1 for b in blist
                       if isinstance(b, dict) and b.get("doc_count", 0) > 0)
        if isinstance(agg, TermsAgg):
            field = getattr(agg, "field", "")
            ft = mapper.field_type(field) if mapper else None
            if isinstance(ft, NumberFieldType) or (
                    ft is not None and not isinstance(ft, KeywordFieldType)):
                entry["type"] = "NumericTermsAggregator"
                tn = getattr(ft, "type_name", "long")
                entry["debug"].setdefault(
                    "result_strategy",
                    "double_terms" if tn in ("double", "float", "half_float")
                    else "long_terms")
                entry["debug"].setdefault("total_buckets", len(blist))
            else:
                hint = raw.get("execution_hint", "global_ordinals")
                entry["type"] = ("MapStringTermsAggregator"
                                 if hint == "map"
                                 else "GlobalOrdinalsStringTermsAggregator")
                entry["debug"].setdefault("result_strategy", "terms")
                entry["debug"].setdefault("collection_strategy",
                                          "from string terms"
                                          if hint == "map" else "dense")
                entry["debug"].setdefault("has_filter", False)
                single = multi = 0
                for seg in segments:
                    kf = seg.keyword_fields.get(field)
                    if kf is None or kf.dv_docs_host.shape[0] == 0:
                        continue
                    if np.unique(kf.dv_docs_host).size == \
                            kf.dv_docs_host.shape[0]:
                        single += 1
                    else:
                        multi += 1
                entry["debug"].setdefault(
                    "segments_with_single_valued_ords", single)
                entry["debug"].setdefault(
                    "segments_with_multi_valued_ords", multi)
                if raw.get("collect_mode") == "breadth_first" and agg.subs:
                    entry["debug"].setdefault("deferred_aggregators",
                                              sorted(agg.subs))
        elif isinstance(agg, DateHistogramAgg):
            ft = mapper.field_type(getattr(agg, "field", "")) \
                if mapper else None
            entry["type"] = "DateHistogramAggregator"
            entry["debug"].setdefault("total_buckets", nonempty)
        elif isinstance(agg, HistogramAgg):
            entry["type"] = "NumericHistogramAggregator"
            entry["debug"].setdefault("total_buckets", nonempty)
        elif type(agg).__name__ == "AutoDateHistogramAgg":
            entry["type"] = "AutoDateHistogramAggregator.FromSingle"
        elif type(agg).__name__ == "CardinalityAgg":
            field = getattr(agg, "field", "")
            ft = mapper.field_type(field) if mapper else None
            is_kw = isinstance(ft, KeywordFieldType) or (
                ft is None and any(field in seg.keyword_fields
                                   for seg in segments))
            entry["type"] = ("GlobalOrdCardinalityAggregator" if is_kw
                             else "CardinalityAggregator")
            entry["debug"].update({
                "empty_collectors_used": 0,
                "numeric_collectors_used": 0 if is_kw else 1,
                "ordinals_collectors_used": 1 if is_kw else 0,
                "ordinals_collectors_overhead_too_high": 0,
                "string_hashing_collectors_used": 0})
        if getattr(agg, "subs", None):
            children = build_agg_profile(
                agg.subs,
                blist[0] if blist and isinstance(blist[0], dict) else res,
                mapper, segments, collect_count)
            # metric children get their ES metric class names
            for c in children:
                c["type"] = {
                    "MaxAgg": "MaxAggregator", "MinAgg": "MinAggregator",
                    "SumAgg": "SumAggregator", "AvgAgg": "AvgAggregator",
                    "ValueCountAgg": "ValueCountAggregator",
                    "CardinalityAgg": "CardinalityAggregator",
                }.get(c["type"], c["type"])
            if children:
                entry["children"] = children
        out.append(entry)
        # ES metric class names at the top level too
        entry["type"] = {
            "MaxAgg": "MaxAggregator", "MinAgg": "MinAggregator",
            "SumAgg": "SumAggregator", "AvgAgg": "AvgAggregator",
            "ValueCountAgg": "ValueCountAggregator",
            "CardinalityAgg": "CardinalityAggregator",
            "GlobalAgg": "GlobalAggregator",
        }.get(entry["type"], entry["type"])
    return out
