"""Shard-level search execution: query phase + hit merge.

Re-design of the reference's shard search entry
(``search/SearchService.java:378 executeQueryPhase`` →
``search/query/QueryPhase.java:132`` → per-segment collectors). Here the
"collector" is data-parallel: every segment is scored eagerly to dense
(scores, mask) arrays by the query tree (``query_dsl.py``), top-k hits are
selected on device per segment (``ops/topk.py``), and the tiny per-segment
candidate lists are merged on the host (score desc, then segment/doc id asc —
Lucene's tie-break order).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..common.errors import IllegalArgumentError
from ..index.mapping import MapperService
from ..index.segment import Segment
from ..ops.topk import get_topk_kernel
from ..utils.shapes import round_up_pow2
from .aggregations import (AggregationContext, BucketAggregator, TopHitsAgg,
                           parse_aggs, run_aggregations)
from .query_dsl import ShardContext, parse_query, MatchAllQuery


def _tree_needs_scores(aggs: dict) -> bool:
    for a in aggs.values():
        if isinstance(a, TopHitsAgg):
            return True
        if isinstance(a, BucketAggregator) and _tree_needs_scores(a.subs):
            return True
    return False


@dataclass
class ShardHit:
    doc_id: str
    score: float
    seg_idx: int
    local_doc: int
    source: Optional[dict]
    sort_values: Optional[List[Any]] = None
    seq_no: Optional[int] = None


@dataclass
class ShardSearchResult:
    total: int
    total_relation: str
    hits: List[ShardHit]
    max_score: Optional[float]
    aggregations: Optional[Dict[str, Any]] = None
    profile: Optional[dict] = None


class ShardSearcher:
    """Executes one search request against one shard's segment list."""

    def __init__(self, segments: List[Segment], mapper: MapperService):
        self.segments = [s for s in segments if s.n_docs > 0]
        self.mapper = mapper
        self.ctx = ShardContext(self.segments, mapper)

    def search(self, body: Optional[dict] = None, *, size: int = 10,
               from_: int = 0, min_score: Optional[float] = None,
               track_total_hits=True) -> ShardSearchResult:
        body = body or {}
        size = int(body.get("size", size))
        from_ = int(body.get("from", from_))
        min_score = body.get("min_score", min_score)
        track_total_hits = body.get("track_total_hits", track_total_hits)
        query = (parse_query(body["query"]) if body.get("query")
                 else MatchAllQuery())
        aggs_spec = body.get("aggs") or body.get("aggregations")
        aggs = parse_aggs(aggs_spec) if aggs_spec else None

        k = size + from_
        # Dispatch all per-segment device work first, pull results after —
        # no host sync between segments, so XLA can overlap their kernels
        # (the reference overlaps segments via per-leaf search threads,
        # ContextIndexSearcher.java:177).
        pending = []  # (seg_idx, count_dev, vals_dev|None, idx_dev|None)
        agg_pending = []  # (seg, mask_dev, scores_dev)
        for seg_idx, seg in enumerate(self.segments):
            scores, mask = query.execute(self.ctx, seg)
            mask = mask & seg.live_dev
            if min_score is not None:
                mask = mask & (scores >= np.float32(min_score))
            count_dev = jnp.sum(mask) if track_total_hits is not False else None
            vals_dev = idx_dev = None
            if k > 0:
                kk = min(max(k, 1), seg.n_pad)
                topk = get_topk_kernel(seg.n_pad, kk)
                vals_dev, idx_dev = topk(scores, mask)
            pending.append((seg_idx, count_dev, vals_dev, idx_dev))
            if aggs is not None:
                agg_pending.append((seg, mask, scores))

        total = 0
        candidates: List[Tuple[float, int, int]] = []  # (score, seg_idx, doc)
        max_score = None
        for seg_idx, count_dev, vals_dev, idx_dev in pending:
            if count_dev is not None:
                total += int(count_dev)
            if vals_dev is not None:
                vals = np.asarray(vals_dev)
                idx = np.asarray(idx_dev)
                valid = vals > float("-inf")
                for v, d in zip(vals[valid], idx[valid]):
                    candidates.append((float(v), seg_idx, int(d)))

        # merge: score desc, then (seg_idx, doc) asc — global doc-id order
        candidates.sort(key=lambda c: (-c[0], c[1], c[2]))
        if candidates:
            max_score = candidates[0][0]
        page = candidates[from_: from_ + size]
        total_relation = "eq"
        if track_total_hits is False:
            total = len(candidates)
            total_relation = "gte" if total >= k else "eq"
        elif isinstance(track_total_hits, int) and not isinstance(
                track_total_hits, bool) and total > track_total_hits:
            total = track_total_hits
            total_relation = "gte"

        hits = []
        for score, seg_idx, d in page:
            seg = self.segments[seg_idx]
            hits.append(ShardHit(
                doc_id=seg.doc_uids[d], score=score, seg_idx=seg_idx,
                local_doc=d, source=seg.sources[d],
                seq_no=int(seg.seq_nos[d])))

        agg_results = None
        if aggs is not None:
            # score arrays only leave the device when a top_hits agg needs them
            seg_scores = ({seg.seg_id: np.asarray(sc)
                           for seg, _, sc in agg_pending}
                          if _tree_needs_scores(aggs) else {})
            agg_ctx = AggregationContext(self.mapper, shard_ctx=self.ctx,
                                         seg_scores=seg_scores)
            seg_masks = [(seg, np.asarray(m)) for seg, m, _ in agg_pending]
            agg_results = run_aggregations(aggs, agg_ctx, seg_masks)

        return ShardSearchResult(total=total, total_relation=total_relation,
                                 hits=hits, max_score=max_score,
                                 aggregations=agg_results)

    def count(self, body: Optional[dict] = None) -> int:
        body = body or {}
        query = (parse_query(body["query"]) if body.get("query")
                 else MatchAllQuery())
        total = 0
        for seg in self.segments:
            _, mask = query.execute(self.ctx, seg)
            total += int(jnp.sum(mask & seg.live_dev))
        return total
