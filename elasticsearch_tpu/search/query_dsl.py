"""Query DSL: parse JSON queries and execute them against segments.

Re-design of the reference's query layer (``server/.../index/query/`` — 40+
``QueryBuilder``s parsed in ``AbstractQueryBuilder.parseInnerQueryBuilder``,
compiled to Lucene ``Query``s and scored by iterator-based ``BulkScorer``s).

TPU-first execution model: every query evaluates, per segment, to a pair of
dense device arrays ``(scores float32[N_pad], mask bool[N_pad])`` — eager
whole-segment scoring (the BM25S insight, see PAPERS.md) instead of doc-at-a-
time iterators. Compound queries are then pure array algebra:

- ``bool``: AND/OR/NOT on masks, sum of scores over scoring clauses
  (reference semantics: ``BoolQueryBuilder.java``),
- ``dis_max``: elementwise max + tie_breaker,
- ``constant_score``: mask with a constant fill.

This maps the whole query tree onto the VPU with no per-doc control flow, and
the same arrays feed aggregations (masks) and top-k hit selection downstream.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..common.errors import (ElasticsearchError,
                             IllegalArgumentError, ParsingError)
from ..index.mapping import (
    BooleanFieldType, ConstantKeywordFieldType, DateFieldType,
    DenseVectorFieldType, IpFieldType, KeywordFieldType, MapperService,
    NumberFieldType, RangeFieldType, RuntimeFieldType, TextFieldType,
    parse_date_millis)
from ..index.segment import Segment
from ..ops.bm25 import DEFAULT_B, DEFAULT_K1, get_bm25_kernel, idf_weight
from ..ops.masks import get_postings_match_kernel, get_range_mask_kernel
from ..utils.shapes import round_up_pow2


# ---------------------------------------------------------------------------
# Shard-level execution context
# ---------------------------------------------------------------------------


class ShardContext:
    """Shard-level stats + segment list for one search. idf/avgdl are
    cross-segment (Lucene computes them at the IndexSearcher level —
    ``search/similarity`` stats in ``TermStatistics``)."""

    def __init__(self, segments: List[Segment], mapper: MapperService):
        self.segments = [s for s in segments if s.n_docs > 0]
        self.mapper = mapper
        # Lucene idf uses docCount of the field (docs incl. deleted).
        self.total_docs = sum(s.n_docs for s in self.segments)
        self._df_cache: Dict[Tuple[str, str], int] = {}
        self._field_stats_cache: Dict[str, Tuple[float, int]] = {}

    def term_df(self, field: str, term: str) -> int:
        key = (field, term)
        df = self._df_cache.get(key)
        if df is None:
            df = sum(s.term_df(field, term) for s in self.segments)
            self._df_cache[key] = df
        return df

    def field_avgdl(self, field: str) -> float:
        stats = self._field_stats_cache.get(field)
        if stats is None:
            sum_dl = 0.0
            doc_count = 0
            for s in self.segments:
                sdl, dc = s.field_stats(field)
                sum_dl += sdl
                doc_count += dc
            stats = (sum_dl, doc_count)
            self._field_stats_cache[field] = stats
        sum_dl, doc_count = stats
        return sum_dl / doc_count if doc_count else 1.0

    def field_type(self, name: str):
        return self.mapper.field_type(name)

    def concrete_field(self, name: str) -> str:
        """Resolve a field ALIAS to its target path (segment tables key by
        concrete names; FieldAliasMapper semantics)."""
        ft = self.mapper.field_type(name)
        return ft.name if ft is not None and ft.name != name else name


def _const_result(seg: Segment, score: float, value: bool):
    n = seg.n_pad
    if value:
        mask = jnp.ones(n, jnp.bool_)
        scores = jnp.full(n, np.float32(score))
    else:
        mask = jnp.zeros(n, jnp.bool_)
        scores = jnp.zeros(n, jnp.float32)
    return scores, mask


# ---------------------------------------------------------------------------
# Scoring helpers
# ---------------------------------------------------------------------------


def _score_text_terms(ctx: ShardContext, seg: Segment, field: str,
                      term_weights: Dict[str, float]):
    """BM25-score a bag of unique terms against one segment's text field.
    Returns (scores f32[N_pad], matched int32[N_pad], n_unique_terms)."""
    f = seg.text_fields.get(field)
    terms = list(term_weights)
    q = len(terms)
    if f is None or q == 0:
        z = jnp.zeros(seg.n_pad, jnp.float32)
        return z, jnp.zeros(seg.n_pad, jnp.int32), q
    starts = np.zeros(q, np.int32)
    lengths = np.zeros(q, np.int32)
    dfs = np.zeros(q, np.int64)
    max_len = 1
    for i, t in enumerate(terms):
        s, l, _ = f.term_run(t)
        starts[i], lengths[i] = s, l
        dfs[i] = ctx.term_df(field, t)
        max_len = max(max_len, l)
    L = round_up_pow2(max_len)
    idf = idf_weight(ctx.total_docs, dfs)
    weights = np.asarray([term_weights[t] for t in terms], np.float32)
    avgdl = np.float32(max(ctx.field_avgdl(field), 1e-9))
    kernel = get_bm25_kernel(seg.n_pad, L)
    scores, matched = kernel(f.docs_dev, f.tf_dev, f.doc_len_dev, starts,
                             lengths, idf, weights,
                             avgdl, np.float32(DEFAULT_K1), np.float32(DEFAULT_B))
    return scores, matched, q


def _keyword_terms_result(ctx: ShardContext, seg: Segment, field: str,
                          term_weights: Dict[str, float], scored: bool):
    """Match keyword terms. When ``scored``, per-term score is idf × weight
    (norms disabled → LegacyBM25 collapses to idf for tf=1; reference:
    Lucene BM25 with omitNorms, selected by ``KeywordFieldMapper``)."""
    f = seg.keyword_fields.get(field)
    terms = list(term_weights)
    q = len(terms)
    if f is None or q == 0:
        return (jnp.zeros(seg.n_pad, jnp.float32),
                jnp.zeros(seg.n_pad, jnp.int32), q)
    starts = np.zeros(q, np.int32)
    lengths = np.zeros(q, np.int32)
    dfs = np.zeros(q, np.int64)
    max_len = 1
    for i, t in enumerate(terms):
        s, l, _ = f.term_run(t)
        starts[i], lengths[i] = s, l
        dfs[i] = ctx.term_df(field, t)
        max_len = max(max_len, l)
    L = round_up_pow2(max_len)
    if scored:
        idf = idf_weight(ctx.total_docs, dfs)
        weights = np.asarray([term_weights[t] for t in terms], np.float32)
        kernel = get_bm25_kernel(seg.n_pad, L)
        # norms disabled → b=0 and tf=1, so the BM25 kernel reduces to idf
        scores, matched = kernel(
            f.docs_dev, jnp.ones(f.docs_dev.shape[0], jnp.float32),
            jnp.zeros(seg.n_pad, jnp.float32), starts, lengths, idf, weights,
            np.float32(1.0), np.float32(DEFAULT_K1), np.float32(0.0))
        return scores, matched, q
    kernel = get_postings_match_kernel(seg.n_pad, L)
    matched = kernel(f.docs_dev, starts, lengths)
    return jnp.zeros(seg.n_pad, jnp.float32), matched, q


# ---------------------------------------------------------------------------
# minimum_should_match (reference: common/lucene/search/Queries.java)
# ---------------------------------------------------------------------------

_MSM_PART = re.compile(r"^\s*(-?\d+)(%?)\s*$")


def resolve_minimum_should_match(spec, clause_count: int) -> int:
    if spec is None:
        return 0
    if isinstance(spec, int):
        result = spec
    else:
        s = str(spec)
        if "<" in s:
            # "N<spec" conditional: if clause_count > N apply spec, else all
            # clauses are required (reference: Queries.calculateMinShouldMatch)
            chosen = None
            for part in s.split():
                if "<" not in part:
                    continue
                cond, _, val = part.partition("<")
                if clause_count > int(cond):
                    chosen = val
            if chosen is None:
                return clause_count
            s = chosen
        m = _MSM_PART.match(s)
        if not m:
            raise ParsingError(f"invalid minimum_should_match [{spec}]")
        if m.group(2):
            pct = int(m.group(1))
            calc = int(abs(pct) / 100.0 * clause_count)
            result = calc if pct >= 0 else clause_count - calc
        else:
            result = int(m.group(1))
    if result < 0:
        result = clause_count + result
    return max(0, min(result, clause_count))


# ---------------------------------------------------------------------------
# Query tree
# ---------------------------------------------------------------------------


class Query:
    boost: float = 1.0

    def execute(self, ctx: ShardContext, seg: Segment):
        raise NotImplementedError

    def collect_highlight_terms(self, ctx: ShardContext,
                                out: Dict[str, set]) -> None:
        """Accumulate field -> analyzed terms for the highlighter
        (reference: highlight phase extracting terms from the query —
        ``subphase/highlight/``). Default: nothing."""

    def __repr__(self):
        return f"{type(self).__name__}({self.__dict__})"


class MatchAllQuery(Query):
    def __init__(self, boost: float = 1.0):
        self.boost = boost

    def execute(self, ctx, seg):
        return _const_result(seg, self.boost, True)


class MatchNoneQuery(Query):
    def execute(self, ctx, seg):
        return _const_result(seg, 0.0, False)


class MatchQuery(Query):
    """Full-text match (reference: ``index/query/MatchQueryBuilder.java``).
    Analyzes the text with the field's search analyzer; OR semantics by
    default, ``operator=and`` / ``minimum_should_match`` supported."""

    def __init__(self, field: str, text, operator: str = "or",
                 minimum_should_match=None, boost: float = 1.0,
                 analyzer: Optional[str] = None):
        self.field = field
        self.text = text
        self.operator = operator.lower()
        self.msm = minimum_should_match
        self.boost = boost
        self.analyzer = analyzer

    def _analyze(self, ctx: ShardContext) -> List[str]:
        ft = ctx.field_type(self.field)
        if isinstance(ft, TextFieldType):
            analyzer = (ctx.mapper.analysis.get(self.analyzer)
                        if self.analyzer else ft.search_analyzer)
            return analyzer.terms(str(self.text))
        if isinstance(ft, KeywordFieldType):
            v = ft.parse_value(self.text)  # applies normalizer/ignore_above
            return [v] if v is not None else []
        return [str(self.text)]

    def execute(self, ctx, seg):
        self.field = ctx.concrete_field(self.field)
        ft = ctx.field_type(self.field)
        if ft is None:
            return _const_result(seg, 0.0, False)
        if isinstance(ft, (NumberFieldType, DateFieldType, BooleanFieldType)):
            return TermQuery(self.field, self.text, self.boost).execute(ctx, seg)
        terms = self._analyze(ctx)
        if not terms:
            return _const_result(seg, 0.0, False)
        weights: Dict[str, float] = {}
        for t in terms:
            weights[t] = weights.get(t, 0.0) + 1.0
        if isinstance(ft, KeywordFieldType):
            scores, matched, q = _keyword_terms_result(ctx, seg, self.field,
                                                       weights, scored=True)
        else:
            scores, matched, q = _score_text_terms(ctx, seg, self.field, weights)
        n_required = q if self.operator == "and" else \
            max(1, resolve_minimum_should_match(self.msm, q))
        mask = matched >= n_required
        return scores * np.float32(self.boost), mask

    def collect_highlight_terms(self, ctx, out):
        out.setdefault(self.field, set()).update(self._analyze(ctx))


class MatchPhraseQuery(Query):
    """Phrase match (reference: ``MatchPhraseQueryBuilder.java``). Candidate
    docs are computed on device (AND of terms); exact position adjacency is
    verified host-side against the segment's position CSR, and BM25 is scored
    with tf = phrase frequency, matching Lucene's PhraseQuery scoring."""

    def __init__(self, field: str, text, slop: int = 0, boost: float = 1.0):
        self.field = field
        self.text = text
        self.slop = int(slop)
        self.boost = boost

    def execute(self, ctx, seg):
        self.field = ctx.concrete_field(self.field)
        ft = ctx.field_type(self.field)
        if ft is None:
            return _const_result(seg, 0.0, False)
        if not isinstance(ft, TextFieldType):
            return TermQuery(self.field, self.text, self.boost).execute(ctx, seg)
        terms = ft.search_analyzer.terms(str(self.text))
        if not terms:
            return _const_result(seg, 0.0, False)
        if len(terms) == 1:
            q = MatchQuery(self.field, self.text, boost=self.boost)
            return q.execute(ctx, seg)
        f = seg.text_fields.get(self.field)
        if f is None:
            return _const_result(seg, 0.0, False)
        weights = {t: 1.0 for t in terms}
        _, matched, q = _score_text_terms(ctx, seg, self.field, weights)
        cand = np.asarray(matched >= q)[: seg.n_docs].nonzero()[0]
        scores_host = np.zeros(seg.n_pad, np.float32)
        mask_host = np.zeros(seg.n_pad, bool)
        if cand.size:
            dfs = [ctx.term_df(self.field, t) for t in set(terms)]
            # Lucene phrase idf: sum of per-term idfs
            phrase_idf = float(idf_weight(ctx.total_docs, dfs).sum())
            avgdl = max(ctx.field_avgdl(self.field), 1e-9)
            k1, b = DEFAULT_K1, DEFAULT_B
            for d in cand:
                freq = _phrase_freq(f, terms, int(d), self.slop)
                if freq > 0:
                    dl = float(f.doc_len_host[d])
                    norm = freq + k1 * (1 - b + b * dl / avgdl)
                    scores_host[d] = phrase_idf * (k1 + 1) * freq / norm
                    mask_host[d] = True
        return (jnp.asarray(scores_host * np.float32(self.boost)),
                jnp.asarray(mask_host))

    def collect_highlight_terms(self, ctx, out):
        ft = ctx.field_type(self.field)
        if isinstance(ft, TextFieldType):
            out.setdefault(self.field, set()).update(
                ft.search_analyzer.terms(str(self.text)))


def _phrase_freq(f, terms: List[str], doc: int, slop: int) -> float:
    """Count phrase occurrences in one doc. slop=0 → exact adjacency; slop>0
    uses a simplified sloppy match (within-window, order-insensitive pairs),
    an approximation of Lucene's SloppyPhraseMatcher."""
    pos_lists = []
    for i, t in enumerate(terms):
        p = f.positions_for(t, doc)
        if p.size == 0:
            return 0.0
        pos_lists.append(np.asarray(p, np.int64) - i)
    if slop == 0:
        common = pos_lists[0]
        for p in pos_lists[1:]:
            common = np.intersect1d(common, p, assume_unique=True)
            if common.size == 0:
                return 0.0
        return float(common.size)
    count = 0
    for start in pos_lists[0]:
        ok = all(np.abs(p - start).min() <= slop for p in pos_lists[1:])
        if ok:
            count += 1
    return float(count)


class TermQuery(Query):
    """Exact term (reference: ``TermQueryBuilder.java``). Text fields score
    BM25 on the unanalyzed term; keyword fields score idf; numeric/date/bool
    behave as an equality filter with constant score."""

    def __init__(self, field: str, value, boost: float = 1.0,
                 case_insensitive: bool = False):
        self.field = field
        self.value = value
        self.boost = boost
        self.case_insensitive = case_insensitive

    def execute(self, ctx, seg):
        if self.field == "_id":
            return IdsQuery([self.value], self.boost).execute(ctx, seg)
        if self.case_insensitive:
            # case-insensitive exact term = ci literal scan of the term
            # dictionary (TermQueryBuilder's caseInsensitive flag)
            import re as _re
            return WildcardQuery(
                self.field, _re.escape(str(self.value)), self.boost,
                is_regexp=True, case_insensitive=True).execute(ctx, seg)
        self.field = ctx.concrete_field(self.field)
        ft = ctx.field_type(self.field)
        if ft is None:
            # unmapped META keyword columns (_ignored, _routing) are
            # still term-addressable
            if self.field in seg.keyword_fields:
                scores, matched, _ = _keyword_terms_result(
                    ctx, seg, self.field, {str(self.value): 1.0},
                    scored=False)
                return scores * np.float32(self.boost), matched > 0
            return _const_result(seg, 0.0, False)
        if isinstance(ft, TextFieldType):
            scores, matched, _ = _score_text_terms(
                ctx, seg, self.field, {str(self.value): 1.0})
            return scores * np.float32(self.boost), matched > 0
        if isinstance(ft, ConstantKeywordFieldType):
            # query-time rewrite against the mapped constant: matches all
            # docs (including ones indexed before the value pinned) or
            # none (ConstantKeywordFieldMapper.termQuery)
            hit = ft.value is not None and str(self.value) == ft.value
            return _const_result(seg, self.boost if hit else 0.0, hit)
        if isinstance(ft, KeywordFieldType):
            v = ft.parse_value(self.value)
            scores, matched, _ = _keyword_terms_result(
                ctx, seg, self.field, {v: 1.0}, scored=True)
            return scores * np.float32(self.boost), matched > 0
        if isinstance(ft, IpFieldType):
            cidr = IpFieldType.cidr_bounds(self.value)
            if cidr is not None:
                return _exact_numeric_mask(seg, self.field, cidr[0],
                                           cidr[1], self.boost)
            _, num = ft.parse_value(self.value)
            return _exact_numeric_mask(seg, self.field, num, num,
                                       self.boost)
        if isinstance(ft, RangeFieldType):
            if ft.range_kind == "ip_range" and "/" in str(self.value):
                lo, hi = IpFieldType.cidr_bounds(self.value)
                return _range_field_result(seg, self.field, lo, hi,
                                           "intersects", self.boost)
            p = ft._point(self.value)      # point containment
            return _range_field_result(seg, self.field, p, p,
                                       "intersects", self.boost)
        if isinstance(ft, DateFieldType):
            # query-side values may use date math (now/d etc.)
            val = parse_date_millis(self.value, ft.format)
            return _numeric_range_result(seg, self.field, val, val,
                                         self.boost)
        if isinstance(ft, (NumberFieldType, BooleanFieldType)):
            val = ft.parse_value(self.value)
            return _numeric_range_result(seg, self.field, val, val, self.boost)
        from ..index.mapping import AggregateMetricDoubleFieldType
        if isinstance(ft, AggregateMetricDoubleFieldType):
            # equality against the default_metric column
            val = float(self.value)
            return _numeric_range_result(seg, self.field, val, val,
                                         self.boost)
        return _const_result(seg, 0.0, False)

    def collect_highlight_terms(self, ctx, out):
        out.setdefault(self.field, set()).add(str(self.value))


class TermsQuery(Query):
    """Terms disjunction, constant score (reference: ``TermsQueryBuilder``
    rewrites to a constant-score set query)."""

    def __init__(self, field: str, values: List, boost: float = 1.0):
        self.field = field
        self.values = values
        self.boost = boost

    def execute(self, ctx, seg):
        if self.field == "_id":
            return IdsQuery(list(self.values), self.boost).execute(ctx, seg)
        self.field = ctx.concrete_field(self.field)
        ft = ctx.field_type(self.field)
        if ft is None and self.field in seg.keyword_fields and self.values:
            scores, matched, _ = _keyword_terms_result(
                ctx, seg, self.field,
                {str(v): 1.0 for v in self.values}, scored=False)
            return scores * np.float32(self.boost), matched > 0
        if ft is None or not self.values:
            return _const_result(seg, 0.0, False)
        if isinstance(ft, (NumberFieldType, DateFieldType, BooleanFieldType)):
            mask = jnp.zeros(seg.n_pad, jnp.bool_)
            for v in self.values:
                val = parse_date_millis(v, ft.format) \
                    if isinstance(ft, DateFieldType) else ft.parse_value(v)
                _, m = _numeric_range_result(seg, self.field, val, val, 1.0)
                mask = mask | m
            return jnp.where(mask, np.float32(self.boost), 0.0), mask
        if isinstance(ft, ConstantKeywordFieldType):
            hit = ft.value is not None and \
                any(str(v) == ft.value for v in self.values)
            return _const_result(seg, self.boost if hit else 0.0, hit)
        if isinstance(ft, KeywordFieldType):
            weights = {}
            for v in self.values:
                pv = ft.parse_value(v)
                if pv is not None:
                    weights[pv] = 1.0
            _, matched, _ = _keyword_terms_result(ctx, seg, self.field,
                                                  weights, scored=False)
        else:
            weights = {str(v): 1.0 for v in self.values}
            _, matched, _ = _score_text_terms(ctx, seg, self.field, weights)
        mask = matched > 0
        return jnp.where(mask, np.float32(self.boost), 0.0), mask


def _exact_numeric_mask(seg: Segment, field: str, lo, hi, boost):
    """Host-side EXACT f64 inclusive range mask over a numeric field's
    pairs — for ip fields, whose query bounds are pre-adjusted to inclusive
    exact integers (CIDR boundaries near 2^32); general numeric ranges run
    in device rank space (``_numeric_range_result``)."""
    nf = seg.numeric_fields.get(field)
    if nf is None:
        return _const_result(seg, 0.0, False)
    lo_v = -1.8e308 if lo is None else float(lo)
    hi_v = 1.8e308 if hi is None else float(hi)
    sel = (nf.vals_host >= lo_v) & (nf.vals_host <= hi_v)
    m = np.zeros(seg.n_pad, bool)
    m[nf.docs_host[sel]] = True
    mask = jnp.asarray(m)
    return jnp.where(mask, np.float32(boost), 0.0), mask


def _range_field_result(seg: Segment, field: str, lo, hi, relation: str,
                        boost: float):
    """Relation mask for a RANGE field's stored intervals
    (``RangeFieldMapper`` queries): the query interval [lo, hi] vs EVERY
    stored [gte, lte] pair of a doc — a doc matches if ANY of its
    intervals satisfies the relation (the pairs append in lockstep at
    parse time, so the two columns align positionally)."""
    g = seg.numeric_fields.get(f"{field}._gte")
    l = seg.numeric_fields.get(f"{field}._lte")
    if g is None or l is None or g.vals_host.size == 0:
        return _const_result(seg, 0.0, False)
    glo, ghi = g.vals_host, l.vals_host
    lo_v = -1.8e308 if lo is None else float(lo)
    hi_v = 1.8e308 if hi is None else float(hi)
    if relation == "within":            # doc interval inside the query's
        sel = (glo >= lo_v) & (ghi <= hi_v)
    elif relation == "contains":        # doc interval covers the query's
        sel = (glo <= lo_v) & (ghi >= hi_v)
    else:                               # intersects
        sel = (glo <= hi_v) & (ghi >= lo_v)
    m = np.zeros(seg.n_pad, bool)
    m[g.docs_host[sel]] = True
    mask = jnp.asarray(m)
    return jnp.where(mask, np.float32(boost), 0.0), mask


def _numeric_range_result(seg: Segment, field: str, lo, hi, boost,
                          include_lo=True, include_hi=True):
    """Range mask over a numeric field's (value, doc) pairs. Bounds arrive
    in value space (float64) and are binary-searched into the segment's
    sorted-distinct-value RANK space on the host; the device compares int32
    ranks — exact for gt/gte/lt/lte at any magnitude/span (no f32
    offset rounding; see ``NumericFieldData``)."""
    nf = seg.numeric_fields.get(field)
    if nf is None or nf.uniq_vals is None or nf.uniq_vals.size == 0:
        return _const_result(seg, 0.0, False)
    uniq = nf.uniq_vals
    # NaN values sort to the tail of uniq and must never match a range
    n_comparable = int(uniq.shape[0] - np.isnan(uniq).sum())
    if n_comparable == 0:
        return _const_result(seg, 0.0, False)
    if lo is None:
        lo_rank = 0
    else:
        lo_rank = int(np.searchsorted(uniq, float(lo),
                                      "left" if include_lo else "right"))
    if hi is None:
        hi_rank = n_comparable - 1
    else:
        hi_rank = min(int(np.searchsorted(uniq, float(hi),
                                          "right" if include_hi else "left"))
                      - 1, n_comparable - 1)
    if lo_rank > hi_rank:
        return _const_result(seg, 0.0, False)
    kernel = get_range_mask_kernel(seg.n_pad)
    mask = kernel(nf.ranks_dev, nf.docs_dev,
                  np.int32(lo_rank), np.int32(hi_rank))
    scores = jnp.where(mask, np.float32(boost), 0.0)
    return scores, mask


class RangeQuery(Query):
    """Range (reference: ``RangeQueryBuilder.java``). Constant-score."""

    def __init__(self, field: str, gte=None, gt=None, lte=None, lt=None,
                 boost: float = 1.0, date_format: Optional[str] = None,
                 relation: str = "intersects"):
        self.field = field
        self.gte, self.gt, self.lte, self.lt = gte, gt, lte, lt
        self.boost = boost
        self.date_format = date_format
        self.relation = relation
        if relation not in ("intersects", "contains", "within"):
            raise ParsingError(
                f"[range] unknown relation [{relation}]")

    def execute(self, ctx, seg):
        self.field = ctx.concrete_field(self.field)
        ft = ctx.field_type(self.field)
        if ft is None:
            return _const_result(seg, 0.0, False)
        if isinstance(ft, RuntimeFieldType):
            col = ft.column(seg)
            lo = float(self.gte if self.gte is not None else self.gt) \
                if (self.gte is not None or self.gt is not None) \
                else float("-inf")
            hi = float(self.lte if self.lte is not None else self.lt) \
                if (self.lte is not None or self.lt is not None) \
                else float("inf")
            with np.errstate(invalid="ignore"):
                m = ~np.isnan(col)
                m &= (col > lo) if self.gt is not None else (col >= lo)
                m &= (col < hi) if self.lt is not None else (col <= hi)
            mask = jnp.asarray(m)
            return jnp.where(mask, np.float32(self.boost), 0.0), mask
        if isinstance(ft, IpFieldType):
            lo = hi = None
            for v, inclusive in ((self.gte, True), (self.gt, False)):
                if v is not None:
                    cidr = IpFieldType.cidr_bounds(v)
                    if cidr is not None:
                        # gte block → from its start; gt block → past its
                        # END (the whole block is excluded)
                        lo = cidr[0] if inclusive else cidr[1] + 1
                    else:
                        lo = ft.parse_value(v)[1]
                        if not inclusive:
                            lo += 1
            for v, inclusive in ((self.lte, True), (self.lt, False)):
                if v is not None:
                    cidr = IpFieldType.cidr_bounds(v)
                    if cidr is not None:
                        # lte block → to its end; lt block → below its START
                        hi = cidr[1] if inclusive else cidr[0] - 1
                    else:
                        hi = ft.parse_value(v)[1]
                        if not inclusive:
                            hi -= 1
            return _exact_numeric_mask(seg, self.field, lo, hi, self.boost)
        if isinstance(ft, RangeFieldType):
            # gt/lte date bounds round UP through /unit date math
            lo = ft._point(self.gte if self.gte is not None else self.gt,
                           round_up=self.gte is None) \
                if (self.gte is not None or self.gt is not None) else None
            hi = ft._point(self.lte if self.lte is not None else self.lt,
                           round_up=self.lte is not None) \
                if (self.lte is not None or self.lt is not None) else None
            integral = ft.range_kind in ("integer_range", "long_range",
                                         "date_range", "ip_range")
            if self.gt is not None and lo is not None:
                lo = lo + 1 if integral else float(np.nextafter(lo, np.inf))
            if self.lt is not None and hi is not None:
                hi = hi - 1 if integral else float(np.nextafter(hi, -np.inf))
            return _range_field_result(seg, self.field, lo, hi,
                                       self.relation, self.boost)
        from ..index.mapping import AggregateMetricDoubleFieldType, \
            RankFeatureFieldType
        if isinstance(ft, (NumberFieldType, BooleanFieldType,
                           AggregateMetricDoubleFieldType,
                           RankFeatureFieldType)):
            # aggregate_metric_double's bare column carries its
            # default_metric; rank_feature is an ordinary positive float
            lo = self.gte if self.gte is not None else self.gt
            hi = self.lte if self.lte is not None else self.lt
            lo_v = float(lo) if lo is not None else None
            hi_v = float(hi) if hi is not None else None
            return _numeric_range_result(
                seg, self.field, lo_v, hi_v, self.boost,
                include_lo=self.gt is None, include_hi=self.lt is None)
        if isinstance(ft, DateFieldType):
            fmt = self.date_format or ft.format
            cached = getattr(self, "_date_bounds", {}).get(fmt) \
                if hasattr(self, "_date_bounds") else None
            if cached is not None:
                return _numeric_range_result(
                    seg, self.field, cached[0], cached[1], self.boost,
                    include_lo=self.gt is None, include_hi=self.lt is None)
            lo = self.gte if self.gte is not None else self.gt
            hi = self.lte if self.lte is not None else self.lt

            def _bound(v, round_up=False):
                # numeric bounds coerce through the format list (a bare
                # 4-digit number reads as a year, DateMathParser-style)
                if isinstance(v, (int, float)) and not isinstance(
                        v, bool) and 1000 <= v <= 9999 and \
                        float(v).is_integer():
                    v = str(int(v))
                return parse_date_millis(
                    v, fmt, round_up=round_up,
                    locale=getattr(ft, "locale", "en"))
            lo_v = _bound(lo, round_up=self.gte is None) \
                if lo is not None else None
            hi_v = _bound(hi, round_up=self.lte is not None) \
                if hi is not None else None
            # snapshot so 'now' resolves ONCE per request, not per
            # segment (keyed by format — indexes may map it differently)
            if not hasattr(self, "_date_bounds"):
                self._date_bounds = {}
            self._date_bounds[fmt] = (lo_v, hi_v)
            return _numeric_range_result(
                seg, self.field, lo_v, hi_v, self.boost,
                include_lo=self.gt is None, include_hi=self.lt is None)
        if isinstance(ft, KeywordFieldType):
            return self._keyword_range(seg)
        raise IllegalArgumentError(
            f"range query not supported on field [{self.field}] of type "
            f"[{ft.type_name}]")

    def _keyword_range(self, seg):
        f = seg.keyword_fields.get(self.field)
        if f is None:
            return _const_result(seg, 0.0, False)
        import bisect
        terms = f.ord_terms
        lo_ord = 0
        hi_ord = len(terms) - 1
        if self.gte is not None:
            lo_ord = bisect.bisect_left(terms, str(self.gte))
        elif self.gt is not None:
            lo_ord = bisect.bisect_right(terms, str(self.gt))
        if self.lte is not None:
            hi_ord = bisect.bisect_right(terms, str(self.lte)) - 1
        elif self.lt is not None:
            hi_ord = bisect.bisect_left(terms, str(self.lt)) - 1
        if lo_ord > hi_ord:
            return _const_result(seg, 0.0, False)
        kernel = get_range_mask_kernel(seg.n_pad)
        mask = kernel(f.dv_ords_dev.astype(jnp.float32), f.dv_docs_dev,
                      np.float32(lo_ord), np.float32(hi_ord))
        return jnp.where(mask, np.float32(self.boost), 0.0), mask


class ExistsQuery(Query):
    def __init__(self, field: str, boost: float = 1.0):
        self.field = field
        self.boost = boost

    #: metadata fields every live doc carries (FieldNamesFieldMapper
    #: exempts them from _field_names; exists matches all docs)
    ALWAYS_PRESENT = {"_id", "_index", "_type", "_seq_no", "_version",
                      "_primary_term", "_doc_count"}

    def execute(self, ctx, seg):
        if self.field == "_source":
            from ..common.errors import QueryShardError
            raise QueryShardError(
                "the [_source] field may not be queried directly")
        if self.field in self.ALWAYS_PRESENT:
            return _const_result(seg, self.boost, True)
        field = ctx.concrete_field(self.field)
        if isinstance(ctx.field_type(field), ConstantKeywordFieldType):
            ck = ctx.field_type(field)
            return _const_result(seg, self.boost, ck.value is not None)
        # object field: exists iff any mapped subfield exists
        sub_fields = [n for n in getattr(ctx.mapper, "_fields", {})
                      if n.startswith(field + ".")]
        from ..index.mapping import ObjectFieldType as _Obj
        ft_self = ctx.field_type(field)
        if isinstance(ft_self, _Obj) and sub_fields:
            sub = [ExistsQuery(sf) for sf in sub_fields]
            return BoolQuery(should=sub, boost=self.boost).execute(ctx, seg)
        # geo_point: presence via the paired coordinate columns
        if seg.numeric_fields.get(f"{field}._lat") is not None:
            exists = np.zeros(seg.n_pad, bool)
            exists[seg.numeric_fields[f"{field}._lat"].docs_host] = True
            mask = jnp.asarray(exists)
            return jnp.where(mask, np.float32(self.boost), 0.0), mask
        exists = np.zeros(seg.n_pad, bool)
        tf_ = seg.text_fields.get(field)
        if tf_ is not None:
            exists[: seg.n_docs] |= tf_.doc_len_host > 0
        kf = seg.keyword_fields.get(field)
        if kf is not None:
            exists[kf.dv_docs_host] = True
        nf = seg.numeric_fields.get(field)
        if nf is not None:
            exists[nf.docs_host] = True
        vf = seg.vector_fields.get(field)
        if vf is not None:
            exists[: seg.n_docs] |= vf.exists
        fn = seg.keyword_fields.get("_field_names")
        if fn is not None:               # source-only types (binary)
            st, ln, _ = fn.term_run(field)
            exists[fn.docs_host[st: st + ln]] = True
        mask = jnp.asarray(exists)
        return jnp.where(mask, np.float32(self.boost), 0.0), mask


class IdsQuery(Query):
    def __init__(self, values: List[str], boost: float = 1.0):
        self.values = [str(v) for v in values]
        self.boost = boost

    def execute(self, ctx, seg):
        mask = np.zeros(seg.n_pad, bool)
        for uid in self.values:
            d = seg.find_doc(uid)
            if d is not None:
                mask[d] = True
        m = jnp.asarray(mask)
        return jnp.where(m, np.float32(self.boost), 0.0), m


class PrefixQuery(Query):
    """Prefix (reference: ``PrefixQueryBuilder.java``). Terms are sorted at
    segment build, so a prefix is a contiguous term-id range → its postings
    are one contiguous flat slice; a single-run mask kernel covers it."""

    def __init__(self, field: str, value: str, boost: float = 1.0):
        self.field = field
        self.value = str(value)
        self.boost = boost

    def execute(self, ctx, seg):
        self.field = ctx.concrete_field(self.field)
        import bisect
        ft = ctx.field_type(self.field)
        value = self.value
        f = seg.text_fields.get(self.field)
        if f is not None:
            # term_ids insertion order is sorted term order (segment build)
            terms_sorted = list(f.term_ids)
            offsets = f.offsets
            docs_dev = f.docs_dev
        else:
            kf = seg.keyword_fields.get(self.field)
            if kf is None:
                return _const_result(seg, 0.0, False)
            if isinstance(ft, KeywordFieldType):
                value = ft.parse_value(value) or value
            terms_sorted = kf.ord_terms
            offsets = kf.offsets
            docs_dev = kf.docs_dev
        lo = bisect.bisect_left(terms_sorted, value)
        hi = bisect.bisect_left(terms_sorted,
                                value[:-1] + chr(ord(value[-1]) + 1)
                                if value else chr(0x10FFFF))
        if lo >= hi:
            return _const_result(seg, 0.0, False)
        start = int(offsets[lo])
        length = int(offsets[hi] - offsets[lo])
        L = round_up_pow2(length)
        kernel = get_postings_match_kernel(seg.n_pad, L)
        matched = kernel(docs_dev, np.asarray([start], np.int32),
                         np.asarray([length], np.int32))
        mask = matched > 0
        return jnp.where(mask, np.float32(self.boost), 0.0), mask

    def collect_highlight_terms(self, ctx, out):
        # expand the prefix over the shard's term dictionaries so the
        # highlighter can mark the concrete matching terms
        dest = out.setdefault(self.field, set())
        for seg in ctx.segments:
            f = seg.text_fields.get(self.field)
            terms = list(f.term_ids) if f is not None else None
            if terms is None:
                kf = seg.keyword_fields.get(self.field)
                terms = kf.ord_terms if kf is not None else []
            for t in terms:
                if t.startswith(self.value):
                    dest.add(t)


def wildcard_regex(pattern: str, flags: int = 0) -> "re.Pattern":
    """``*``/``?`` wildcard → anchored regex (shared by wildcard query,
    interval wildcard source and span_multi)."""
    esc = re.escape(pattern).replace(r"\*", ".*").replace(r"\?", ".")
    return re.compile(f"{esc}\\Z", flags)


class WildcardQuery(Query):
    """Wildcard/regexp: host-side term-dictionary scan → postings union mask
    (uploads a host-computed doc mask; term dictionaries are host-resident)."""

    def __init__(self, field: str, pattern: str, boost: float = 1.0,
                 is_regexp: bool = False, case_insensitive: bool = False):
        self.field = field
        self.pattern = pattern
        self.boost = boost
        flags = re.IGNORECASE if case_insensitive else 0
        if is_regexp:
            # Lucene regexp is anchored at both ends
            self._re = re.compile(f"(?:{pattern})\\Z", flags)
        else:
            self._re = wildcard_regex(pattern, flags)

    def execute(self, ctx, seg):
        self.field = ctx.concrete_field(self.field)
        mask = np.zeros(seg.n_pad, bool)
        f = seg.text_fields.get(self.field)
        if f is not None:
            for term, tid in f.term_ids.items():
                if self._re.match(term):
                    s, e = int(f.offsets[tid]), int(f.offsets[tid + 1])
                    mask[f.docs_host[s:e]] = True
        kf = seg.keyword_fields.get(self.field)
        if kf is not None:
            for term, o in kf.term_ords.items():
                if self._re.match(term):
                    s, e = int(kf.offsets[o]), int(kf.offsets[o + 1])
                    mask[kf.docs_host[s:e]] = True
        m = jnp.asarray(mask)
        return jnp.where(m, np.float32(self.boost), 0.0), m


class FuzzyQuery(Query):
    """Fuzzy term matching by Damerau–Levenshtein distance over the term
    dictionary (host side), constant-score union like wildcard.
    Reference: ``FuzzyQueryBuilder.java`` (AUTO fuzziness)."""

    def __init__(self, field: str, value: str, fuzziness="AUTO",
                 prefix_length: int = 0, boost: float = 1.0):
        self.field = field
        self.value = str(value)
        self.boost = boost
        self.prefix_length = int(prefix_length)
        if fuzziness in ("AUTO", "auto", None):
            n = len(self.value)
            self.max_edits = 0 if n <= 2 else (1 if n <= 5 else 2)
        else:
            self.max_edits = int(fuzziness)

    def _matches(self, term: str) -> bool:
        if self.prefix_length and \
                term[: self.prefix_length] != self.value[: self.prefix_length]:
            return False
        return _edit_distance_le(term, self.value, self.max_edits)

    def execute(self, ctx, seg):
        self.field = ctx.concrete_field(self.field)
        mask = np.zeros(seg.n_pad, bool)
        f = seg.text_fields.get(self.field)
        if f is not None:
            for term, tid in f.term_ids.items():
                if self._matches(term):
                    s, e = int(f.offsets[tid]), int(f.offsets[tid + 1])
                    mask[f.docs_host[s:e]] = True
        kf = seg.keyword_fields.get(self.field)
        if kf is not None:
            for term, o in kf.term_ords.items():
                if self._matches(term):
                    s, e = int(kf.offsets[o]), int(kf.offsets[o + 1])
                    mask[kf.docs_host[s:e]] = True
        m = jnp.asarray(mask)
        return jnp.where(m, np.float32(self.boost), 0.0), m


def _edit_distance_le(a: str, b: str, k: int) -> bool:
    """Damerau–Levenshtein distance <= k (early-exit banded DP)."""
    if k == 0:
        return a == b
    if abs(len(a) - len(b)) > k:
        return False
    prev2: list = []
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        for j, cb in enumerate(b, 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (ca != cb))
            if i > 1 and j > 1 and ca == b[j - 2] and a[i - 2] == cb:
                cur[j] = min(cur[j], prev2[j - 2] + 1)
        prev2, prev = prev, cur
        if min(prev) > k:
            return False
    return prev[-1] <= k


class BoolQuery(Query):
    """Boolean composition (reference: ``BoolQueryBuilder.java``): must and
    should contribute scores; filter and must_not only constrain the mask."""

    def __init__(self, must=None, filter=None, should=None, must_not=None,
                 minimum_should_match=None, boost: float = 1.0):
        self.must: List[Query] = must or []
        self.filter: List[Query] = filter or []
        self.should: List[Query] = should or []
        self.must_not: List[Query] = must_not or []
        self.msm = minimum_should_match
        self.boost = boost

    def execute(self, ctx, seg):
        n = seg.n_pad
        scores = jnp.zeros(n, jnp.float32)
        mask = None
        for q in self.must:
            s, m = q.execute(ctx, seg)
            scores = scores + s
            mask = m if mask is None else (mask & m)
        for q in self.filter:
            _, m = q.execute(ctx, seg)
            mask = m if mask is None else (mask & m)
        should_count = None
        if self.should:
            should_count = jnp.zeros(n, jnp.int32)
            for q in self.should:
                s, m = q.execute(ctx, seg)
                scores = scores + jnp.where(m, s, 0.0)
                should_count = should_count + m.astype(jnp.int32)
        if self.msm is not None:
            required = resolve_minimum_should_match(self.msm, len(self.should))
        else:
            required = 0
        if not self.must and not self.filter:
            # no required clauses → at least one should must match, even with
            # an explicit minimum_should_match of 0 (Lucene Boolean2Scorer)
            required = max(required, 1)
        if should_count is not None and required > 0:
            sm = should_count >= required
            mask = sm if mask is None else (mask & sm)
        elif mask is None:
            # only must_not (or empty): start from all docs
            mask = jnp.ones(n, jnp.bool_)
        for q in self.must_not:
            _, m = q.execute(ctx, seg)
            mask = mask & ~m
        scores = jnp.where(mask, scores, 0.0) * np.float32(self.boost)
        return scores, mask

    def collect_highlight_terms(self, ctx, out):
        for q in self.must + self.filter + self.should:
            q.collect_highlight_terms(ctx, out)


class ConstantScoreQuery(Query):
    def __init__(self, inner: Query, boost: float = 1.0):
        self.inner = inner
        self.boost = boost

    def execute(self, ctx, seg):
        _, mask = self.inner.execute(ctx, seg)
        return jnp.where(mask, np.float32(self.boost), 0.0), mask

    def collect_highlight_terms(self, ctx, out):
        self.inner.collect_highlight_terms(ctx, out)


class DisMaxQuery(Query):
    def __init__(self, queries: List[Query], tie_breaker: float = 0.0,
                 boost: float = 1.0):
        self.queries = queries
        self.tie_breaker = float(tie_breaker)
        self.boost = boost

    def execute(self, ctx, seg):
        n = seg.n_pad
        best = jnp.zeros(n, jnp.float32)
        total = jnp.zeros(n, jnp.float32)
        mask = jnp.zeros(n, jnp.bool_)
        for q in self.queries:
            s, m = q.execute(ctx, seg)
            s = jnp.where(m, s, 0.0)
            best = jnp.maximum(best, s)
            total = total + s
            mask = mask | m
        scores = best + self.tie_breaker * (total - best)
        return scores * np.float32(self.boost), mask

    def collect_highlight_terms(self, ctx, out):
        for q in self.queries:
            q.collect_highlight_terms(ctx, out)


class BoostingQuery(Query):
    def __init__(self, positive: Query, negative: Query,
                 negative_boost: float, boost: float = 1.0):
        self.positive = positive
        self.negative = negative
        self.negative_boost = float(negative_boost)
        self.boost = boost

    def execute(self, ctx, seg):
        s, m = self.positive.execute(ctx, seg)
        _, nm = self.negative.execute(ctx, seg)
        scores = jnp.where(nm, s * np.float32(self.negative_boost), s)
        return scores * np.float32(self.boost), m

    def collect_highlight_terms(self, ctx, out):
        self.positive.collect_highlight_terms(ctx, out)


class NestedQuery(Query):
    """Block-join nested query (reference: ``NestedQueryBuilder.java`` →
    Lucene ``ToParentBlockJoinQuery``): the inner query executes against
    the hidden child documents of ``path`` (see
    ``index/mapping.py NestedFieldType``) and matches join back to their
    parents with ``score_mode`` (avg default | sum | max | min | none)
    aggregating child scores per parent."""

    def __init__(self, path: str, inner: Query, boost: float = 1.0,
                 score_mode: str = "avg"):
        self.path = path
        self.inner = inner
        self.boost = boost
        if score_mode not in ("avg", "sum", "max", "min", "none"):
            raise ParsingError(
                f"[nested] illegal score_mode [{score_mode}]")
        self.score_mode = score_mode

    def execute(self, ctx, seg):
        path_mask = seg.nested_paths.get(self.path)
        if path_mask is None:
            # no children for this path in the segment (or legacy
            # flattened data): no parent can match
            return _const_result(seg, 0.0, False)
        s, m = self.inner.execute(ctx, seg)
        child_m = np.zeros(seg.n_pad, bool)
        child_m[: seg.n_docs] = path_mask & seg.live[: seg.n_docs]
        child_m &= np.asarray(m)
        child_docs = np.flatnonzero(child_m)
        n = seg.n_pad
        pscore = np.zeros(n, np.float32)
        pmask = np.zeros(n, bool)
        if child_docs.size:
            parents = seg.parent_of[child_docs]
            pmask[parents] = True
            cs = np.asarray(s)[child_docs].astype(np.float32)
            if self.score_mode == "sum":
                np.add.at(pscore, parents, cs)
            elif self.score_mode == "max":
                np.maximum.at(pscore, parents, cs)
            elif self.score_mode == "min":
                tmp = np.full(n, np.inf, np.float32)
                np.minimum.at(tmp, parents, cs)
                pscore = np.where(pmask, tmp, 0.0).astype(np.float32)
            elif self.score_mode == "none":
                pscore = pmask.astype(np.float32)
            else:                       # avg
                cnt = np.zeros(n, np.float32)
                np.add.at(pscore, parents, cs)
                np.add.at(cnt, parents, 1.0)
                pscore = np.where(cnt > 0, pscore / np.maximum(cnt, 1), 0.0)
        return (jnp.asarray(pscore * np.float32(self.boost)),
                jnp.asarray(pmask))

    def collect_highlight_terms(self, ctx, out):
        self.inner.collect_highlight_terms(ctx, out)


_VECTOR_FN_RE = re.compile(
    r"(cosineSimilarity|dotProduct|l1norm|l2norm)\s*\(\s*"
    r"params\.(\w+)\s*,\s*['\"]([\w.]+)['\"]\s*\)")


def _vector_similarity(kind: str, qv: np.ndarray, seg: Segment,
                       field: str):
    """Whole-segment vector similarity — one einsum/VPU pass (replaces the
    reference's per-doc script loop,
    ``x-pack/plugin/vectors/.../query/ScoreScriptUtils.java:112-136``)."""
    f = seg.vector_fields.get(field)
    if f is None:
        return jnp.zeros(seg.n_pad, jnp.float32), jnp.zeros(seg.n_pad, bool)
    mat = f.matrix_dev                                  # [n_pad, D]
    q = jnp.asarray(qv, jnp.float32)
    exists = np.zeros(seg.n_pad, bool)
    exists[: f.exists.shape[0]] = f.exists
    exists_dev = jnp.asarray(exists)
    if kind == "cosineSimilarity":
        # corpus rows are a segment invariant: normalized once when the
        # column is first used (VectorFieldData.unit_matrix_dev), only the
        # query side is normalized per call
        qn = q / jnp.maximum(jnp.linalg.norm(q), 1e-12)
        sim = f.unit_matrix_dev() @ qn
    elif kind == "dotProduct":
        sim = mat @ q
    elif kind == "l1norm":
        sim = jnp.sum(jnp.abs(mat - q[None, :]), axis=-1)
    else:  # l2norm
        # direct subtraction, NOT the expanded ‖v‖²-2v·q+‖q‖² form: the
        # expansion loses the distance to f32 cancellation for
        # near-duplicate vectors, and script distances are user-facing
        sim = jnp.linalg.norm(mat - q[None, :], axis=-1)
    return jnp.where(exists_dev, sim, 0.0), exists_dev


class ScriptScoreQuery(Query):
    """Re-scores an inner query's matches with a sandboxed expression
    (reference: ``index/query/functionscore/ScriptScoreQueryBuilder`` +
    the vectors script utilities). Vector calls like
    ``cosineSimilarity(params.qv, 'embedding')`` compile to whole-segment
    einsums; ``doc['f'].value`` reads doc-values columns; the remaining
    arithmetic traces to one fused XLA program per segment."""

    def __init__(self, inner: Query, source: str, params: dict,
                 min_score: Optional[float] = None, boost: float = 1.0):
        self.inner = inner
        self.params = params or {}
        self.min_score = min_score
        self.boost = boost
        # rewrite vector calls + doc access into plain variables
        self._vector_refs = []   # (var, kind, param_name, field)
        src = source

        def repl(m):
            var = f"__vec{len(self._vector_refs)}"
            self._vector_refs.append((var, m.group(1), m.group(2), m.group(3)))
            return var

        src = _VECTOR_FN_RE.sub(repl, src)
        self._doc_refs = []      # (var, field)
        doc_re = re.compile(r"doc\[['\"]([\w.]+)['\"]\]\.value")

        def drepl(m):
            var = f"__doc{len(self._doc_refs)}"
            self._doc_refs.append((var, m.group(1)))
            return var

        self.source = doc_re.sub(drepl, src)

    def _doc_column(self, seg: Segment, field: str):
        """Dense [n_pad] f32 column of the field's first value per doc
        (0 where absent, f32 for device math)."""
        col = seg.numeric_first_value_column(field)
        return jnp.asarray(np.nan_to_num(col, nan=0.0).astype(np.float32))

    def execute(self, ctx, seg):
        from ..utils.expressions import evaluate_expression_vec
        inner_scores, mask = self.inner.execute(ctx, seg)
        env: dict = {"_score": inner_scores}
        for name, v in self.params.items():
            if not isinstance(v, (list, np.ndarray)):
                env[name] = float(v)
        for var, kind, pname, field in self._vector_refs:
            qv = np.asarray(self.params.get(pname), np.float32)
            sim, _ = _vector_similarity(kind, qv, seg, field)
            env[var] = sim
        for var, field in self._doc_refs:
            env[var] = self._doc_column(seg, field)
        scores = evaluate_expression_vec(self.source, env)
        scores = jnp.broadcast_to(jnp.asarray(scores, jnp.float32),
                                  (seg.n_pad,)) * np.float32(self.boost)
        if self.min_score is not None:
            mask = mask & (scores >= np.float32(self.min_score))
        return scores, mask

    def collect_highlight_terms(self, ctx, out):
        self.inner.collect_highlight_terms(ctx, out)


class FunctionScoreQuery(Query):
    """Subset of the reference's function_score
    (``index/query/functionscore/FunctionScoreQueryBuilder``): script_score,
    weight, and field_value_factor functions with multiply/sum/replace
    score modes and boost modes."""

    def __init__(self, inner: Query, functions: List[dict],
                 score_mode: str = "multiply", boost_mode: str = "multiply",
                 boost: float = 1.0):
        self.inner = inner
        self.functions = functions
        self.score_mode = score_mode
        self.boost_mode = boost_mode
        self.boost = boost

    def _fn_scores(self, ctx, seg, spec, base_scores):
        if "script_score" in spec:
            script = spec["script_score"].get("script", {})
            src = script.get("source") if isinstance(script, dict) else script
            q = ScriptScoreQuery(MatchAllQuery(), src,
                                 (script.get("params", {})
                                  if isinstance(script, dict) else {}))
            s, _ = q.execute(ctx, seg)
            return s
        if "field_value_factor" in spec:
            fv = spec["field_value_factor"]
            col = jnp.asarray(np.nan_to_num(
                seg.numeric_first_value_column(fv["field"]),
                nan=0.0).astype(np.float32))
            factor = np.float32(fv.get("factor", 1.0))
            col = col * factor
            modifier = fv.get("modifier", "none")
            if modifier == "log1p":
                col = jnp.log1p(jnp.maximum(col, 0.0))
            elif modifier == "sqrt":
                col = jnp.sqrt(jnp.maximum(col, 0.0))
            elif modifier == "square":
                col = col * col
            elif modifier == "reciprocal":
                col = 1.0 / jnp.maximum(col, 1e-9)
            return col
        if "weight" in spec:
            return jnp.full(seg.n_pad, np.float32(spec["weight"]))
        raise ParsingError("unsupported function_score function")

    def execute(self, ctx, seg):
        base, mask = self.inner.execute(ctx, seg)
        parts = []   # (scores, applies_mask)
        for spec in self.functions:
            filt = spec.get("filter")
            s = self._fn_scores(ctx, seg, spec, base)
            if "weight" in spec and "script_score" not in spec and \
                    "field_value_factor" not in spec:
                pass  # pure weight function, s already the weight
            elif "weight" in spec:
                s = s * np.float32(spec["weight"])
            if filt is not None:
                _, fmask = parse_query(filt).execute(ctx, seg)
            else:
                fmask = jnp.ones(seg.n_pad, jnp.bool_)
            parts.append((s, fmask))
        if not parts:
            fn_score = jnp.ones(seg.n_pad, jnp.float32)
        else:
            # a function whose filter doesn't match a doc is EXCLUDED for
            # that doc (reference: FunctionScoreQuery per-doc function
            # subset), not folded in with a 0/1 neutral fill
            n_match = sum(fm.astype(jnp.int32) for _, fm in parts)
            if self.score_mode == "sum":
                fn_score = sum(jnp.where(fm, s, 0.0) for s, fm in parts)
            elif self.score_mode == "avg":
                tot = sum(jnp.where(fm, s, 0.0) for s, fm in parts)
                fn_score = tot / jnp.maximum(n_match, 1)
            elif self.score_mode == "max":
                fn_score = parts[0][0]
                fn_score = jnp.where(parts[0][1], fn_score, -jnp.inf)
                for s, fm in parts[1:]:
                    fn_score = jnp.maximum(fn_score, jnp.where(fm, s, -jnp.inf))
            elif self.score_mode == "min":
                fn_score = jnp.where(parts[0][1], parts[0][0], jnp.inf)
                for s, fm in parts[1:]:
                    fn_score = jnp.minimum(fn_score, jnp.where(fm, s, jnp.inf))
            elif self.score_mode == "first":
                fn_score = jnp.full(seg.n_pad, 1.0, jnp.float32)
                assigned = jnp.zeros(seg.n_pad, jnp.bool_)
                for s, fm in parts:
                    take = fm & ~assigned
                    fn_score = jnp.where(take, s, fn_score)
                    assigned = assigned | fm
            else:  # multiply
                fn_score = jnp.ones(seg.n_pad, jnp.float32)
                for s, fm in parts:
                    fn_score = fn_score * jnp.where(fm, s, 1.0)
            # docs matched by no function: neutral score 1
            fn_score = jnp.where(n_match > 0, fn_score, 1.0)
        if self.boost_mode == "replace":
            out = fn_score
        elif self.boost_mode == "sum":
            out = base + fn_score
        elif self.boost_mode == "avg":
            out = (base + fn_score) / 2.0
        elif self.boost_mode == "max":
            out = jnp.maximum(base, fn_score)
        elif self.boost_mode == "min":
            out = jnp.minimum(base, fn_score)
        else:  # multiply
            out = base * fn_score
        return out * np.float32(self.boost), mask

    def collect_highlight_terms(self, ctx, out):
        self.inner.collect_highlight_terms(ctx, out)


# ---------------------------------------------------------------------------
# Parsing (reference: each QueryBuilder's fromXContent)
# ---------------------------------------------------------------------------


def parse_query(spec: dict) -> Query:
    if not isinstance(spec, dict) or len(spec) != 1:
        raise ParsingError(
            "query malformed, expected a single top-level query clause")
    (qtype, body), = spec.items()
    parser = _PARSERS.get(qtype)
    if parser is None:
        import difflib
        hint = difflib.get_close_matches(qtype, sorted(_PARSERS), n=1)
        suffix = f" did you mean [{hint[0]}]?" if hint else ""
        raise ParsingError(f"unknown query [{qtype}]{suffix}")
    return parser(body)


def _field_body(body: dict, value_key: str):
    """Handle the `{field: {value_key: v, ...opts}}` and `{field: v}` forms."""
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingError("expected a single field name")
    (field, spec), = body.items()
    if isinstance(spec, dict):
        opts = dict(spec)
        value = opts.pop(value_key, None)
        if value is None and value_key == "value":
            value = opts.pop("query", None)
        return field, value, opts
    return field, spec, {}


def _parse_match(body):
    field, value, opts = _field_body(body, "query")
    return MatchQuery(field, value, opts.get("operator", "or"),
                      opts.get("minimum_should_match"),
                      float(opts.get("boost", 1.0)), opts.get("analyzer"))


def _parse_match_phrase(body):
    field, value, opts = _field_body(body, "query")
    return MatchPhraseQuery(field, value, int(opts.get("slop", 0)),
                            float(opts.get("boost", 1.0)))


def _parse_term(body):
    field, value, opts = _field_body(body, "value")
    return TermQuery(field, value, float(opts.get("boost", 1.0)),
                     case_insensitive=bool(opts.get("case_insensitive",
                                                    False)))


def _parse_terms(body):
    opts = dict(body)
    boost = float(opts.pop("boost", 1.0))
    if len(opts) != 1:
        raise ParsingError("[terms] query requires exactly one field")
    (field, values), = opts.items()
    if not isinstance(values, list):
        raise ParsingError("[terms] query requires an array of values")
    # count limits are enforced settings-aware at the request layer
    return TermsQuery(field, values, boost)


def _parse_range(body):
    if len(body) != 1:
        raise ParsingError("[range] query requires exactly one field")
    (field, spec), = body.items()
    opts = dict(spec)
    # legacy from/to support
    if "from" in opts:
        opts.setdefault("gte" if opts.pop("include_lower", True) else "gt",
                        opts.pop("from"))
    if "to" in opts:
        opts.setdefault("lte" if opts.pop("include_upper", True) else "lt",
                        opts.pop("to"))
    return RangeQuery(field, opts.get("gte"), opts.get("gt"), opts.get("lte"),
                      opts.get("lt"), float(opts.get("boost", 1.0)),
                      opts.get("format"),
                      relation=opts.get("relation", "intersects"))


def _parse_bool(body):
    def clause(name):
        c = body.get(name)
        if c is None:
            return []
        if isinstance(c, dict):
            c = [c]
        return [parse_query(q) for q in c]

    return BoolQuery(clause("must"), clause("filter"), clause("should"),
                     clause("must_not"), body.get("minimum_should_match"),
                     float(body.get("boost", 1.0)))


def _parse_dis_max(body):
    return DisMaxQuery([parse_query(q) for q in body.get("queries", [])],
                       float(body.get("tie_breaker", 0.0)),
                       float(body.get("boost", 1.0)))


def _parse_constant_score(body):
    return ConstantScoreQuery(parse_query(body["filter"]),
                              float(body.get("boost", 1.0)))


def _parse_exists(body):
    return ExistsQuery(body["field"], float(body.get("boost", 1.0)))


def _parse_ids(body):
    return IdsQuery(body.get("values", []), float(body.get("boost", 1.0)))


def _parse_prefix(body):
    field, value, opts = _field_body(body, "value")
    return PrefixQuery(field, value, float(opts.get("boost", 1.0)))


def _parse_wildcard(body):
    field, value, opts = _field_body(body, "value")
    if value is None:
        value = opts.pop("wildcard", None)
    return WildcardQuery(field, value, float(opts.get("boost", 1.0)),
                         case_insensitive=bool(
                             opts.get("case_insensitive", False)))


def _parse_regexp(body):
    # length limits are enforced settings-aware at the request layer
    # (RestAPI._validate_search walk), not here
    field, value, opts = _field_body(body, "value")
    return WildcardQuery(field, value, float(opts.get("boost", 1.0)),
                         is_regexp=True,
                         case_insensitive=bool(
                             opts.get("case_insensitive", False)))


def _parse_fuzzy(body):
    field, value, opts = _field_body(body, "value")
    return FuzzyQuery(field, value, opts.get("fuzziness", "AUTO"),
                      int(opts.get("prefix_length", 0)),
                      float(opts.get("boost", 1.0)))


def _parse_boosting(body):
    return BoostingQuery(parse_query(body["positive"]),
                         parse_query(body["negative"]),
                         float(body.get("negative_boost", 0.5)),
                         float(body.get("boost", 1.0)))


class _AllTextFieldsQuery(Query):
    """Match against every text field (the ``*`` / default-field case of
    query_string): dis_max over the segment's text fields, resolved at
    execute time."""

    def __init__(self, text: str, phrase: bool, boost: float = 1.0):
        self.text = text
        self.phrase = phrase
        self.boost = boost

    def execute(self, ctx, seg):
        fields = sorted(seg.text_fields)
        subs = [(MatchPhraseQuery(f, self.text) if self.phrase
                 else MatchQuery(f, self.text)) for f in fields]
        if not subs:
            return _const_result(seg, 0.0, False)
        return DisMaxQuery(subs, 0.0, self.boost).execute(ctx, seg)

    def collect_highlight_terms(self, ctx, out):
        for seg in ctx.segments:
            for f in seg.text_fields:
                MatchQuery(f, self.text).collect_highlight_terms(ctx, out)


class _AllFieldsRegexpQuery(Query):
    """Regex literal with no explicit field: dis_max of regexp over every
    text field, resolved per segment (the default-field case)."""

    def __init__(self, pattern: str, boost: float = 1.0):
        self.pattern = pattern
        self.boost = boost

    def execute(self, ctx, seg):
        subs = [WildcardQuery(f, self.pattern, is_regexp=True)
                for f in sorted(seg.text_fields)]
        if not subs:
            return _const_result(seg, 0.0, False)
        return DisMaxQuery(subs, 0.0, self.boost).execute(ctx, seg)


class _LenientQuery(Query):
    """Wraps a clause so data-conversion failures mean "no match" —
    query_string/simple_query_string lenient semantics."""

    def __init__(self, inner: Query):
        self.inner = inner

    def execute(self, ctx, seg):
        from ..common.errors import ElasticsearchError
        try:
            return self.inner.execute(ctx, seg)
        except ElasticsearchError:
            return _const_result(seg, 0.0, False)

    def collect_highlight_terms(self, ctx, out):
        self.inner.collect_highlight_terms(ctx, out)


class QueryStringQuery(Query):
    """Lucene query-string syntax, the commonly-used subset (reference:
    ``QueryStringQueryBuilder`` wrapping the full Lucene parser):
    ``field:term``, quoted phrases, AND/OR/NOT + default_operator, +/-
    prefixes, trailing-* wildcards, field boosts (``title^2``).
    ``simple_query_string`` shares the parser with lenient semantics
    (reference: ``SimpleQueryStringBuilder`` — its +|- operator spellings
    map onto the same tree)."""

    def __init__(self, query: str, fields: Optional[List[str]] = None,
                 default_operator: str = "or", boost: float = 1.0,
                 lenient: bool = False, data_lenient: Optional[bool] = None):
        self.boost = boost
        self.lenient = lenient          # syntax tolerance
        # data tolerance (conversion errors → no match); defaults to the
        # syntax flag for plain query_string, but simple_query_string is
        # always syntax-lenient WITHOUT being data-lenient
        self.data_lenient = lenient if data_lenient is None \
            else data_lenient
        self.inner = self._compile(str(query), fields or ["*"],
                                   default_operator.lower())

    @staticmethod
    def _tokenize(q: str) -> List[str]:
        out, cur, in_q, in_rng = [], "", False, False
        for ch in q:
            if ch == '"':
                cur += ch
                if in_q:
                    out.append(cur)
                    cur = ""
                in_q = not in_q
            elif ch in "[{" and not in_q and cur.endswith(":"):
                in_rng = True               # field:[a TO b] range syntax
                cur += ch
            elif ch in "]}" and in_rng:
                in_rng = False
                cur += ch
                out.append(cur)
                cur = ""
            elif ch.isspace() and not in_q and not in_rng:
                if cur:
                    out.append(cur)
                    cur = ""
            else:
                cur += ch
        if cur:
            out.append(cur)
        return out

    def _leaf(self, fields: List[str], text: str) -> "Query":
        field = None
        if ":" in text and not text.startswith('"') \
                and not text.startswith("/"):
            field, _, text = text.partition(":")
        phrase = text.startswith('"') and text.endswith('"') and \
            len(text) >= 2
        if phrase:
            text = text[1:-1]
        m_range = re.match(r"^([\[{])\s*(\S+)\s+TO\s+(\S+)\s*([\]}])$",
                           text)
        if m_range and field and not phrase:
            open_b, lo, hi, close_b = m_range.groups()
            kw = {}
            if lo != "*":
                kw["gte" if open_b == "[" else "gt"] = lo
            if hi != "*":
                kw["lte" if close_b == "]" else "lt"] = hi
            return RangeQuery(field, kw.get("gte"), kw.get("gt"),
                              kw.get("lte"), kw.get("lt"))
        regex = None
        if text.startswith("/") and text.endswith("/") and len(text) >= 2:
            regex = text[1:-1]
            if len(regex) > 1000:
                raise IllegalArgumentError(
                    f"The length of regex [{len(regex)}] used in the "
                    f"Regexp Query request has exceeded the allowed "
                    f"maximum of [1000]. This maximum can be set by "
                    f"changing the [index.max_regex_length] index level "
                    f"setting.")
        targets = [field] if field else fields
        subs: List[Query] = []
        for f in targets:
            boost = 1.0
            if "^" in f:
                head, _, b = f.partition("^")
                try:
                    boost = float(b)
                    f = head
                except ValueError:
                    pass             # a literal ^ in the term, not a boost
            if regex is not None:
                sub = (_AllFieldsRegexpQuery(regex, boost)
                       if f in ("*", "")
                       else WildcardQuery(f, regex, boost, is_regexp=True))
            elif f in ("*", ""):
                sub = _AllTextFieldsQuery(text, phrase, boost)
            elif phrase:
                sub = MatchPhraseQuery(f, text, 0, boost)
            elif text.endswith("*") and len(text) > 1:
                sub = WildcardQuery(f, text.lower(), boost)
            else:
                sub = MatchQuery(f, text, boost=boost)
            subs.append(sub)
        return subs[0] if len(subs) == 1 else DisMaxQuery(subs, 0.0)

    def _compile(self, q: str, fields: List[str], default_op: str) -> Query:
        qs = q.strip()
        if qs.startswith("/") and qs.endswith("/") and len(qs) >= 2:
            # a whole-query regex literal (spaces inside stay part of it)
            return self._leaf(fields, qs)
        tokens = self._tokenize(q)
        must, should, must_not = [], [], []
        pending_op = None
        last_bucket = None                    # where the previous leaf went
        for tok in tokens:
            up = tok.upper()
            if up in ("AND", "OR"):
                pending_op = up
                continue
            if up == "NOT":
                pending_op = "NOT"
                continue
            neg = tok.startswith("-") or pending_op == "NOT"
            req = tok.startswith("+")
            tok = tok.lstrip("+-") if not tok.startswith('"') else tok
            if not tok:
                pending_op = None
                continue
            try:
                leaf = self._leaf(fields, tok)
            except Exception:   # noqa: BLE001
                if self.lenient:
                    pending_op = None
                    continue            # simple_query_string never throws
                raise
            if self.data_lenient:
                leaf = _LenientQuery(leaf)
            if neg:
                must_not.append(leaf)
                last_bucket = must_not
            elif pending_op == "OR":
                # an explicit OR joins the PREVIOUS leaf too, even under
                # default_operator=and ("a OR b" matches either)
                if last_bucket is must and must:
                    should.append(must.pop())
                should.append(leaf)
                last_bucket = should
            elif req or pending_op == "AND" or (
                    pending_op is None and default_op == "and"):
                must.append(leaf)
                last_bucket = must
            else:
                should.append(leaf)
                last_bucket = should
            pending_op = None
        if default_op == "and" and should and not must and \
                len(should) == 1:
            must, should = should, []
        return BoolQuery(must=must, should=should, must_not=must_not,
                         filter=[],
                         minimum_should_match=(1 if should and not must
                                               else 0))

    def execute(self, ctx, seg):
        s, m = self.inner.execute(ctx, seg)
        return s * np.float32(self.boost), m

    def collect_highlight_terms(self, ctx, out):
        self.inner.collect_highlight_terms(ctx, out)


class MatchBoolPrefixQuery(Query):
    """match_bool_prefix (reference: ``MatchBoolPrefixQueryBuilder``):
    every analyzed term as a term clause, the LAST as a prefix. Analysis
    (and the optional custom analyzer / fuzziness) resolves at execute
    time against the target field."""

    def __init__(self, field: str, spec: dict, boost: float = 1.0):
        self.field = field
        self.spec = spec
        self.boost = boost

    def _build(self, ctx):
        spec = self.spec
        text = str(spec.get("query", ""))
        operator = str(spec.get("operator", "or")).lower()
        field = ctx.concrete_field(self.field)
        ft = ctx.field_type(field)
        an_name = spec.get("analyzer")
        if an_name and ctx.mapper is not None:
            analyzer = ctx.mapper.analysis.get(an_name)
            terms = analyzer.terms(text)
        elif isinstance(ft, TextFieldType):
            terms = ft.search_analyzer.terms(text)
        else:
            terms = text.split()
        fuzziness = spec.get("fuzziness")
        clauses: List[Query] = []
        for t in terms[:-1]:
            if fuzziness is not None:
                clauses.append(FuzzyQuery(field, t, fuzziness))
            else:
                clauses.append(TermQuery(field, t))
        if terms:
            clauses.append(PrefixQuery(field, terms[-1]))
        if not clauses:
            return MatchNoneQuery()
        msm = spec.get("minimum_should_match")
        if operator == "and":
            return BoolQuery(must=clauses, boost=self.boost)
        return BoolQuery(should=clauses,
                         minimum_should_match=msm if msm is not None
                         else 1, boost=self.boost)

    def execute(self, ctx, seg):
        return self._build(ctx).execute(ctx, seg)

    def collect_highlight_terms(self, ctx, out):
        self._build(ctx).collect_highlight_terms(ctx, out)


def _parse_match_bool_prefix(body):
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingError("[match_bool_prefix] requires exactly one field")
    (field, spec), = body.items()
    if isinstance(spec, str):
        spec = {"query": spec}
    return MatchBoolPrefixQuery(field, spec,
                                float(spec.get("boost", 1.0)))


def _parse_query_string(body):
    if "query" not in body:
        raise ParsingError("[query_string] requires [query]")
    fields = body.get("fields") or (
        [body["default_field"]] if body.get("default_field") else None)
    return QueryStringQuery(body["query"], fields,
                            body.get("default_operator", "or"),
                            float(body.get("boost", 1.0)),
                            lenient=bool(body.get("lenient", False)))


def _parse_simple_query_string(body):
    if "query" not in body:
        raise ParsingError("[simple_query_string] requires [query]")
    return QueryStringQuery(body["query"], body.get("fields"),
                            body.get("default_operator", "or"),
                            float(body.get("boost", 1.0)), lenient=True,
                            data_lenient=bool(body.get("lenient", False)))


def _parse_nested(body):
    return NestedQuery(body.get("path", ""), parse_query(body["query"]),
                       float(body.get("boost", 1.0)),
                       score_mode=body.get("score_mode", "avg"))


class _LazyMultiMatch(Query):
    """multi_match with wildcard field patterns: expansion needs the
    mapping, which only exists at execute time (reference:
    ``QueryParserHelper.resolveMappingFields``)."""

    def __init__(self, body):
        self.body = body
        self._built = None

    def _build(self, ctx):
        if self._built is None:
            fields = []
            for f in self.body.get("fields") or []:
                pat, caret, boost = f.partition("^")
                if "*" in pat:
                    from ..index.mapping import (KeywordFieldType,
                                                 TextFieldType,
                                                 resolve_field_patterns)
                    fields.extend(
                        n + caret + boost for n in resolve_field_patterns(
                            ctx.mapper, pat,
                            (TextFieldType, KeywordFieldType)))
                else:
                    fields.append(f)
            self._built = _parse_multi_match(
                dict(self.body, fields=fields))
        return self._built

    def execute(self, ctx, seg):
        return self._build(ctx).execute(ctx, seg)

    def collect_highlight_terms(self, ctx, out):
        self._build(ctx).collect_highlight_terms(ctx, out)


def _parse_multi_match(body):
    if any("*" in (f or "") for f in body.get("fields") or []):
        return _LazyMultiMatch(body)
    fields = body.get("fields") or []
    text = body.get("query")
    mtype = body.get("type", "best_fields")
    if mtype == "bool_prefix" and "slop" in body:
        raise IllegalArgumentError(
            "[slop] not allowed for type [bool_prefix]")
    if mtype == "bool_prefix":
        queries = []
        for f in body.get("fields") or []:
            fboost = 1.0
            if "^" in f:
                head, _, b_ = f.partition("^")
                try:
                    fboost = float(b_)
                    f = head
                except ValueError:
                    pass            # literal ^ in the field name
            spec = {"query": body.get("query"), "boost": fboost}
            for opt in ("minimum_should_match", "fuzziness", "analyzer",
                        "operator"):
                if body.get(opt) is not None:
                    spec[opt] = body[opt]
            queries.append(MatchBoolPrefixQuery(f, spec, fboost))
        if not queries:
            return MatchNoneQuery()
        return DisMaxQuery(queries, float(body.get("tie_breaker", 0.0)),
                           float(body.get("boost", 1.0)))
    tie = float(body.get("tie_breaker", 0.0))
    queries: List[Query] = []
    for f in fields:
        boost = 1.0
        if "^" in f:
            f, _, b = f.partition("^")
            boost = float(b)
        queries.append(MatchQuery(f, text, body.get("operator", "or"),
                                  body.get("minimum_should_match"), boost))
    if not queries:
        return MatchNoneQuery()
    if mtype in ("best_fields", "phrase"):
        return DisMaxQuery(queries, tie, float(body.get("boost", 1.0)))
    # most_fields: sum of field scores
    return BoolQuery(should=queries, boost=float(body.get("boost", 1.0)))


def _parse_match_all(body):
    return MatchAllQuery(float((body or {}).get("boost", 1.0)))


def _parse_match_none(body):
    return MatchNoneQuery()


def _parse_script_score(body):
    script = body.get("script", {})
    src = script.get("source") if isinstance(script, dict) else script
    if not src:
        raise ParsingError("[script_score] requires a script")
    return ScriptScoreQuery(
        parse_query(body.get("query", {"match_all": {}})), src,
        script.get("params", {}) if isinstance(script, dict) else {},
        body.get("min_score"), float(body.get("boost", 1.0)))


def _parse_function_score(body):
    inner = parse_query(body.get("query", {"match_all": {}}))
    functions = body.get("functions")
    if functions is None:
        functions = []
        for k in ("script_score", "field_value_factor", "weight"):
            if k in body:
                functions.append({k: body[k]})
    return FunctionScoreQuery(inner, functions,
                              body.get("score_mode", "multiply"),
                              body.get("boost_mode", "multiply"),
                              float(body.get("boost", 1.0)))


def _kw_values_by_doc(seg, field: str) -> Dict[int, str]:
    """doc → first keyword value of ``field`` (join columns are
    single-valued)."""
    kf = seg.keyword_fields.get(field)
    if kf is None:
        return {}
    out: Dict[int, str] = {}
    for d, o in zip(kf.dv_docs_host.tolist(), kf.dv_ords_host.tolist()):
        out.setdefault(int(d), kf.ord_terms[o])
    return out


def _join_field(ctx):
    from ..index.mapping import JoinFieldType
    mapper = getattr(ctx, "mapper", None)
    for ft in (getattr(mapper, "_fields", {}) or {}).values():
        if isinstance(ft, JoinFieldType):
            return ft
    return None


def _rel_mask(ctx, seg, field: str, names) -> np.ndarray:
    """bool[n_pad]: docs whose join relation name is in ``names``."""
    m = np.zeros(seg.n_pad, bool)
    for d, rel in _kw_values_by_doc(seg, field).items():
        if rel in names:
            m[d] = True
    return m


class HasChildQuery(Query):
    """Parents with a matching child (reference:
    ``modules/parent-join/.../HasChildQueryBuilder.java``). Children and
    parents share a shard (routing to the parent id), so the join is a
    per-segment group-by over the family-id column."""

    def __init__(self, child_type: str, inner: Query,
                 score_mode: str = "none", boost: float = 1.0,
                 min_children: int = 1,
                 max_children: Optional[int] = None):
        self.child_type = child_type
        self.inner = inner
        self.score_mode = score_mode
        self.boost = boost
        self.min_children = min_children
        self.max_children = max_children

    def execute(self, ctx, seg):
        jf = _join_field(ctx)
        if jf is None or jf.parent_rel_of(self.child_type) is None:
            return _const_result(seg, 0.0, False)
        id_field = jf.id_field_for(self.child_type)
        s, m = self.inner.execute(ctx, seg)
        child_m = _rel_mask(ctx, seg, jf.name, {self.child_type})
        child_m &= np.asarray(m)
        child_m[: seg.n_docs] &= seg.live[: seg.n_docs]
        fam = _kw_values_by_doc(seg, id_field)
        sn = np.asarray(s)
        agg: Dict[str, List[float]] = {}
        for d in np.flatnonzero(child_m).tolist():
            pid = fam.get(d)
            if pid is not None:
                agg.setdefault(pid, []).append(float(sn[d]))
        parent_rel = jf.parent_rel_of(self.child_type)
        scores = np.zeros(seg.n_pad, np.float32)
        mask = np.zeros(seg.n_pad, bool)
        rels = _kw_values_by_doc(seg, jf.name)
        for pid, child_scores in agg.items():
            n = len(child_scores)
            if n < self.min_children or \
                    (self.max_children is not None
                     and n > self.max_children):
                continue
            d = seg.find_doc(pid)
            if d is None or rels.get(d) != parent_rel or \
                    not seg.live[d]:
                continue
            if self.score_mode == "sum":
                v = sum(child_scores)
            elif self.score_mode == "max":
                v = max(child_scores)
            elif self.score_mode == "min":
                v = min(child_scores)
            elif self.score_mode == "avg":
                v = sum(child_scores) / n
            else:                        # none
                v = 1.0
            mask[d] = True
            scores[d] = v
        return (jnp.asarray(scores * np.float32(self.boost)),
                jnp.asarray(mask))

    def collect_highlight_terms(self, ctx, out):
        pass                             # parent hits carry no child terms


class HasParentQuery(Query):
    """Children of a matching parent (``HasParentQueryBuilder.java``)."""

    def __init__(self, parent_type: str, inner: Query,
                 score: bool = False, boost: float = 1.0):
        self.parent_type = parent_type
        self.inner = inner
        self.score = score
        self.boost = boost

    def execute(self, ctx, seg):
        jf = _join_field(ctx)
        if jf is None or self.parent_type not in jf.relations:
            return _const_result(seg, 0.0, False)
        s, m = self.inner.execute(ctx, seg)
        parent_m = _rel_mask(ctx, seg, jf.name, {self.parent_type})
        parent_m &= np.asarray(m)
        parent_m[: seg.n_docs] &= seg.live[: seg.n_docs]
        sn = np.asarray(s)
        matched: Dict[str, float] = {}
        for d in np.flatnonzero(parent_m).tolist():
            matched[seg.doc_uids[d]] = float(sn[d])
        kids = set(jf.relations[self.parent_type])
        id_field = f"{jf.name}#{self.parent_type}"
        fam = _kw_values_by_doc(seg, id_field)
        rels = _kw_values_by_doc(seg, jf.name)
        scores = np.zeros(seg.n_pad, np.float32)
        mask = np.zeros(seg.n_pad, bool)
        for d, pid in fam.items():
            if rels.get(d) in kids and pid in matched and seg.live[d]:
                mask[d] = True
                scores[d] = matched[pid] if self.score else 1.0
        return (jnp.asarray(scores * np.float32(self.boost)),
                jnp.asarray(mask))

    def collect_highlight_terms(self, ctx, out):
        pass


class ParentIdQuery(Query):
    """Children of one specific parent id (``ParentIdQueryBuilder``)."""

    def __init__(self, child_type: str, parent_id: str,
                 boost: float = 1.0):
        self.child_type = child_type
        self.parent_id = str(parent_id)
        self.boost = boost

    def execute(self, ctx, seg):
        jf = _join_field(ctx)
        if jf is None or jf.parent_rel_of(self.child_type) is None:
            return _const_result(seg, 0.0, False)
        id_field = jf.id_field_for(self.child_type)
        fam = _kw_values_by_doc(seg, id_field)
        rels = _kw_values_by_doc(seg, jf.name)
        mask = np.zeros(seg.n_pad, bool)
        for d, pid in fam.items():
            if pid == self.parent_id and \
                    rels.get(d) == self.child_type and seg.live[d]:
                mask[d] = True
        return (jnp.asarray(mask.astype(np.float32)
                            * np.float32(self.boost)),
                jnp.asarray(mask))


def _extract_required_terms(spec) -> "Optional[set]":
    """Candidate-extraction (reference: ``modules/percolator/
    QueryAnalyzer.java``): a set of (field, token) pairs such that the
    stored query can only match documents containing AT LEAST ONE of
    them; None → unanalyzable (ranges, match_all, negations…) — the
    stored query must always execute. Conservative by construction:
    over-approximating the set only costs an execution, never a miss."""
    if not isinstance(spec, dict) or len(spec) != 1:
        return None
    (kind, body), = spec.items()
    if kind in ("term", "match", "match_phrase"):
        if not isinstance(body, dict) or len(body) != 1:
            return None
        (field, v), = body.items()
        if isinstance(v, dict):
            v = v.get("value", v.get("query"))
        if v is None or isinstance(v, (dict, list, bool)):
            return None
        # tokens by the standard lowercase/word split — matching the
        # default analyzer's output is enough for an over-approximation
        import re as _re
        whole = str(v).lower()
        toks = [t for t in _re.split(r"\W+", whole) if t]
        if not toks:
            return None
        # a match/phrase needs every term for AND/phrase, any term for
        # OR — requiring presence of AT LEAST ONE is safe for all three;
        # the whole value joins the set so exact keyword terms
        # ("foo-bar") intersect the candidate's untokenized ord_terms
        return {(field, t) for t in toks} | {(field, whole)}
    if kind == "bool":
        if not isinstance(body, dict):
            return None
        musts = body.get("must") or body.get("filter") or []
        if isinstance(musts, dict):
            musts = [musts]
        for clause in musts:
            got = _extract_required_terms(clause)
            if got is not None:
                return got          # one analyzable must-clause suffices
        shoulds = body.get("should") or []
        if isinstance(shoulds, dict):
            shoulds = [shoulds]
        if shoulds and not musts:
            union: set = set()
            for clause in shoulds:
                got = _extract_required_terms(clause)
                if got is None:
                    return None     # one opaque branch could match alone
                union |= got
            return union
        return None
    return None


class PercolateQuery(Query):
    """Reverse search (reference: ``modules/percolator/PercolateQuery
    .java``): each doc carrying a stored query at ``field`` matches when
    that query matches the candidate document(s). The candidates index
    into a throwaway in-memory segment under this index's mapper; stored
    queries whose extracted required terms (``_extract_required_terms``,
    the QueryAnalyzer analog) are absent from the candidate are pruned
    without executing — O(matching-ish queries), not O(stored)."""

    def __init__(self, field: str, documents: List[dict],
                 boost: float = 1.0):
        self.field = field
        self.documents = documents
        self.boost = boost
        self._tmp = None                 # (searcher, segment) lazy

    def _temp_segment(self, ctx):
        if self._tmp is None:
            from ..index.segment import SegmentBuilder
            from .shard_search import ShardSearcher
            b = SegmentBuilder("_percolate_tmp")
            for i, doc in enumerate(self.documents):
                b.add(ctx.mapper.parse_document(f"_tmp_{i}", dict(doc)),
                      seq_no=i)
            seg = b.build()
            self._tmp = (ShardSearcher([seg], ctx.mapper), seg)
        return self._tmp

    def execute(self, ctx, seg):
        from ..index.mapping import PercolatorFieldType
        ft = ctx.mapper.field_type(self.field) if ctx.mapper else None
        if not isinstance(ft, PercolatorFieldType):
            return _const_result(seg, 0.0, False)
        searcher, tmp_seg = self._temp_segment(ctx)
        # candidate term set: every (field, token) present in the tmp
        # segment (text tokens + keyword values, lowercased to meet the
        # extractor's normalization)
        cand: set = set()
        for fname, f in tmp_seg.text_fields.items():
            base = fname.split(".")[0]
            for t in f.term_ids:
                cand.add((fname, str(t).lower()))
                cand.add((base, str(t).lower()))
        import re as _re
        for fname, f in tmp_seg.keyword_fields.items():
            base = fname.split(".")[0]
            for t in f.ord_terms:
                whole = str(t).lower()
                for tok in [whole] + [x for x in _re.split(r"\W+", whole)
                                      if x]:
                    cand.add((fname, tok))
                    cand.add((base, tok))
        for fname, f in tmp_seg.numeric_fields.items():
            base = fname.split(".")[0]
            for v in np.asarray(f.vals_host).tolist():
                for rep in (str(v), str(int(v)) if float(v).is_integer()
                            else str(v)):
                    cand.add((fname, rep))
                    cand.add((base, rep))
        # per-segment extraction cache: stored queries are immutable for
        # a segment's lifetime
        cache = getattr(seg, "_percolate_extractions", None)
        if cache is None or cache[0] != self.field:
            cache = (self.field, {})
            seg._percolate_extractions = cache
        extractions = cache[1]
        mask = np.zeros(seg.n_pad, bool)
        for d in range(seg.n_docs):
            if not seg.live[d]:
                continue
            src = seg.sources[d]
            spec = (src or {}).get(self.field)
            if not isinstance(spec, dict):
                continue
            try:
                if d not in extractions:
                    extractions[d] = _extract_required_terms(spec)
                req = extractions[d]
                if req is not None and not (req & cand):
                    continue        # no required term present: pruned
                q = parse_query(spec)
                _s, m2 = q.execute(searcher.ctx, tmp_seg)
                if bool(np.asarray(m2)[: tmp_seg.n_docs].any()):
                    mask[d] = True
            except Exception:   # noqa: BLE001 — a malformed stored query
                # cannot match; the reference rejects these at index
                # time, here percolate-time failures stay non-fatal
                continue
        return (jnp.asarray(mask.astype(np.float32)
                            * np.float32(self.boost)),
                jnp.asarray(mask))


def _parse_has_child(body):
    if "type" not in body or "query" not in body:
        raise ParsingError("[has_child] requires [type] and [query]")
    return HasChildQuery(
        body["type"], parse_query(body["query"]),
        score_mode=body.get("score_mode", "none"),
        boost=float(body.get("boost", 1.0)),
        min_children=int(body.get("min_children", 1)),
        max_children=(int(body["max_children"])
                      if "max_children" in body else None))


def _parse_has_parent(body):
    if "parent_type" not in body or "query" not in body:
        raise ParsingError(
            "[has_parent] requires [parent_type] and [query]")
    return HasParentQuery(
        body["parent_type"], parse_query(body["query"]),
        score=bool(body.get("score", False)),
        boost=float(body.get("boost", 1.0)))


def _parse_parent_id(body):
    if "type" not in body or "id" not in body:
        raise ParsingError("[parent_id] requires [type] and [id]")
    return ParentIdQuery(body["type"], body["id"],
                         float(body.get("boost", 1.0)))


def _parse_percolate(body):
    if "field" not in body:
        raise ParsingError("[percolate] requires [field]")
    docs = body.get("documents")
    if docs is None:
        doc = body.get("document")
        docs = [doc] if doc is not None else None
    if docs is None:
        raise ParsingError(
            "[percolate] requires [document], [documents], or a "
            "[index]/[id] pair (resolved by the REST layer)")
    return PercolateQuery(body["field"], list(docs),
                          float(body.get("boost", 1.0)))


_PARSERS = {
    "match_all": _parse_match_all,
    "has_child": _parse_has_child,
    "has_parent": _parse_has_parent,
    "parent_id": _parse_parent_id,
    "percolate": _parse_percolate,
    "script_score": _parse_script_score,
    "function_score": _parse_function_score,
    "match_none": _parse_match_none,
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "multi_match": _parse_multi_match,
    "term": _parse_term,
    "terms": _parse_terms,
    "range": _parse_range,
    "bool": _parse_bool,
    "dis_max": _parse_dis_max,
    "constant_score": _parse_constant_score,
    "exists": _parse_exists,
    "ids": _parse_ids,
    "prefix": _parse_prefix,
    "wildcard": _parse_wildcard,
    "regexp": _parse_regexp,
    "fuzzy": _parse_fuzzy,
    "boosting": _parse_boosting,
    "nested": _parse_nested,
    "match_bool_prefix": _parse_match_bool_prefix,
    "query_string": _parse_query_string,
    "simple_query_string": _parse_simple_query_string,
}


def register_query_parser(name: str, parser) -> None:
    """SPI hook mirroring ``SearchPlugin#getQueries``."""
    _PARSERS[name] = parser


# positional/expansion queries (intervals, spans, more_like_this,
# distance_feature) register themselves through the SPI hook above; the
# import must come after the registry exists (same pattern as aggs_extra)
from . import positional as _positional          # noqa: E402, F401
from . import geo_queries as _geo_queries        # noqa: E402, F401
