"""Query insights: plan-shape fingerprinting and heavy-hitter top-N.

The observability stack can say *that* the cluster is slow (SLO burn,
roofline drift, queue depth) but not *which queries make it slow*:
per-tenant rollups and the task ledger aggregate away the query shape.
This module closes that gap:

- :func:`shape_of` fingerprints a search/agg body into a normalized
  **query shape id** — a short hash of the lowered :class:`FusedPlan`
  (when the planner lowered the request) or of the legacy body
  structure, with literals stripped: field names and clause roles are
  kept, term *values* and query vectors dropped, and every size-ish
  parameter (``k``, windows, ``num_candidates``) bucketed exactly as
  the lattice buckets them (:func:`utils.shapes.round_up_pow2`), so
  two requests that compile to the same dispatch shape share one id.

- :class:`InsightStore` — per-node space-saving (Metwally) heavy-hitter
  sketches of the top-N shapes AND tenants by count, latency, cpu-ms,
  device-ms, and bytes. Bounded memory (capacity = top-N x
  ``SLACK``), per-window rotation (current + previous window
  retained), one exemplar trace id and one verbatim (truncated) sample
  body per retained shape. ``GET /_insights/top_queries`` serves it;
  the cluster front fans it in via ``rest:exec`` and MERGES sketches
  (sums per-key estimates, then re-applies the request limit — the
  PR 13/PR 15 limit-after-merge lesson).

The shape id itself rides the request as ambient context
(``common/flightrec.py``'s shape holder) so the slow log, the task
ledger, dispatch-profile records, and flight-recorder events all join
on it without argument plumbing.

Writes here are O(1) dict/heap-free updates under this module's own
lock — never under a serving lock (ESTP-L02 lists this module with
``common/telemetry``).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import telemetry
from ..common.settings import CLUSTER_SETTINGS, Setting
from ..utils.shapes import round_up_pow2

__all__ = [
    "shape_of", "fingerprint_plan", "fingerprint_body", "InsightStore",
    "store_for", "merge_top_docs", "topn", "window_seconds",
    "insights_enabled", "METRICS",
]

#: the tracked cost metrics (one sketch each, per dimension); ``count``
#: covers all observed traffic, ``shed`` the QoS-rejected subset, so
#: served = count - shed per shape/tenant
METRICS = ("count", "latency_ms", "cpu_ms", "device_ms", "bytes", "shed")

#: metrics formatted as integers in rows (the rest round to 3 places)
_INT_METRICS = ("count", "shed")

#: sketch capacity per metric = topn() x SLACK — generous enough that
#: a Zipf-heavy stream of a few dozen distinct shapes never evicts, so
#: the space-saving top-N guarantee degenerates to exact counting
SLACK = 8

#: verbatim sample bodies are truncated to this many serialized chars
SAMPLE_CAP = 2048

SETTING_TOPN = CLUSTER_SETTINGS.register(
    Setting.int_setting("insights.topn", 32,
                        scope="cluster", dynamic=False, min_value=1))
SETTING_WINDOW_S = CLUSTER_SETTINGS.register(
    Setting.float_setting("insights.window_seconds", 60.0,
                          scope="cluster", dynamic=False))
SETTING_DOMINANCE = CLUSTER_SETTINGS.register(
    Setting.float_setting("insights.dominance_fraction", 0.5,
                          scope="cluster", dynamic=True))
SETTING_MIN_OBS = CLUSTER_SETTINGS.register(
    Setting.int_setting("insights.min_window_observations", 16,
                        scope="cluster", dynamic=True, min_value=1))


def insights_enabled() -> bool:
    """Master on/off gate (``ES_TPU_INSIGHTS`` env; default on). The
    bench's insights-off arm uses this to measure the overhead."""
    return os.environ.get("ES_TPU_INSIGHTS", "1").lower() \
        not in ("0", "false")


def topn() -> int:
    raw = os.environ.get("ES_TPU_INSIGHTS_TOPN")
    if raw is not None:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return int(SETTING_TOPN.default)


def window_seconds() -> float:
    raw = os.environ.get("ES_TPU_INSIGHTS_WINDOW_S")
    if raw is not None:
        try:
            return max(1.0, float(raw))
        except ValueError:
            pass
    return float(SETTING_WINDOW_S.default)


def dominance_fraction() -> float:
    raw = os.environ.get("ES_TPU_INSIGHTS_DOMINANCE")
    if raw is not None:
        try:
            return float(raw)
        except ValueError:
            pass
    return float(SETTING_DOMINANCE.default)


def min_window_observations() -> int:
    raw = os.environ.get("ES_TPU_INSIGHTS_MIN_OBS")
    if raw is not None:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return int(SETTING_MIN_OBS.default)


# ---------------------------------------------------------------------------
# Fingerprinting
# ---------------------------------------------------------------------------

def _digest(parts) -> str:
    """Stable short id from a JSON-serializable normalized structure."""
    blob = json.dumps(parts, sort_keys=True, default=str,
                      separators=(",", ":")).encode()
    return "qs-" + hashlib.sha1(blob).hexdigest()[:12]


def fingerprint_plan(plan) -> str:
    """Shape id of a lowered :class:`query_planner.FusedPlan`: literals
    (term values, the query vector) stripped, clause roles and per-
    clause term COUNTS kept, every size bucketed exactly as
    ``make_item`` buckets the dispatch shape."""
    clauses = tuple((role, round_up_pow2(len(terms), 1))
                    for role, terms in plan.clauses)
    knn = None
    if plan.knn is not None:
        knn = ("knn", plan.knn.field, round_up_pow2(plan.knn.k, 1),
               round_up_pow2(plan.knn.num_candidates, 1),
               plan.knn.nprobe, plan.knn.rerank)
    rescore = None
    if plan.rescore is not None:
        rescore = ("rescore", plan.rescore.mode,
                   round_up_pow2(len(plan.rescore.terms), 1),
                   round_up_pow2(plan.rescore.window, 1))
    aggs = None
    if plan.aggs is not None:
        aggs = _strip_literals(_agg_structure(plan.aggs))
    return _digest(["fused", plan.field, clauses, plan.msm,
                    plan.bag is not None, knn, plan.fusion,
                    plan.rank_constant,
                    round_up_pow2(plan.rank_window, 1),
                    rescore, round_up_pow2(plan.k, 1),
                    round_up_pow2(plan.window_text, 1), aggs])


def _agg_structure(agg_plan):
    """The agg plan's canonical spec (``spec_key`` is the sorted-JSON
    spec the planner already canonicalizes on); parse it back so the
    literal stripper can walk it."""
    try:
        return json.loads(agg_plan.spec_key)
    except Exception:   # noqa: BLE001 — opaque plan: keep its key
        return str(getattr(agg_plan, "spec_key", ""))


_SIZE_KEYS = {"size", "from", "k", "num_candidates", "window_size",
              "rank_window_size", "rank_constant", "shard_size",
              "num_partitions", "precision_threshold", "nprobe",
              "rerank"}
#: keys whose values are literals (query text, vectors, ranges) — the
#: shape keeps the KEY (the field name / clause kind) and drops values
_LITERAL_DROP = {"query_vector", "query_vector_builder"}


def _strip_literals(node):
    """Normalize a body fragment: dict KEYS (query kinds, field names,
    agg types, option names) survive; scalar VALUES become their type
    tag except size-ish integers, which bucket pow2. Lists keep a
    bucketed length plus the normalized first element (homogeneous
    clause arrays collapse — ten should-terms and twelve hash the
    same once the count buckets equal)."""
    if isinstance(node, dict):
        out = {}
        for key, val in sorted(node.items()):
            key = str(key)
            if key in _LITERAL_DROP:
                out[key] = "_"
            elif key in _SIZE_KEYS and isinstance(val, (int, float)) \
                    and not isinstance(val, bool):
                out[key] = round_up_pow2(int(val), 1)
            else:
                out[key] = _strip_literals(val)
        return out
    if isinstance(node, list):
        head = _strip_literals(node[0]) if node else None
        return ["[]", round_up_pow2(len(node), 1), head]
    if isinstance(node, bool) or node is None:
        return node
    if isinstance(node, (int, float)):
        return "n"
    return "s"


def fingerprint_body(body: Optional[dict]) -> str:
    """Shape id for a request the planner did NOT lower: a structural
    walk keeping query kinds / field names / agg types, stripping
    literal values, bucketing sizes."""
    if not isinstance(body, dict):
        return _digest(["legacy", None])
    keep = {}
    for section in ("query", "knn", "aggs", "aggregations", "rescore",
                    "sort", "collapse", "suggest", "rank", "_source",
                    "size", "from", "min_score", "search_after"):
        if section in body:
            keep[section] = body[section]
    return _digest(["legacy", _strip_literals(keep)])


def shape_of(body: Optional[dict], plan=None) -> str:
    """The query shape id: plan-based when the request lowered to a
    :class:`FusedPlan`, structural otherwise. Never raises — insight
    must not fail the request it fingerprints."""
    try:
        if plan is not None:
            return fingerprint_plan(plan)
        return fingerprint_body(body)
    except Exception:   # noqa: BLE001 — best-effort by contract
        return "qs-error"


# ---------------------------------------------------------------------------
# Space-saving sketch
# ---------------------------------------------------------------------------

class SpaceSaving:
    """Metwally et al. space-saving summary over a weighted stream:
    at most ``cap`` tracked keys; an untracked arrival evicts the
    current minimum and inherits its estimate as the new key's error
    bound. ``est - err <= true <= est`` for every tracked key, and any
    key whose true weight exceeds ``total / cap`` is guaranteed
    tracked. Not thread-safe — the owning store serializes."""

    __slots__ = ("cap", "items", "total")

    def __init__(self, cap: int):
        self.cap = max(1, int(cap))
        # key -> [estimate, error]
        self.items: Dict[str, list] = {}
        self.total = 0.0

    def offer(self, key: str, weight: float) -> None:
        self.total += weight
        ent = self.items.get(key)
        if ent is not None:
            ent[0] += weight
            return
        if len(self.items) < self.cap:
            self.items[key] = [weight, 0.0]
            return
        mkey = min(self.items, key=lambda k: self.items[k][0])
        mest = self.items.pop(mkey)[0]
        self.items[key] = [mest + weight, mest]

    def top(self, n: int) -> List[Tuple[str, float, float]]:
        """``[(key, estimate, error)]`` sorted by estimate desc."""
        rows = sorted(self.items.items(),
                      key=lambda kv: (-kv[1][0], kv[0]))
        return [(k, v[0], v[1]) for k, v in rows[:max(0, int(n))]]

    def to_doc(self) -> dict:
        return {"cap": self.cap, "total": round(self.total, 3),
                "items": {k: [round(v[0], 3), round(v[1], 3)]
                          for k, v in self.items.items()}}


# ---------------------------------------------------------------------------
# Per-node store
# ---------------------------------------------------------------------------

class _Window:
    """One rotation window: per-dimension, per-metric sketches plus
    bounded shape metadata (exemplar trace id + sample body)."""

    __slots__ = ("start", "sketches", "meta", "observations")

    def __init__(self, start: float, cap: int):
        self.start = start
        self.observations = 0
        # dimension -> metric -> SpaceSaving
        self.sketches = {
            dim: {m: SpaceSaving(cap) for m in METRICS}
            for dim in ("shape", "tenant")}
        # shape_id -> {"trace_id", "sample"}
        self.meta: Dict[str, dict] = {}


class InsightStore:
    """Per-node query-insight accumulator: bounded sketches with
    current + previous window retained, rotated lazily off the
    injectable clock."""

    def __init__(self, node: Optional[str] = None,
                 topn_: Optional[int] = None,
                 window_s: Optional[float] = None,
                 clock=time.monotonic,
                 registry: Optional[telemetry.TelemetryRegistry] = None):
        self.node = node or "local"
        self.topn = topn_ if topn_ is not None else topn()
        self.cap = self.topn * SLACK
        self.window_s = window_s if window_s is not None \
            else window_seconds()
        self._clock = clock
        self._lock = threading.Lock()
        self._cur = _Window(self._clock(), self.cap)
        self._prev: Optional[_Window] = None
        self._reg = registry or telemetry.DEFAULT
        # pre-create the families so the catalogue lint always sees them
        self._reg.counter("es_insight_observations_total",
                          help="query-insight observations folded into "
                               "the heavy-hitter sketches")
        self._reg.counter("es_insight_window_rotations_total",
                          help="insight window rotations (current -> "
                               "previous)")
        self._reg.gauge("es_insight_shapes_tracked",
                        help="distinct shapes tracked in the current "
                             "insight window (count sketch)").set(0)

    # -- write path ---------------------------------------------------------

    def _rotate_locked(self, now: float) -> None:
        if now - self._cur.start < self.window_s:
            return
        self._prev = self._cur
        self._cur = _Window(now, self.cap)
        self._reg.counter("es_insight_window_rotations_total").inc()

    def observe(self, shape_id: Optional[str], tenant: Optional[str],
                latency_ms: float = 0.0, cpu_ms: float = 0.0,
                device_ms: float = 0.0, bytes_: float = 0.0,
                trace_id: Optional[str] = None,
                sample_body: Optional[dict] = None,
                shed: float = 0.0) -> None:
        """Fold one finished search into the sketches. O(topn) worst
        case (a min() scan on eviction), O(1) typically; never
        raises."""
        if not shape_id:
            return
        try:
            vals = {"count": 1.0, "latency_ms": float(latency_ms),
                    "cpu_ms": float(cpu_ms),
                    "device_ms": float(device_ms),
                    "bytes": float(bytes_), "shed": float(shed)}
            now = self._clock()
            with self._lock:
                self._rotate_locked(now)
                win = self._cur
                win.observations += 1
                for metric, v in vals.items():
                    win.sketches["shape"][metric].offer(shape_id, v)
                    if tenant:
                        win.sketches["tenant"][metric].offer(
                            str(tenant), v)
                if shape_id not in win.meta:
                    if len(win.meta) >= 2 * self.cap:
                        # keep only shapes the count sketch still tracks
                        live = win.sketches["shape"]["count"].items
                        for dead in [k for k in win.meta
                                     if k not in live]:
                            win.meta.pop(dead, None)
                    if len(win.meta) < 2 * self.cap:
                        win.meta[shape_id] = {
                            "trace_id": trace_id,
                            "sample": _truncate_sample(sample_body)}
                shapes_tracked = len(
                    win.sketches["shape"]["count"].items)
            self._reg.counter("es_insight_observations_total").inc()
            self._reg.gauge("es_insight_shapes_tracked") \
                .set(shapes_tracked)
        except Exception:   # noqa: BLE001 — insight must not fail serving
            pass

    # -- read path ----------------------------------------------------------

    def _windows_locked(self, window: str) -> List[_Window]:
        if window == "previous":
            return [self._prev] if self._prev is not None else []
        if window == "both":
            return [w for w in (self._cur, self._prev) if w is not None]
        return [self._cur]

    def top_doc(self, limit: Optional[int] = None,
                metric: str = "count",
                window: str = "current") -> dict:
        """The per-node ``GET /_insights/top_queries`` document: rows
        ranked by ``metric``'s sketch, each enriched with every other
        metric's estimate for the same key plus the retained exemplar
        trace id and sample body."""
        if metric not in METRICS:
            metric = "count"
        n = limit if limit is not None else self.topn
        with self._lock:
            self._rotate_locked(self._clock())
            wins = self._windows_locked(window)
            doc = {"node": self.node, "metric": metric,
                   "window_seconds": self.window_s,
                   "observations": sum(w.observations for w in wins),
                   "shapes": self._rows_locked("shape", wins, metric, n),
                   "tenants": self._rows_locked("tenant", wins, metric,
                                                n)}
        return doc

    def _rows_locked(self, dim: str, wins: List[_Window], metric: str,
                     n: int) -> List[dict]:
        # merge the selected windows' sketches per metric (sum of
        # estimates — same rule the cluster fan-in applies per node)
        merged: Dict[str, dict] = {}
        for win in wins:
            for m in METRICS:
                for key, est, err in win.sketches[dim][m].top(
                        win.sketches[dim][m].cap):
                    row = merged.setdefault(
                        key, {m2: 0.0 for m2 in METRICS})
                    row[m] = row.get(m, 0.0) + est
                    if m == metric:
                        row["error"] = row.get("error", 0.0) + err
        rows = sorted(merged.items(),
                      key=lambda kv: (-kv[1].get(metric, 0.0), kv[0]))
        out = []
        for key, vals in rows[:max(0, int(n))]:
            row = {("shape" if dim == "shape" else "tenant"): key}
            for m in METRICS:
                row[m] = int(vals.get(m, 0)) if m in _INT_METRICS \
                    else round(vals.get(m, 0.0), 3)
            row["error"] = round(vals.get("error", 0.0), 3)
            if dim == "shape":
                for win in wins:
                    meta = win.meta.get(key)
                    if meta is not None:
                        if meta.get("trace_id"):
                            row["exemplar_trace_id"] = meta["trace_id"]
                        if meta.get("sample") is not None:
                            row["sample"] = meta["sample"]
                        break
            out.append(row)
        return out

    def dominance(self) -> dict:
        """The health indicator's read: the top shape's and tenant's
        fraction of windowed (current + previous) device-ms, with the
        shape's retained sample for the diagnosis."""
        with self._lock:
            self._rotate_locked(self._clock())
            wins = self._windows_locked("both")
            obs = sum(w.observations for w in wins)
            out = {"observations": obs}
            for dim in ("shape", "tenant"):
                total = sum(w.sketches[dim]["device_ms"].total
                            for w in wins)
                agg: Dict[str, float] = {}
                for w in wins:
                    for key, est, _err in \
                            w.sketches[dim]["device_ms"].top(self.cap):
                        agg[key] = agg.get(key, 0.0) + est
                if agg and total > 0:
                    key = max(agg, key=lambda k: agg[k])
                    shed = sum(est for w in wins
                               for k, est, _e in
                               w.sketches[dim]["shed"].top(self.cap)
                               if k == key)
                    out[dim] = {"key": key,
                                "device_ms": round(agg[key], 3),
                                "fraction": round(agg[key] / total, 4),
                                "shed": int(shed)}
                    if dim == "shape":
                        for w in wins:
                            meta = w.meta.get(key)
                            if meta is not None:
                                out[dim]["sample"] = meta.get("sample")
                                break
        return out


def _truncate_sample(body: Optional[dict]):
    """One verbatim sample body per shape, truncated so a pathological
    10k-term request cannot bloat the store."""
    if body is None:
        return None
    try:
        blob = json.dumps(body, default=str)
    except Exception:   # noqa: BLE001 — unserializable body
        return None
    if len(blob) <= SAMPLE_CAP:
        return body
    return {"_truncated": blob[:SAMPLE_CAP]}


# ---------------------------------------------------------------------------
# Per-node registry (in-process clusters share the module, not a store)
# ---------------------------------------------------------------------------

_STORES_LOCK = threading.Lock()
_STORES: Dict[str, InsightStore] = {}
_STORES_CAP = 64


def store_for(node: Optional[str]) -> InsightStore:
    """The node's insight store, created on first touch. Bounded:
    test suites spin up many short-lived in-process nodes; oldest
    entries fall off past ``_STORES_CAP``."""
    key = node or "local"
    with _STORES_LOCK:
        store = _STORES.get(key)
        if store is None:
            while len(_STORES) >= _STORES_CAP:
                _STORES.pop(next(iter(_STORES)))
            store = _STORES[key] = InsightStore(node=key)
        return store


# ---------------------------------------------------------------------------
# Cluster fan-in merge
# ---------------------------------------------------------------------------

def merge_top_docs(docs: List[dict], limit: int,
                   metric: str = "count") -> dict:
    """Merge per-node ``top_doc`` payloads: per-key SUM of sketch
    estimates across nodes (space-saving summaries merge by adding
    estimates and error bounds), re-rank by the requested metric, then
    re-apply the request ``limit`` AFTER the merge — never concatenate
    per-node top-N lists (the n_nodes x limit bug)."""
    if metric not in METRICS:
        metric = "count"
    out = {"metric": metric, "nodes": [], "observations": 0,
           "shapes": [], "tenants": []}
    merged = {"shapes": {}, "tenants": {}}
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        out["nodes"].append(doc.get("node", "?"))
        out["observations"] += int(doc.get("observations", 0))
        if "window_seconds" in doc:
            out["window_seconds"] = doc["window_seconds"]
        for section, keyname in (("shapes", "shape"),
                                 ("tenants", "tenant")):
            for row in doc.get(section) or []:
                key = row.get(keyname)
                if not key:
                    continue
                ent = merged[section].setdefault(
                    key, {m: 0.0 for m in METRICS} | {"error": 0.0})
                for m in METRICS:
                    ent[m] += float(row.get(m, 0.0))
                ent["error"] += float(row.get("error", 0.0))
                if "exemplar_trace_id" in row and \
                        "exemplar_trace_id" not in ent:
                    ent["exemplar_trace_id"] = row["exemplar_trace_id"]
                if "sample" in row and "sample" not in ent:
                    ent["sample"] = row["sample"]
    for section, keyname in (("shapes", "shape"), ("tenants", "tenant")):
        rows = sorted(merged[section].items(),
                      key=lambda kv: (-kv[1].get(metric, 0.0), kv[0]))
        sect = []
        for key, vals in rows[:max(0, int(limit))]:
            row = {keyname: key}
            for m in METRICS:
                row[m] = int(vals[m]) if m in _INT_METRICS \
                    else round(vals[m], 3)
            row["error"] = round(vals.get("error", 0.0), 3)
            for extra in ("exemplar_trace_id", "sample"):
                if extra in vals:
                    row[extra] = vals[extra]
            sect.append(row)
        out[section] = sect
    out["nodes"] = sorted(set(out["nodes"]))
    return out
