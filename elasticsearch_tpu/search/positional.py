"""Positional + relevance-expansion queries: ``intervals``, the span
family, ``more_like_this`` and ``distance_feature``.

References: ``index/query/IntervalQueryBuilder.java``,
``SpanNearQueryBuilder.java`` / ``SpanTermQueryBuilder.java`` (+ siblings),
``MoreLikeThisQueryBuilder.java``, ``DistanceFeatureQueryBuilder.java``.

Execution model: candidate docs come from device postings masks, the
positional algebra itself runs host-side over the segment position CSR
(see ``search/intervals.py``); ``more_like_this`` rewrites into the
bool/term machinery which is fully device-side.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..common.errors import IllegalArgumentError, ParsingError
from ..common.settings import parse_time_millis
from ..index.mapping import (DateFieldType, GeoPointFieldType, TextFieldType,
                             parse_date_millis)
from ..ops.bm25 import DEFAULT_B, DEFAULT_K1, idf_weight
from . import intervals as iv
from .query_dsl import (BoolQuery, FuzzyQuery, Query, TermQuery,
                        _const_result, _edit_distance_le,
                        register_query_parser, wildcard_regex)

# ---------------------------------------------------------------------------
# shared: interval-source scoring as a Query
# ---------------------------------------------------------------------------


class _IntervalScoredQuery(Query):
    """Scores any IntervalSource tree: freq = Σ 1/(1+width-1) over minimal
    intervals (Lucene ``IntervalScorer`` sloppy weight), idf = Σ leaf idfs."""

    def __init__(self, field: str, boost: float = 1.0):
        self.field = field
        self.boost = boost

    def build_source(self, ctx, seg) -> Optional[iv.IntervalSource]:
        raise NotImplementedError

    def execute(self, ctx, seg):
        field = ctx.concrete_field(self.field)
        ft = ctx.field_type(field)
        if not isinstance(ft, TextFieldType):
            return _const_result(seg, 0.0, False)
        src = self.build_source(ctx, seg)
        if src is None:
            return _const_result(seg, 0.0, False)
        cands = src.doc_candidates(seg)
        scores_host = np.zeros(seg.n_pad, np.float32)
        mask_host = np.zeros(seg.n_pad, bool)
        if cands.size:
            leaves = src.leaf_weights(seg)
            by_field: Dict[str, set] = {}
            for lf, lt in leaves:
                by_field.setdefault(lf, set()).add(lt)
            idf = 0.0
            for lf, terms in by_field.items():
                dfs = [ctx.term_df(lf, t) for t in terms]
                idf += float(idf_weight(ctx.total_docs, dfs).sum())
            avgdl = max(ctx.field_avgdl(field), 1e-9)
            f = seg.text_fields.get(field)
            k1, b = DEFAULT_K1, DEFAULT_B
            for d in np.unique(cands):
                ints = src.intervals(seg, int(d))
                if not ints:
                    continue
                freq = sum(1.0 / (1 + (e - s)) for s, e in ints)
                dl = float(f.doc_len_host[d]) if f is not None else 1.0
                norm = freq + k1 * (1 - b + b * dl / avgdl)
                scores_host[d] = idf * (k1 + 1) * freq / norm
                mask_host[d] = True
        return (jnp.asarray(scores_host * np.float32(self.boost)),
                jnp.asarray(mask_host))

    def collect_highlight_terms(self, ctx, out):
        pass


# ---------------------------------------------------------------------------
# intervals query
# ---------------------------------------------------------------------------

_RULE_KEYS = ("match", "all_of", "any_of", "prefix", "wildcard", "fuzzy")


class IntervalsQuery(_IntervalScoredQuery):
    def __init__(self, field: str, rule: dict, boost: float = 1.0):
        super().__init__(field, boost)
        self.rule = rule

    def build_source(self, ctx, seg):
        return _build_interval_source(ctx, self.field, self.rule)


def _analyzer_for(ctx, field: str):
    ft = ctx.field_type(ctx.concrete_field(field))
    if isinstance(ft, TextFieldType):
        return ft.search_analyzer
    return None


def _build_interval_source(ctx, field: str, rule: dict):
    if not isinstance(rule, dict):
        raise ParsingError("Expected an object for interval source")
    keys = [k for k in rule if k in _RULE_KEYS]
    if len(keys) != 1:
        raise ParsingError(
            f"expected one interval source, found {sorted(rule)}")
    kind = keys[0]
    body = rule[kind]
    if kind == "match":
        use_field = body.get("use_field", field)
        an = _analyzer_for(ctx, use_field)
        if an is None:
            return None
        cfield = ctx.concrete_field(use_field)
        terms = an.terms(str(body.get("query", "")))
        if not terms:
            return None
        if len(terms) == 1:
            src = iv.TermSource(cfield, terms[0])
        else:
            src = iv.CombineSource(
                [iv.TermSource(cfield, t) for t in terms],
                ordered=bool(body.get("ordered", False)),
                max_gaps=int(body.get("max_gaps", -1)))
        return _apply_interval_filter(ctx, field, src, body.get("filter"))
    if kind == "all_of":
        subs = [_build_interval_source(ctx, field, r)
                for r in body.get("intervals", [])]
        if not subs or any(s is None for s in subs):
            return None
        src = iv.CombineSource(subs,
                               ordered=bool(body.get("ordered", False)),
                               max_gaps=int(body.get("max_gaps", -1)))
        return _apply_interval_filter(ctx, field, src, body.get("filter"))
    if kind == "any_of":
        subs = [_build_interval_source(ctx, field, r)
                for r in body.get("intervals", [])]
        subs = [s for s in subs if s is not None]
        if not subs:
            return None
        src = iv.AnyOfSource(subs)
        return _apply_interval_filter(ctx, field, src, body.get("filter"))
    if kind == "prefix":
        use_field = body.get("use_field", field)
        cfield = ctx.concrete_field(use_field)
        pfx = str(body.get("prefix", ""))
        return iv.ExpansionSource(cfield, lambda t: t.startswith(pfx),
                                  f"prefix:{pfx}")
    if kind == "wildcard":
        use_field = body.get("use_field", field)
        cfield = ctx.concrete_field(use_field)
        pat = str(body.get("pattern", ""))
        rx = wildcard_regex(pat)
        return iv.ExpansionSource(cfield, lambda t: bool(rx.match(t)),
                                  f"wildcard:{pat}")
    if kind == "fuzzy":
        use_field = body.get("use_field", field)
        cfield = ctx.concrete_field(use_field)
        term = str(body.get("term", ""))
        fz = body.get("fuzziness", "AUTO")
        if fz in ("AUTO", "auto", None):
            n = len(term)
            max_edits = 0 if n <= 2 else (1 if n <= 5 else 2)
        else:
            max_edits = int(fz)
        plen = int(body.get("prefix_length", 0))

        def pred(t, term=term, k=max_edits, plen=plen):
            if plen and t[:plen] != term[:plen]:
                return False
            return _edit_distance_le(t, term, k)

        return iv.ExpansionSource(cfield, pred, f"fuzzy:{term}")
    raise ParsingError(f"unknown interval source [{kind}]")


def _apply_interval_filter(ctx, field: str, src, flt: Optional[dict]):
    if not flt:
        return src
    if not isinstance(flt, dict) or len(flt) != 1:
        raise ParsingError("interval filter must define exactly one relation")
    (kind, inner), = flt.items()
    if kind == "script":
        raise ParsingError("interval script filters are not supported")
    if kind not in iv.FilteredSource.KINDS:
        raise ParsingError(f"unknown interval filter [{kind}]")
    ref = _build_interval_source(ctx, field, inner)
    if ref is None:
        # an unbuildable reference filters nothing for not_* kinds and
        # everything for positive kinds
        if kind.startswith("not_"):
            return src
        return None
    return iv.FilteredSource(src, kind, ref)


def _parse_intervals(body):
    if not isinstance(body, dict):
        raise ParsingError("[intervals] query malformed")
    opts = dict(body)
    boost = float(opts.pop("boost", 1.0))
    if len(opts) != 1:
        raise ParsingError("[intervals] expects exactly one field")
    (field, rule), = opts.items()
    if isinstance(rule, dict) and "boost" in rule:
        # boost nests inside the field object (IntervalQueryBuilder)
        rule = dict(rule)
        boost *= float(rule.pop("boost"))
    return IntervalsQuery(field, rule, boost)


# ---------------------------------------------------------------------------
# span queries — thin adapters over the same interval algebra
# ---------------------------------------------------------------------------


class SpanQuery(_IntervalScoredQuery):
    """A span query node: carries a builder fn (ctx, seg) -> source and the
    field it reports (field_masking_span may mask the true one)."""

    def __init__(self, field: str, builder, boost: float = 1.0):
        super().__init__(field, boost)
        self._builder = builder

    def build_source(self, ctx, seg):
        return self._builder(ctx, seg)


def _span_field_and_builder(spec: dict) -> Tuple[str, "callable"]:
    """Parse one span clause to (reported_field, builder)."""
    if not isinstance(spec, dict) or len(spec) != 1:
        raise ParsingError("span clause malformed")
    (kind, body), = spec.items()

    if kind == "span_term":
        if len(body) != 1:
            raise ParsingError("[span_term] expects one field")
        (field, v), = body.items()
        value = v.get("value") if isinstance(v, dict) else v

        def b(ctx, seg, field=field, value=value):
            return iv.TermSource(ctx.concrete_field(field), str(value))
        return field, b

    if kind == "span_near":
        clauses = [(_span_field_and_builder(c)) for c in body.get("clauses", [])]
        if not clauses:
            raise ParsingError("[span_near] requires clauses")
        slop = int(body.get("slop", 0))
        in_order = bool(body.get("in_order", True))
        field = clauses[0][0]

        def b(ctx, seg, clauses=clauses, slop=slop, in_order=in_order):
            subs = [cb(ctx, seg) for _, cb in clauses]
            if any(s is None for s in subs):
                return None
            return iv.CombineSource(subs, ordered=in_order, max_gaps=slop)
        return field, b

    if kind == "span_or":
        clauses = [(_span_field_and_builder(c)) for c in body.get("clauses", [])]
        if not clauses:
            raise ParsingError("[span_or] requires clauses")
        field = clauses[0][0]

        def b(ctx, seg, clauses=clauses):
            subs = [cb(ctx, seg) for _, cb in clauses]
            subs = [s for s in subs if s is not None]
            return iv.AnyOfSource(subs) if subs else None
        return field, b

    if kind == "span_not":
        fi, bi = _span_field_and_builder(body["include"])
        _, be = _span_field_and_builder(body["exclude"])
        dist = body.get("dist")
        pre = int(dist if dist is not None else body.get("pre", 0))
        post = int(dist if dist is not None else body.get("post", 0))

        def b(ctx, seg, bi=bi, be=be, pre=pre, post=post):
            inc, exc = bi(ctx, seg), be(ctx, seg)
            if inc is None:
                return None
            if exc is None:
                return inc
            return iv.NotNearSource(inc, exc, pre, post)
        return fi, b

    if kind == "span_first":
        if "match" not in body or "end" not in body:
            raise ParsingError("[span_first] requires [match] and [end]")
        fi, bi = _span_field_and_builder(body["match"])
        end = int(body["end"])

        def b(ctx, seg, bi=bi, end=end):
            src = bi(ctx, seg)
            return iv.FirstSource(src, end) if src is not None else None
        return fi, b

    if kind == "span_multi":
        inner = body.get("match")
        if not isinstance(inner, dict) or len(inner) != 1:
            raise ParsingError("[span_multi] requires a [match] clause")
        (mt_kind, mt_body), = inner.items()
        if len(mt_body) != 1:
            raise ParsingError("[span_multi] match expects one field")
        (field, v), = mt_body.items()
        opts = dict(v) if isinstance(v, dict) else {"value": v}
        value = str(opts.get("value", opts.get("query", "")))

        def b(ctx, seg, mt_kind=mt_kind, field=field, value=value, opts=opts):
            cfield = ctx.concrete_field(field)
            if mt_kind == "prefix":
                return iv.ExpansionSource(
                    cfield, lambda t: t.startswith(value), f"prefix:{value}")
            if mt_kind == "wildcard":
                rx = wildcard_regex(value)
                return iv.ExpansionSource(
                    cfield, lambda t: bool(rx.match(t)), f"wildcard:{value}")
            if mt_kind == "regexp":
                rx = re.compile(f"(?:{value})\\Z")
                return iv.ExpansionSource(
                    cfield, lambda t: bool(rx.match(t)), f"regexp:{value}")
            if mt_kind == "fuzzy":
                fq = FuzzyQuery(field, value,
                                opts.get("fuzziness", "AUTO"),
                                int(opts.get("prefix_length", 0)))
                return iv.ExpansionSource(
                    cfield, fq._matches, f"fuzzy:{value}")
            if mt_kind == "range":
                lo = opts.get("gte", opts.get("gt"))
                hi = opts.get("lte", opts.get("lt"))

                def pred(t, lo=lo, hi=hi):
                    return ((lo is None or t >= str(lo)) and
                            (hi is None or t <= str(hi)))
                return iv.ExpansionSource(cfield, pred, "range")
            raise ParsingError(
                f"[span_multi] cannot wrap query type [{mt_kind}]")
        return field, b

    if kind in ("span_containing", "span_within"):
        fl, bl = _span_field_and_builder(body["little"])
        fb, bb = _span_field_and_builder(body["big"])
        containing = kind == "span_containing"

        def b(ctx, seg, bl=bl, bb=bb, containing=containing):
            little, big = bl(ctx, seg), bb(ctx, seg)
            if little is None or big is None:
                return None
            if containing:
                return iv.FilteredSource(big, "containing", little)
            return iv.FilteredSource(little, "contained_by", big)
        return (fb if containing else fl), b

    if kind == "field_masking_span":
        _, bi = _span_field_and_builder(body["query"])
        return body.get("field", ""), bi

    raise ParsingError(f"unknown span query [{kind}]")


def _make_span_parser(kind: str):
    def parse(body):
        opts = dict(body) if isinstance(body, dict) else body
        boost = 1.0
        if isinstance(opts, dict):
            boost = float(opts.pop("boost", 1.0))
            if kind == "span_term" and len(opts) == 1:
                # boost nests inside the per-field value object
                (fld, v), = opts.items()
                if isinstance(v, dict) and "boost" in v:
                    v = dict(v)
                    boost *= float(v.pop("boost"))
                    opts = {fld: v}
        field, builder = _span_field_and_builder({kind: opts})
        return SpanQuery(field, builder, boost)
    return parse


# ---------------------------------------------------------------------------
# more_like_this
# ---------------------------------------------------------------------------


class MoreLikeThisQuery(Query):
    """Term-vector similarity (reference: ``MoreLikeThisQueryBuilder.java``,
    Lucene ``MoreLikeThis``): select the highest tf·idf terms from the
    *like* texts/docs, drop *unlike* terms, rewrite to a should-of-terms
    bool. The rewrite happens once per shard context and then scores fully
    device-side."""

    def __init__(self, like, unlike=None, fields=None, *,
                 max_query_terms: int = 25, min_term_freq: int = 2,
                 min_doc_freq: int = 5, max_doc_freq: int = 1 << 62,
                 minimum_should_match="30%", include: bool = False,
                 boost: float = 1.0):
        self.like = like if isinstance(like, list) else [like]
        self.unlike = (unlike if isinstance(unlike, list)
                       else [unlike]) if unlike else []
        self.fields = fields
        self.max_query_terms = max_query_terms
        self.min_term_freq = min_term_freq
        self.min_doc_freq = min_doc_freq
        self.max_doc_freq = max_doc_freq
        self.minimum_should_match = minimum_should_match
        self.include = include
        self.boost = boost
        self._ctx_cache: Dict[int, Query] = {}

    # -- helpers ----------------------------------------------------------

    def _doc_source(self, ctx, item: dict) -> Optional[dict]:
        if "doc" in item:
            return item["doc"]
        doc_id = item.get("_id")
        if doc_id is None:
            return None
        for seg in ctx.segments:
            d = seg.find_doc(str(doc_id))
            if d is not None:
                return seg.sources[d]
        return None

    def _field_texts(self, ctx, items) -> Tuple[Dict[str, List[str]], List[str]]:
        """Per selected field, the texts contributed by like/unlike items;
        plus the _ids of items that referenced live docs."""
        if self.fields:
            fields = list(self.fields)
        else:
            fields = [name for name, ft in ctx.mapper._fields.items()
                      if isinstance(ft, TextFieldType)]
        texts: Dict[str, List[str]] = {f: [] for f in fields}
        seen_ids: List[str] = []
        for item in items:
            if isinstance(item, str):
                for f in fields:
                    texts[f].append(item)
                continue
            if isinstance(item, dict):
                if "_id" in item and "doc" not in item:
                    seen_ids.append(str(item["_id"]))
                src = self._doc_source(ctx, item)
                if src is None:
                    continue
                for f in fields:
                    v = _dig(src, f)
                    if v is not None:
                        texts[f].append(str(v))
        return texts, seen_ids

    def _rewrite(self, ctx) -> Query:
        like_texts, like_ids = self._field_texts(ctx, self.like)
        unlike_texts, _ = self._field_texts(ctx, self.unlike)

        stop: Dict[str, set] = {}
        for f, txts in unlike_texts.items():
            an = _analyzer_for(ctx, f)
            if an is None:
                continue
            s = stop.setdefault(f, set())
            for t in txts:
                s.update(an.terms(t))

        scored: List[Tuple[float, str, str]] = []      # (tfidf, field, term)
        for f, txts in like_texts.items():
            an = _analyzer_for(ctx, f)
            if an is None or not txts:
                continue
            tf: Dict[str, int] = {}
            for t in txts:
                for term in an.terms(t):
                    tf[term] = tf.get(term, 0) + 1
            for term, freq in tf.items():
                if freq < self.min_term_freq:
                    continue
                if term in stop.get(f, ()):
                    continue
                df = ctx.term_df(f, term)
                if df < self.min_doc_freq or df > self.max_doc_freq:
                    continue
                idf = math.log(1 + (ctx.total_docs - df + 0.5) / (df + 0.5))
                scored.append((freq * idf, f, term))
        scored.sort(reverse=True)
        scored = scored[: self.max_query_terms]
        if not scored:
            from .query_dsl import MatchNoneQuery
            return MatchNoneQuery()
        should = [TermQuery(f, term) for _, f, term in scored]
        must_not: List[Query] = []
        if not self.include and like_ids:
            from .query_dsl import IdsQuery
            must_not.append(IdsQuery(like_ids))
        return BoolQuery(should=should, must_not=must_not,
                         minimum_should_match=self.minimum_should_match,
                         boost=self.boost)

    def execute(self, ctx, seg):
        q = self._ctx_cache.get(id(ctx))
        if q is None:
            q = self._ctx_cache[id(ctx)] = self._rewrite(ctx)
        return q.execute(ctx, seg)

    def collect_highlight_terms(self, ctx, out):
        q = self._ctx_cache.get(id(ctx))
        if q is not None:
            q.collect_highlight_terms(ctx, out)


def _dig(src: dict, path: str):
    cur = src
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _parse_more_like_this(body):
    if not isinstance(body, dict):
        raise ParsingError("[more_like_this] malformed")
    like = body.get("like")
    if like is None:
        raise ParsingError("more_like_this requires 'like' to be specified")
    kwargs = {}
    for src_key, dst_key, conv in (
            ("max_query_terms", "max_query_terms", int),
            ("min_term_freq", "min_term_freq", int),
            ("min_doc_freq", "min_doc_freq", int),
            ("max_doc_freq", "max_doc_freq", int),
            ("minimum_should_match", "minimum_should_match", lambda v: v),
            ("include", "include", bool),
            ("boost", "boost", float)):
        if src_key in body:
            kwargs[dst_key] = conv(body[src_key])
    return MoreLikeThisQuery(like, body.get("unlike"),
                             body.get("fields"), **kwargs)


# ---------------------------------------------------------------------------
# distance_feature
# ---------------------------------------------------------------------------

_DIST_METERS = {"mm": 1e-3, "millimeters": 1e-3, "cm": 1e-2,
                "centimeters": 1e-2, "m": 1.0, "meters": 1.0,
                "km": 1000.0, "kilometers": 1000.0,
                "mi": 1609.344, "miles": 1609.344, "yd": 0.9144,
                "yards": 0.9144, "ft": 0.3048, "feet": 0.3048,
                "in": 0.0254, "inch": 0.0254, "nmi": 1852.0, "NM": 1852.0,
                "nauticalmiles": 1852.0, None: 1.0}
_DIST_RE = re.compile(
    r"^\s*(-?\d+(?:\.\d+)?)\s*(" +
    "|".join(sorted((u for u in _DIST_METERS if u), key=len, reverse=True)) +
    r")?\s*$")

EARTH_MEAN_RADIUS_M = 6371008.7714      # Lucene GeoUtils.EARTH_MEAN_RADIUS


def parse_distance_meters(value) -> float:
    if isinstance(value, (int, float)):
        return float(value)
    m = _DIST_RE.match(str(value))
    if not m:
        raise IllegalArgumentError(f"failed to parse distance [{value}]")
    return float(m.group(1)) * _DIST_METERS[m.group(2)]


def haversine_meters(lat1, lon1, lat2, lon2):
    """Vectorized great-circle distance (numpy) in meters."""
    p1, p2 = np.radians(lat1), np.radians(lat2)
    dp = p2 - p1
    dl = np.radians(lon2) - np.radians(lon1)
    a = (np.sin(dp / 2.0) ** 2 +
         np.cos(p1) * np.cos(p2) * np.sin(dl / 2.0) ** 2)
    return 2.0 * EARTH_MEAN_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


class DistanceFeatureQuery(Query):
    """score = boost · pivot / (pivot + distance(value, origin)); matches
    every doc that has the field (``DistanceFeatureQueryBuilder.java``)."""

    def __init__(self, field: str, origin, pivot, boost: float = 1.0):
        self.field = field
        self.origin = origin
        self.pivot = pivot
        self.boost = boost

    def execute(self, ctx, seg):
        field = ctx.concrete_field(self.field)
        ft = ctx.field_type(field)
        scores_host = np.zeros(seg.n_pad, np.float32)
        mask_host = np.zeros(seg.n_pad, bool)
        if isinstance(ft, GeoPointFieldType):
            lat = seg.numeric_fields.get(f"{field}._lat")
            lon = seg.numeric_fields.get(f"{field}._lon")
            if lat is None or lon is None or lat.vals_host.size == 0:
                return _const_result(seg, 0.0, False)
            olat, olon = GeoPointFieldType.parse_value(ft, self.origin)
            pivot_m = parse_distance_meters(self.pivot)
            if pivot_m <= 0:
                raise IllegalArgumentError(
                    f"[pivot] must be positive, got [{self.pivot}]")
            dist = haversine_meters(lat.vals_host, lon.vals_host, olat, olon)
            sc = self.boost * pivot_m / (pivot_m + dist)
            np.maximum.at(scores_host, lat.docs_host, sc.astype(np.float32))
            mask_host[lat.docs_host] = True
        elif isinstance(ft, DateFieldType):
            nf = seg.numeric_fields.get(field)
            if nf is None or nf.vals_host.size == 0:
                return _const_result(seg, 0.0, False)
            origin_ms = parse_date_millis(self.origin)
            pivot_ms = parse_time_millis(self.pivot)
            if pivot_ms <= 0:
                raise IllegalArgumentError(
                    f"[pivot] must be positive, got [{self.pivot}]")
            dist = np.abs(nf.vals_host - origin_ms)
            sc = self.boost * pivot_ms / (pivot_ms + dist)
            np.maximum.at(scores_host, nf.docs_host, sc.astype(np.float32))
            mask_host[nf.docs_host] = True
        else:
            raise IllegalArgumentError(
                f"field [{self.field}] is not a date or geo_point field")
        return jnp.asarray(scores_host), jnp.asarray(mask_host)


def _parse_distance_feature(body):
    if not isinstance(body, dict):
        raise ParsingError("[distance_feature] malformed")
    for req in ("field", "origin", "pivot"):
        if req not in body:
            raise ParsingError(f"[distance_feature] requires [{req}]")
    return DistanceFeatureQuery(body["field"], body["origin"], body["pivot"],
                                float(body.get("boost", 1.0)))


# ---------------------------------------------------------------------------
# registration (imported from query_dsl at module bottom — SPI hooks)
# ---------------------------------------------------------------------------

register_query_parser("intervals", _parse_intervals)
register_query_parser("more_like_this", _parse_more_like_this)
register_query_parser("distance_feature", _parse_distance_feature)
for _kind in ("span_term", "span_near", "span_or", "span_not", "span_first",
              "span_multi", "span_containing", "span_within",
              "field_masking_span"):
    register_query_parser(_kind, _make_span_parser(_kind))
