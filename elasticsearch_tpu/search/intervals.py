"""Minimal-interval algebra over segment position lists.

The engine behind the ``intervals`` query (reference:
``index/query/IntervalQueryBuilder.java`` + Lucene's
``queries/intervals/``) and the span family (reference:
``index/query/SpanNearQueryBuilder.java`` etc.). The reference delegates
to Lucene's lazy minimal-interval iterators; here candidate docs are
found with device postings masks first, then per-candidate interval sets
are computed host-side from the segment's position CSR — the same
device-filter → host-verify split the phrase query uses
(``query_dsl.MatchPhraseQuery``).

An interval is an inclusive ``(start, end)`` position pair. Sources
produce the MINIMAL intervals for a doc (no produced interval properly
contains another), matching Lucene's minimal interval semantics; filters
prune them against a second source's intervals.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Tuple

import numpy as np

Interval = Tuple[int, int]

#: cap on chains enumerated per doc per combiner — positions within one
#: document are sentence-scale; this guards pathological repetition.
MAX_CHAINS = 65536

#: cap on terms a multi-term source expands to (Lucene:
#: ``IntervalQueryBuilder`` expands through the same 128-term limit).
MAX_EXPANSIONS = 128


def _minimal(intervals: List[Interval]) -> List[Interval]:
    """Drop every interval that properly contains another one."""
    if len(intervals) <= 1:
        return intervals
    uniq = sorted(set(intervals))
    out = []
    for i, (s, e) in enumerate(uniq):
        contains_other = any(
            (s2, e2) != (s, e) and s2 >= s and e2 <= e
            for (s2, e2) in uniq)
        if not contains_other:
            out.append((s, e))
    return out


class IntervalSource:
    """One node of the interval expression tree, bound to a field."""

    field: str = ""

    def doc_candidates(self, seg) -> np.ndarray:
        """Local doc ids that MAY produce intervals (superset)."""
        raise NotImplementedError

    def intervals(self, seg, doc: int) -> List[Interval]:
        raise NotImplementedError

    def leaf_weights(self, seg) -> List[Tuple[str, str]]:
        """(field, term) pairs for scoring/idf purposes."""
        raise NotImplementedError


def _term_docs(seg, field: str, term: str) -> np.ndarray:
    f = seg.text_fields.get(field)
    if f is None:
        return np.empty(0, np.int32)
    start, length, _ = f.term_run(term)
    return f.docs_host[start:start + length]


def _term_positions(seg, field: str, term: str, doc: int) -> np.ndarray:
    f = seg.text_fields.get(field)
    if f is None:
        return np.empty(0, np.int32)
    return f.positions_for(term, doc)


class TermSource(IntervalSource):
    def __init__(self, field: str, term: str):
        self.field = field
        self.term = term

    def doc_candidates(self, seg):
        return _term_docs(seg, self.field, self.term)

    def intervals(self, seg, doc):
        return [(int(p), int(p))
                for p in _term_positions(seg, self.field, self.term, doc)]

    def leaf_weights(self, seg):
        return [(self.field, self.term)]


class ExpansionSource(IntervalSource):
    """Multi-term source: prefix / wildcard / fuzzy / regexp, expanded
    against each segment's term dictionary (capped at MAX_EXPANSIONS)."""

    def __init__(self, field: str, predicate, descr: str):
        self.field = field
        self.predicate = predicate      # term -> bool
        self.descr = descr
        self._cache = {}                # id(seg) -> expanded terms

    def _terms(self, seg) -> List[str]:
        key = id(seg)
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        f = seg.text_fields.get(self.field)
        out: List[str] = []
        if f is not None:
            for t in f.term_ids:
                if self.predicate(t):
                    out.append(t)
                    if len(out) >= MAX_EXPANSIONS:
                        break
        self._cache[key] = out
        return out

    def doc_candidates(self, seg):
        runs = [_term_docs(seg, self.field, t) for t in self._terms(seg)]
        if not runs:
            return np.empty(0, np.int32)
        return np.unique(np.concatenate(runs))

    def intervals(self, seg, doc):
        out: List[Interval] = []
        for t in self._terms(seg):
            out.extend((int(p), int(p))
                       for p in _term_positions(seg, self.field, t, doc))
        return sorted(set(out))

    def leaf_weights(self, seg):
        return [(self.field, t) for t in self._terms(seg)]


class CombineSource(IntervalSource):
    """all_of (ordered/unordered + max_gaps) over sub-sources."""

    def __init__(self, subs: Sequence[IntervalSource], ordered: bool,
                 max_gaps: int = -1):
        self.subs = list(subs)
        self.ordered = ordered
        self.max_gaps = max_gaps
        self.field = subs[0].field if subs else ""

    def doc_candidates(self, seg):
        runs = [s.doc_candidates(seg) for s in self.subs]
        if not runs or any(r.size == 0 for r in runs):
            return np.empty(0, np.int32)
        out = runs[0]
        for r in runs[1:]:
            out = np.intersect1d(out, r, assume_unique=False)
        return out

    def intervals(self, seg, doc):
        sub_ints = [s.intervals(seg, doc) for s in self.subs]
        if any(not si for si in sub_ints):
            return []
        total = 1
        for si in sub_ints:
            total *= len(si)
            if total > MAX_CHAINS:
                sub_ints = [si[:8] for si in sub_ints]   # bounded fallback
                break
        out: List[Interval] = []
        for chain in itertools.product(*sub_ints):
            if self.ordered:
                ok = all(chain[i + 1][0] > chain[i][1]
                         for i in range(len(chain) - 1))
                if not ok:
                    continue
            s = min(c[0] for c in chain)
            e = max(c[1] for c in chain)
            if not self.ordered:
                # unordered requires genuinely distinct sub-interval slots:
                # two subs may not collapse onto the identical interval
                if len({c for c in chain}) < len(chain):
                    continue
            if self.max_gaps >= 0:
                width = e - s + 1
                inner = sum(c[1] - c[0] + 1 for c in chain)
                if width - inner > self.max_gaps:
                    continue
            out.append((s, e))
        return _minimal(out)

    def leaf_weights(self, seg):
        out = []
        for s in self.subs:
            out.extend(s.leaf_weights(seg))
        return out


class AnyOfSource(IntervalSource):
    def __init__(self, subs: Sequence[IntervalSource]):
        self.subs = list(subs)
        self.field = subs[0].field if subs else ""

    def doc_candidates(self, seg):
        runs = [s.doc_candidates(seg) for s in self.subs]
        runs = [r for r in runs if r.size]
        if not runs:
            return np.empty(0, np.int32)
        return np.unique(np.concatenate(runs))

    def intervals(self, seg, doc):
        out: List[Interval] = []
        for s in self.subs:
            out.extend(s.intervals(seg, doc))
        return _minimal(out)

    def leaf_weights(self, seg):
        out = []
        for s in self.subs:
            out.extend(s.leaf_weights(seg))
        return out


class FilteredSource(IntervalSource):
    """Applies an interval filter (containing / overlapping / before / …)
    from the reference's ``IntervalFilterBuilder``."""

    KINDS = ("containing", "not_containing", "contained_by",
             "not_contained_by", "overlapping", "not_overlapping",
             "before", "after")

    def __init__(self, source: IntervalSource, kind: str,
                 reference: IntervalSource):
        self.source = source
        self.kind = kind
        self.reference = reference
        self.field = source.field

    def doc_candidates(self, seg):
        return self.source.doc_candidates(seg)

    def intervals(self, seg, doc):
        ints = self.source.intervals(seg, doc)
        if not ints:
            return []
        refs = self.reference.intervals(seg, doc)
        kind = self.kind
        out = []
        for (s, e) in ints:
            if kind == "containing":
                keep = any(fs >= s and fe <= e for fs, fe in refs)
            elif kind == "not_containing":
                keep = not any(fs >= s and fe <= e for fs, fe in refs)
            elif kind == "contained_by":
                keep = any(s >= fs and e <= fe for fs, fe in refs)
            elif kind == "not_contained_by":
                keep = not any(s >= fs and e <= fe for fs, fe in refs)
            elif kind == "overlapping":
                keep = any(fs <= e and fe >= s for fs, fe in refs)
            elif kind == "not_overlapping":
                keep = not any(fs <= e and fe >= s for fs, fe in refs)
            elif kind == "before":
                keep = any(e < fs for fs, fe in refs)
            elif kind == "after":
                keep = any(s > fe for fs, fe in refs)
            else:
                keep = True
            if keep:
                out.append((s, e))
        return out

    def leaf_weights(self, seg):
        return self.source.leaf_weights(seg)


class FirstSource(IntervalSource):
    """span_first: intervals ending within the first ``end`` positions."""

    def __init__(self, source: IntervalSource, end: int):
        self.source = source
        self.end = end
        self.field = source.field

    def doc_candidates(self, seg):
        return self.source.doc_candidates(seg)

    def intervals(self, seg, doc):
        return [(s, e) for s, e in self.source.intervals(seg, doc)
                if e < self.end]

    def leaf_weights(self, seg):
        return self.source.leaf_weights(seg)


class NotNearSource(IntervalSource):
    """span_not: include intervals with no exclude interval within
    ``pre`` positions before or ``post`` positions after."""

    def __init__(self, include: IntervalSource, exclude: IntervalSource,
                 pre: int = 0, post: int = 0):
        self.include = include
        self.exclude = exclude
        self.pre = pre
        self.post = post
        self.field = include.field

    def doc_candidates(self, seg):
        return self.include.doc_candidates(seg)

    def intervals(self, seg, doc):
        ints = self.include.intervals(seg, doc)
        if not ints:
            return []
        excl = self.exclude.intervals(seg, doc)
        if not excl:
            return ints
        out = []
        for (s, e) in ints:
            lo, hi = s - self.pre, e + self.post
            if not any(fs <= hi and fe >= lo for fs, fe in excl):
                out.append((s, e))
        return out

    def leaf_weights(self, seg):
        return self.include.leaf_weights(seg)
