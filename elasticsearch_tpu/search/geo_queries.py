"""Geo queries (geo_shape / geo_bounding_box / geo_distance), the
rank_feature query, and the pinned query.

Reference: ``index/query/{GeoShapeQueryBuilder,GeoBoundingBoxQueryBuilder,
GeoDistanceQueryBuilder}.java``, ``mapper-extras/.../
RankFeatureQueryBuilder.java``, and ``x-pack/plugin/
search-business-rules/.../PinnedQueryBuilder.java``.

Design split: the point-based filters (bounding box, distance) are
vectorized numpy over the geo_point ``._lat``/``._lon`` doc-value
columns — a single fused comparison over the whole segment, the same
columns the device aggs read.  geo_shape relations run per matching doc
against geometries parsed out of _source with a per-segment cache
(search/geometry.py documents the trade vs the reference's BKD
triangles); a bbox pre-filter on the indexed ``._minx``… columns skips
the exact predicate for segments/docs that cannot match.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..common.errors import IllegalArgumentError
from ..index.mapping import (GeoPointFieldType, GeoShapeFieldType,
                             RankFeatureFieldType, RankFeaturesFieldType)
from .geometry import Geometry, parse_geometry, relate
from .query_dsl import (ParsingError, Query, _const_result, jnp,
                        parse_query, register_query_parser)

# .positional helpers (haversine_meters, parse_distance_meters) import
# lazily inside execute() — positional itself imports query_dsl, whose
# module-bottom SPI imports land here before positional finishes


def _geo_helpers():
    from .positional import haversine_meters, parse_distance_meters
    return haversine_meters, parse_distance_meters


def _latlon(seg, field):
    lat = seg.numeric_fields.get(f"{field}._lat")
    lon = seg.numeric_fields.get(f"{field}._lon")
    if lat is None or lon is None or lat.vals_host.size == 0:
        return None
    return lat, lon


def _mask_result(seg, mask_host, boost):
    mask = jnp.asarray(mask_host)
    return jnp.where(mask, np.float32(boost), 0.0), mask


class GeoBoundingBoxQuery(Query):
    def __init__(self, field: str, top: float, left: float,
                 bottom: float, right: float, boost: float = 1.0):
        self.field = field
        self.top, self.left = top, left
        self.bottom, self.right = bottom, right
        self.boost = boost

    def execute(self, ctx, seg):
        field = ctx.concrete_field(self.field)
        cols = _latlon(seg, field)
        if cols is None:
            return _const_result(seg, 0.0, False)
        lat, lon = cols
        ok_lat = (lat.vals_host >= self.bottom) & \
            (lat.vals_host <= self.top)
        if self.left <= self.right:
            ok_lon = (lon.vals_host >= self.left) & \
                (lon.vals_host <= self.right)
        else:                               # box crossing the dateline
            ok_lon = (lon.vals_host >= self.left) | \
                (lon.vals_host <= self.right)
        mask_host = np.zeros(seg.n_pad, bool)
        mask_host[lat.docs_host[ok_lat & ok_lon]] = True
        return _mask_result(seg, mask_host, self.boost)


class GeoDistanceQuery(Query):
    def __init__(self, field: str, origin, distance, boost: float = 1.0):
        self.field = field
        self.origin = origin
        self.distance = distance
        self.boost = boost

    def execute(self, ctx, seg):
        field = ctx.concrete_field(self.field)
        cols = _latlon(seg, field)
        if cols is None:
            return _const_result(seg, 0.0, False)
        lat, lon = cols
        haversine_meters, parse_distance_meters = _geo_helpers()
        olat, olon = GeoPointFieldType.parse_value(
            ctx.field_type(field) or GeoPointFieldType(field),
            self.origin)
        dist_m = parse_distance_meters(self.distance)
        d = haversine_meters(lat.vals_host, lon.vals_host, olat, olon)
        mask_host = np.zeros(seg.n_pad, bool)
        mask_host[lat.docs_host[d <= dist_m]] = True
        return _mask_result(seg, mask_host, self.boost)


class GeoShapeQuery(Query):
    def __init__(self, field: str, shape: Geometry, relation: str,
                 boost: float = 1.0, ignore_unmapped: bool = False):
        self.field = field
        self.shape = shape
        self.relation = relation
        self.boost = boost
        self.ignore_unmapped = ignore_unmapped

    def _doc_geometries(self, seg, field):
        """Per-doc parsed geometries, cached on the segment (segments
        are immutable, so the cache lives as long as the geometry
        columns do)."""
        cache = getattr(seg, "_geo_shape_cache", None)
        if cache is None:
            cache = seg._geo_shape_cache = {}
        if field in cache:
            return cache[field]
        per_doc: List[Optional[Geometry]] = [None] * seg.n_docs
        for i, src in enumerate(seg.sources):
            if not src or not seg.parent_mask[i]:
                continue
            # dotted traversal flattening object arrays, like the
            # reference's source lookup
            nodes = [src]
            for part in field.split("."):
                nxt = []
                for node in nodes:
                    if isinstance(node, list):
                        node = [n for n in node if isinstance(n, dict)]
                        nxt.extend(n[part] for n in node if part in n)
                    elif isinstance(node, dict) and part in node:
                        nxt.append(node[part])
                nodes = nxt
            if not nodes:
                continue
            values = []
            for node in nodes:
                if isinstance(node, list) and not (
                        node and isinstance(node[0], (int, float))):
                    values.extend(node)
                else:
                    values.append(node)
            g = Geometry()
            for v in values:
                try:
                    sub = parse_geometry(v)
                except Exception:   # noqa: BLE001 — tolerate odd source
                    continue
                g.points.extend(sub.points)
                g.lines.extend(sub.lines)
                g.polygons.extend(sub.polygons)
            if not g.empty:
                per_doc[i] = g
        cache[field] = per_doc
        return per_doc

    def execute(self, ctx, seg):
        field = ctx.concrete_field(self.field)
        ft = ctx.field_type(field)
        if ft is None:
            if self.ignore_unmapped:
                return _const_result(seg, 0.0, False)
            from ..common.errors import QueryShardError
            raise QueryShardError(
                f"failed to find type for field [{self.field}]")
        mask_host = np.zeros(seg.n_pad, bool)
        if isinstance(ft, GeoPointFieldType):
            cols = _latlon(seg, field)
            if cols is None:
                return _const_result(seg, 0.0, False)
            lat, lon = cols
            # group multi-valued points per doc: within/disjoint are
            # ALL-points relations, not any-point
            by_doc = {}
            for doc, la, lo in zip(lat.docs_host, lat.vals_host,
                                   lon.vals_host):
                by_doc.setdefault(int(doc), Geometry()).add_point(
                    float(lo), float(la))
            for doc, g in by_doc.items():
                if relate(g, self.shape, self.relation):
                    mask_host[doc] = True
            return _mask_result(seg, mask_host, self.boost)
        if not isinstance(ft, GeoShapeFieldType):
            from ..common.errors import QueryShardError
            raise QueryShardError(
                f"Field [{self.field}] is of unsupported type "
                f"[{ft.type_name}] for [geo_shape] query")
        # coarse reject on the indexed bbox columns: only docs whose
        # bbox interacts with the query bbox run the exact predicate
        # (disjoint/contains must still check every doc)
        candidates = None
        minx = seg.numeric_fields.get(f"{field}._minx")
        if minx is not None and self.relation in ("intersects", "within") \
                and not self.shape.empty:
            qx1, qy1, qx2, qy2 = self.shape.bbox()
            maxx = seg.numeric_fields[f"{field}._maxx"]
            miny = seg.numeric_fields[f"{field}._miny"]
            maxy = seg.numeric_fields[f"{field}._maxy"]
            ok = ~((maxx.vals_host < qx1) | (minx.vals_host > qx2)
                   | (maxy.vals_host < qy1) | (miny.vals_host > qy2))
            candidates = set(int(d) for d in minx.docs_host[ok])
        per_doc = self._doc_geometries(seg, field)
        for i, g in enumerate(per_doc):
            if g is None:
                # docs without the field never match intersects/within/
                # contains, and DO match disjoint only when they have
                # the field in ES — no field, no match, all relations
                continue
            if candidates is not None and i not in candidates:
                continue
            if relate(g, self.shape, self.relation):
                mask_host[i] = True
        return _mask_result(seg, mask_host, self.boost)


class RankFeatureQuery(Query):
    """score = boost · f(value); matches docs that have the feature
    (``RankFeatureQueryBuilder.java``: saturation / log / sigmoid /
    linear)."""

    def __init__(self, field: str, function: str, opts: dict,
                 boost: float = 1.0):
        self.field = field
        self.function = function
        self.opts = opts
        self.boost = boost

    def execute(self, ctx, seg):
        field = ctx.concrete_field(self.field)
        ft = ctx.field_type(field)
        root = field.split(".", 1)[0]
        root_ft = ctx.field_type(root)
        if not isinstance(ft, (RankFeatureFieldType,
                               RankFeaturesFieldType)) and \
                not isinstance(root_ft, RankFeaturesFieldType):
            from ..common.errors import QueryShardError
            raise QueryShardError(
                f"[rank_feature] query only works on [rank_feature] "
                f"fields, not [{ft.type_name if ft else None}]")
        positive = True
        for t in (ft, root_ft):
            if isinstance(t, (RankFeatureFieldType,
                              RankFeaturesFieldType)):
                positive = t.positive_score_impact
                break
        nf = seg.numeric_fields.get(field)
        if nf is None or nf.vals_host.size == 0:
            return _const_result(seg, 0.0, False)
        v = nf.vals_host.astype(np.float64)
        fn = self.function
        if fn == "saturation":
            pivot = self.opts.get("pivot")
            if pivot is None:
                # the reference computes an approximate geometric mean
                # when pivot is omitted
                pivot = float(np.exp(np.mean(np.log(np.maximum(
                    v, 1e-9)))))
            pivot = float(pivot)
            sc = v / (v + pivot) if positive else pivot / (v + pivot)
        else:
            # negative-impact fields store the reciprocal in the
            # reference, making EVERY function decrease with the value
            fv = v if positive else 1.0 / np.maximum(v, 1e-9)
            if fn == "log":
                scaling = float(self.opts.get("scaling_factor", 1.0))
                sc = np.log(scaling + fv)
            elif fn == "sigmoid":
                pivot = float(self.opts["pivot"])
                exponent = float(self.opts["exponent"])
                vp = np.power(fv, exponent)
                sc = vp / (vp + pivot ** exponent)
            elif fn == "linear":
                sc = fv
            else:
                sc = None
        if sc is None:
            raise ParsingError(
                f"unknown function [{fn}] for [rank_feature] query")
        scores_host = np.zeros(seg.n_pad, np.float32)
        mask_host = np.zeros(seg.n_pad, bool)
        np.maximum.at(scores_host, nf.docs_host,
                      (self.boost * sc).astype(np.float32))
        mask_host[nf.docs_host] = True
        return jnp.asarray(scores_host), jnp.asarray(mask_host)


class PinnedQuery(Query):
    """Promote the given ids above every organic hit, in the listed
    order (``PinnedQueryBuilder.java`` — implemented there with giant
    per-id boosts above the organic score range; same trick here)."""

    # within float32 integer-exact range (eps(1e7)=1) so BASE - rank
    # stays strictly decreasing; organic scores never approach 1e7
    _PIN_BASE = np.float32(1e7)

    def __init__(self, ids: List[str], organic: Query,
                 boost: float = 1.0):
        self.ids = ids
        self.organic = organic
        self.boost = boost

    def execute(self, ctx, seg):
        scores, mask = self.organic.execute(ctx, seg)
        scores_host = np.asarray(scores).copy()
        mask_host = np.asarray(mask).copy()
        for rank, doc_id in enumerate(self.ids):
            doc = seg._uid_to_doc.get(str(doc_id))
            if doc is None or not seg.live[doc]:
                continue
            scores_host[doc] = self._PIN_BASE - rank
            mask_host[doc] = True
        return jnp.asarray(scores_host), jnp.asarray(mask_host)


# ---------------------------------------------------------------------------
# parsers
# ---------------------------------------------------------------------------

def _parse_geo_bounding_box(body):
    opts = dict(body or {})
    boost = float(opts.pop("boost", 1.0))
    opts.pop("validation_method", None)
    opts.pop("type", None)
    opts.pop("ignore_unmapped", None)
    opts.pop("_name", None)
    if len(opts) != 1:
        raise ParsingError(
            "[geo_bounding_box] query requires exactly one field")
    (field, spec), = opts.items()
    gp = GeoPointFieldType(field)
    if "wkt" in spec:
        g = parse_geometry(spec["wkt"])
        left, bottom, right, top = g.bbox()
    elif "top_left" in spec or "topLeft" in spec:
        tl = GeoPointFieldType.parse_value(
            gp, spec.get("top_left", spec.get("topLeft")))
        br = GeoPointFieldType.parse_value(
            gp, spec.get("bottom_right", spec.get("bottomRight")))
        top, left = tl
        bottom, right = br
    elif "top_right" in spec:
        tr = GeoPointFieldType.parse_value(gp, spec["top_right"])
        bl = GeoPointFieldType.parse_value(gp, spec["bottom_left"])
        top, right = tr
        bottom, left = bl
    else:
        try:
            top = float(spec["top"])
            left = float(spec["left"])
            bottom = float(spec["bottom"])
            right = float(spec["right"])
        except KeyError as e:
            raise ParsingError(
                f"failed to parse [geo_bounding_box] query: missing "
                f"{e}")
    if top < bottom:
        raise ParsingError(
            f"top is below bottom corner: {top} vs. {bottom}")
    return GeoBoundingBoxQuery(field, top, left, bottom, right, boost)


def _parse_geo_distance(body):
    opts = dict(body or {})
    boost = float(opts.pop("boost", 1.0))
    distance = opts.pop("distance", None)
    if distance is None:
        raise ParsingError("geo_distance requires [distance]")
    opts.pop("distance_type", None)
    opts.pop("validation_method", None)
    opts.pop("ignore_unmapped", None)
    opts.pop("_name", None)
    if len(opts) != 1:
        raise ParsingError(
            "[geo_distance] query requires exactly one field")
    (field, origin), = opts.items()
    return GeoDistanceQuery(field, origin, distance, boost)


def _parse_geo_shape(body):
    opts = dict(body or {})
    boost = float(opts.pop("boost", 1.0))
    ignore_unmapped = bool(opts.pop("ignore_unmapped", False))
    opts.pop("_name", None)
    if len(opts) != 1:
        raise ParsingError(
            "[geo_shape] query requires exactly one field")
    (field, spec), = opts.items()
    if not isinstance(spec, dict):
        raise ParsingError("[geo_shape] malformed query")
    if "indexed_shape" in spec:
        raise ParsingError(
            "[geo_shape] indexed_shape is not supported — inline the "
            "[shape] definition")
    shape = spec.get("shape")
    if shape is None:
        raise ParsingError("[geo_shape] requires a [shape]")
    try:
        geom = parse_geometry(shape)
    except Exception as e:
        raise ParsingError(f"[geo_shape] failed to parse shape: {e}")
    return GeoShapeQuery(field, geom,
                         spec.get("relation", "intersects"), boost,
                         ignore_unmapped)


def _parse_rank_feature(body):
    if not isinstance(body, dict) or "field" not in body:
        raise ParsingError("[rank_feature] query requires [field]")
    opts = dict(body)
    field = opts.pop("field")
    boost = float(opts.pop("boost", 1.0))
    opts.pop("_name", None)
    functions = [k for k in ("saturation", "log", "sigmoid", "linear")
                 if k in opts]
    if len(functions) > 1:
        raise ParsingError(
            "[rank_feature] query can only have one of [saturation], "
            "[log], [sigmoid], [linear]")
    fn = functions[0] if functions else "saturation"
    fn_opts = opts.get(fn) or {}
    if fn == "log" and "scaling_factor" not in fn_opts:
        raise ParsingError(
            "[rank_feature] [log] function requires [scaling_factor]")
    if fn == "sigmoid" and ("pivot" not in fn_opts
                            or "exponent" not in fn_opts):
        raise ParsingError(
            "[rank_feature] [sigmoid] function requires [pivot] and "
            "[exponent]")
    return RankFeatureQuery(field, fn, fn_opts, boost)


def _parse_pinned(body):
    if not isinstance(body, dict):
        raise ParsingError("[pinned] malformed query")
    ids = body.get("ids")
    if ids is None:
        raise ParsingError("[pinned] query requires [ids]")
    organic_spec = body.get("organic")
    if organic_spec is None:
        raise ParsingError("[pinned] query requires [organic]")
    return PinnedQuery([str(i) for i in ids],
                       parse_query(organic_spec),
                       float(body.get("boost", 1.0)))


register_query_parser("geo_bounding_box", _parse_geo_bounding_box)
register_query_parser("geo_distance", _parse_geo_distance)
register_query_parser("geo_shape", _parse_geo_shape)
register_query_parser("rank_feature", _parse_rank_feature)
register_query_parser("pinned", _parse_pinned)
