"""Geometry parsing + spatial relations for ``geo_shape``.

Reference: ``x-pack/plugin/spatial/`` + ``server/.../common/geo/`` —
``GeoShapeQueryBuilder`` parses GeoJSON/WKT into a ``Geometry`` tree and
evaluates INTERSECTS / DISJOINT / WITHIN / CONTAINS against BKD-indexed
triangles.  Here geometries normalize into primitive lists (points,
lines, polygons-with-holes) and relations evaluate with exact
host-side predicates (ray-cast point-in-polygon, orientation-test
segment intersection) — O(vertices) per doc instead of a BKD tree,
the right trade for this build where geo_shape docs are orders of
magnitude rarer than text (the hot path stays on device).

Supported input: GeoJSON (Point, MultiPoint, LineString,
MultiLineString, Polygon, MultiPolygon, GeometryCollection + the ES
``envelope`` extension) and WKT (POINT, MULTIPOINT, LINESTRING,
MULTILINESTRING, POLYGON, MULTIPOLYGON, ENVELOPE, GEOMETRYCOLLECTION).
Coordinates are [lon, lat] like the reference.
"""
from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

from ..common.errors import IllegalArgumentError

Coord = Tuple[float, float]                      # (lon, lat)
Ring = List[Coord]


class Geometry:
    """Normalized form: bags of primitives + a bounding box."""

    def __init__(self):
        self.points: List[Coord] = []
        self.lines: List[Ring] = []
        #: each polygon is (shell, [holes...]) with closed rings
        self.polygons: List[Tuple[Ring, List[Ring]]] = []

    # -- construction ---------------------------------------------------
    def add_point(self, lon: float, lat: float) -> None:
        self.points.append((float(lon), float(lat)))

    def add_line(self, coords: Sequence[Sequence[float]]) -> None:
        if len(coords) < 2:
            raise IllegalArgumentError(
                "at least two points required for linestring")
        self.lines.append([(float(c[0]), float(c[1])) for c in coords])

    def add_polygon(self, rings: Sequence[Sequence[Sequence[float]]]
                    ) -> None:
        if not rings:
            raise IllegalArgumentError("polygon requires a shell ring")
        norm: List[Ring] = []
        for ring in rings:
            r = [(float(c[0]), float(c[1])) for c in ring]
            if len(r) < 4 or r[0] != r[-1]:
                raise IllegalArgumentError(
                    "invalid LinearRing: must be closed with at least "
                    "4 points")
            norm.append(r)
        self.polygons.append((norm[0], norm[1:]))

    def add_envelope(self, coords) -> None:
        """ES envelope: [[minLon, maxLat], [maxLon, minLat]]."""
        (x1, y2), (x2, y1) = ((float(coords[0][0]), float(coords[0][1])),
                              (float(coords[1][0]), float(coords[1][1])))
        shell = [(x1, y1), (x2, y1), (x2, y2), (x1, y2), (x1, y1)]
        self.polygons.append((shell, []))

    @property
    def empty(self) -> bool:
        return not (self.points or self.lines or self.polygons)

    def bbox(self) -> Tuple[float, float, float, float]:
        xs: List[float] = []
        ys: List[float] = []
        for x, y in self.points:
            xs.append(x)
            ys.append(y)
        for line in self.lines:
            for x, y in line:
                xs.append(x)
                ys.append(y)
        for shell, _holes in self.polygons:
            for x, y in shell:
                xs.append(x)
                ys.append(y)
        return (min(xs), min(ys), max(xs), max(ys))


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------

def parse_geometry(value) -> Geometry:
    if isinstance(value, str):
        return _parse_wkt(value)
    if isinstance(value, dict):
        g = Geometry()
        _parse_geojson(value, g)
        return g
    raise IllegalArgumentError(
        f"unable to parse geometry from [{value!r}]")


def _parse_geojson(obj: dict, g: Geometry) -> None:
    t = str(obj.get("type", "")).lower()
    coords = obj.get("coordinates")
    if t == "point":
        g.add_point(coords[0], coords[1])
    elif t == "multipoint":
        for c in coords:
            g.add_point(c[0], c[1])
    elif t == "linestring":
        g.add_line(coords)
    elif t == "multilinestring":
        for line in coords:
            g.add_line(line)
    elif t == "polygon":
        g.add_polygon(coords)
    elif t == "multipolygon":
        for rings in coords:
            g.add_polygon(rings)
    elif t == "envelope":
        g.add_envelope(coords)
    elif t == "geometrycollection":
        for sub in obj.get("geometries") or []:
            _parse_geojson(sub, g)
    else:
        raise IllegalArgumentError(f"unknown geometry type [{t}]")


_WKT_NUM = r"[-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?"


def _wkt_coords(text: str) -> List[Coord]:
    out = []
    for pair in text.split(","):
        nums = re.findall(_WKT_NUM, pair)
        if len(nums) < 2:
            raise IllegalArgumentError(
                f"invalid WKT coordinates [{pair.strip()}]")
        out.append((float(nums[0]), float(nums[1])))
    return out


def _split_rings(body: str) -> List[str]:
    """Split '(r1), (r2)' at depth-0 commas."""
    parts, depth, cur = [], 0, []
    for ch in body:
        if ch == "(":
            depth += 1
            if depth == 1:
                cur = []
                continue
        elif ch == ")":
            depth -= 1
            if depth == 0:
                parts.append("".join(cur))
                continue
        if depth >= 1:
            cur.append(ch)
    return parts


def _parse_wkt(text: str) -> Geometry:
    g = Geometry()
    _parse_wkt_into(text.strip(), g)
    return g


def _parse_wkt_into(text: str, g: Geometry) -> None:
    m = re.match(r"\s*([A-Za-z]+)\s*\((.*)\)\s*$", text, re.S)
    if m is None:
        raise IllegalArgumentError(f"unable to parse WKT [{text}]")
    kind = m.group(1).upper()
    body = m.group(2).strip()
    if kind == "POINT":
        (c,) = _wkt_coords(body)
        g.add_point(*c)
    elif kind == "MULTIPOINT":
        cleaned = body.replace("(", "").replace(")", "")
        for c in _wkt_coords(cleaned):
            g.add_point(*c)
    elif kind == "LINESTRING":
        g.add_line(_wkt_coords(body))
    elif kind == "MULTILINESTRING":
        for seg in _split_rings(body):
            g.add_line(_wkt_coords(seg))
    elif kind == "POLYGON":
        g.add_polygon([_wkt_coords(r) for r in _split_rings(body)])
    elif kind == "MULTIPOLYGON":
        depth, cur, polys = 0, [], []
        for ch in body:
            if ch == "(":
                depth += 1
                if depth == 1:
                    cur = []
                    continue
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    polys.append("".join(cur))
                    continue
            if depth >= 1:
                cur.append(ch)
        for p in polys:
            g.add_polygon([_wkt_coords(r) for r in _split_rings(p)])
    elif kind == "ENVELOPE":
        # WKT ENVELOPE(minLon, maxLon, maxLat, minLat) — ES order
        nums = [float(x) for x in re.findall(_WKT_NUM, body)]
        if len(nums) != 4:
            raise IllegalArgumentError(f"invalid ENVELOPE [{body}]")
        g.add_envelope([[nums[0], nums[2]], [nums[1], nums[3]]])
    elif kind == "GEOMETRYCOLLECTION":
        depth, cur, subs = 0, [], []
        start = 0
        # split top-level geometries at depth-0 commas
        for i, ch in enumerate(body):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            elif ch == "," and depth == 0:
                subs.append(body[start:i])
                start = i + 1
        subs.append(body[start:])
        for s in subs:
            _parse_wkt_into(s.strip(), g)
    else:
        raise IllegalArgumentError(f"unknown WKT type [{kind}]")


# ---------------------------------------------------------------------------
# predicates
# ---------------------------------------------------------------------------

def _orient(a: Coord, b: Coord, c: Coord) -> float:
    return (b[0] - a[0]) * (c[1] - a[1]) - (b[1] - a[1]) * (c[0] - a[0])


def _on_segment(a: Coord, b: Coord, p: Coord) -> bool:
    return (min(a[0], b[0]) - 1e-12 <= p[0] <= max(a[0], b[0]) + 1e-12
            and min(a[1], b[1]) - 1e-12 <= p[1]
            <= max(a[1], b[1]) + 1e-12)


def _segments_intersect(a: Coord, b: Coord, c: Coord, d: Coord) -> bool:
    o1, o2 = _orient(a, b, c), _orient(a, b, d)
    o3, o4 = _orient(c, d, a), _orient(c, d, b)
    if ((o1 > 0) != (o2 > 0)) and ((o3 > 0) != (o4 > 0)) \
            and o1 != 0 and o2 != 0 and o3 != 0 and o4 != 0:
        return True
    if o1 == 0 and _on_segment(a, b, c):
        return True
    if o2 == 0 and _on_segment(a, b, d):
        return True
    if o3 == 0 and _on_segment(c, d, a):
        return True
    if o4 == 0 and _on_segment(c, d, b):
        return True
    return False


def _point_in_ring(p: Coord, ring: Ring) -> bool:
    """Ray cast; boundary counts as inside."""
    x, y = p
    inside = False
    for i in range(len(ring) - 1):
        a, b = ring[i], ring[i + 1]
        if _orient(a, b, p) == 0 and _on_segment(a, b, p):
            return True
        if (a[1] > y) != (b[1] > y):
            xi = a[0] + (y - a[1]) * (b[0] - a[0]) / (b[1] - a[1])
            if x < xi:
                inside = not inside
    return inside


def _point_in_polygon(p: Coord, poly: Tuple[Ring, List[Ring]]) -> bool:
    shell, holes = poly
    if not _point_in_ring(p, shell):
        return False
    for h in holes:
        if _point_in_ring(p, h) and not _on_ring_boundary(p, h):
            return False
    return True


def _on_ring_boundary(p: Coord, ring: Ring) -> bool:
    for i in range(len(ring) - 1):
        if _orient(ring[i], ring[i + 1], p) == 0 and \
                _on_segment(ring[i], ring[i + 1], p):
            return True
    return False


def _rings_of(poly: Tuple[Ring, List[Ring]]) -> List[Ring]:
    return [poly[0]] + list(poly[1])


def _line_intersects_polygon(line: Ring,
                             poly: Tuple[Ring, List[Ring]]) -> bool:
    for p in line:
        if _point_in_polygon(p, poly):
            return True
    for ring in _rings_of(poly):
        for i in range(len(line) - 1):
            for j in range(len(ring) - 1):
                if _segments_intersect(line[i], line[i + 1],
                                       ring[j], ring[j + 1]):
                    return True
    return False


def _polygons_intersect(p1, p2) -> bool:
    if any(_point_in_polygon(v, p2) for v in p1[0]):
        return True
    if any(_point_in_polygon(v, p1) for v in p2[0]):
        return True
    for r1 in _rings_of(p1):
        for r2 in _rings_of(p2):
            for i in range(len(r1) - 1):
                for j in range(len(r2) - 1):
                    if _segments_intersect(r1[i], r1[i + 1],
                                           r2[j], r2[j + 1]):
                        return True
    return False


def _lines_intersect(l1: Ring, l2: Ring) -> bool:
    for i in range(len(l1) - 1):
        for j in range(len(l2) - 1):
            if _segments_intersect(l1[i], l1[i + 1], l2[j], l2[j + 1]):
                return True
    return False


def intersects(a: Geometry, b: Geometry) -> bool:
    # cheap bbox reject first
    if a.empty or b.empty:
        return False
    ax1, ay1, ax2, ay2 = a.bbox()
    bx1, by1, bx2, by2 = b.bbox()
    if ax2 < bx1 or bx2 < ax1 or ay2 < by1 or by2 < ay1:
        return False
    for p in a.points:
        if any(abs(p[0] - q[0]) < 1e-12 and abs(p[1] - q[1]) < 1e-12
               for q in b.points):
            return True
        if any(_on_line(p, line) for line in b.lines):
            return True
        if any(_point_in_polygon(p, poly) for poly in b.polygons):
            return True
    for line in a.lines:
        if any(_on_line(q, line) for q in b.points):
            return True
        if any(_lines_intersect(line, l2) for l2 in b.lines):
            return True
        if any(_line_intersects_polygon(line, poly)
               for poly in b.polygons):
            return True
    for poly in a.polygons:
        if any(_point_in_polygon(q, poly) for q in b.points):
            return True
        if any(_line_intersects_polygon(l2, poly) for l2 in b.lines):
            return True
        if any(_polygons_intersect(poly, p2) for p2 in b.polygons):
            return True
    return False


def _on_line(p: Coord, line: Ring) -> bool:
    for i in range(len(line) - 1):
        if _orient(line[i], line[i + 1], p) == 0 and \
                _on_segment(line[i], line[i + 1], p):
            return True
    return False


def _line_within_polygon(line: Ring,
                         poly: Tuple[Ring, List[Ring]]) -> bool:
    # all vertices inside, and each segment midpoint too (catches
    # concave escapes and hole crossings between two inside vertices)
    if not all(_point_in_polygon(p, poly) for p in line):
        return False
    for i in range(len(line) - 1):
        mid = ((line[i][0] + line[i + 1][0]) / 2,
               (line[i][1] + line[i + 1][1]) / 2)
        if not _point_in_polygon(mid, poly):
            return False
    return True


def _polygon_within_polygon(inner, outer) -> bool:
    if not all(_point_in_polygon(v, outer) for v in inner[0]):
        return False
    # no boundary crossing
    for r1 in _rings_of(inner):
        for r2 in _rings_of(outer):
            for i in range(len(r1) - 1):
                for j in range(len(r2) - 1):
                    a, b = r1[i], r1[i + 1]
                    c, d = r2[j], r2[j + 1]
                    o1, o2 = _orient(c, d, a), _orient(c, d, b)
                    if (o1 > 0) != (o2 > 0) and o1 != 0 and o2 != 0 \
                            and ((_orient(a, b, c) > 0)
                                 != (_orient(a, b, d) > 0)):
                        return False
    # an outer hole lying inside the inner shell means the inner
    # polygon covers excluded area (hole swallowed whole — no edge
    # crossings to catch it above)
    for hole in outer[1]:
        if any(_point_in_ring(v, inner[0])
               and not _on_ring_boundary(v, inner[0])
               for v in hole[:-1]):
            return False
    return True


def within(a: Geometry, b: Geometry) -> bool:
    """Every part of ``a`` lies inside ``b`` (b must have area)."""
    if a.empty or not b.polygons:
        return False
    for p in a.points:
        if not any(_point_in_polygon(p, poly) for poly in b.polygons):
            return False
    for line in a.lines:
        if not any(_line_within_polygon(line, poly)
                   for poly in b.polygons):
            return False
    for poly in a.polygons:
        if not any(_polygon_within_polygon(poly, outer)
                   for outer in b.polygons):
            return False
    return True


def relate(doc: Geometry, query: Geometry, relation: str) -> bool:
    relation = relation.lower()
    if relation == "intersects":
        return intersects(doc, query)
    if relation == "disjoint":
        return not intersects(doc, query)
    if relation == "within":
        return within(doc, query)
    if relation == "contains":
        return within(query, doc)
    raise IllegalArgumentError(
        f"invalid relation [{relation}]: must be one of [intersects, "
        f"disjoint, within, contains]")
