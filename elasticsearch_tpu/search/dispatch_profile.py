"""Dispatch timeline profiler: a bounded ring of per-dispatch records
with a Chrome trace-event renderer.

PR 3's stage timings say how long a dispatch's queue/prep/execute/fetch
took IN AGGREGATE; nothing shows how the PIPELINE_DEPTH=2 dispatcher
threads, co-batching decisions, and device execution actually overlap
in time. This module is that surface:

- :class:`DispatchProfileRing` — a lock-light bounded ring (the
  flight-recorder shape: ``dispatch_profile.ring.size`` /
  ``ES_TPU_DISPATCH_PROFILE_CAP``, default 2048). Each micro-batch
  dispatch appends ONE record from the dispatcher loop in
  ``search/microbatch.py`` — OUTSIDE ``_cond`` (ESTP-L02 treats this
  module like ``common/telemetry``): wall + monotonic start/end per
  stage (queue-drain, host prep, device execute, fetch), the
  dispatcher thread id, bucket key/params, batch composition (request
  count, dedup lane count, k bucket, view size, mesh axes), h2d/d2h
  bytes, compile-cache verdict, kernel family, and the roofline audit
  (``common/roofline.py``). Flightrec ``slow_dispatch`` events carry
  the record's ``seq`` so the two journals cross-link.

- :func:`chrome_trace` — renders records as Chrome trace-event JSON
  (the ``{"traceEvents": [...]}`` format perfetto/chrome://tracing
  load): one *process* per (node, batcher), one *thread track* per
  dispatcher thread carrying complete ``"X"`` events for prep/execute/
  fetch (sequential per thread by construction), plus a synthetic
  ``queue`` track per batcher — queue-drain windows of consecutive
  dispatches overlap each other and the previous dispatch's execute,
  so they cannot share the dispatcher's track without breaking the
  viewer's nesting invariant. ``GET /_profiler/timeline`` serves this;
  the cluster front fans it in over ``rest:exec`` with per-node dedup
  (``node/cluster_rest.py``).

Emission is a dict build + locked deque append (~µs, measured in
TELEMETRY.md's overhead budget); rendering is snapshot-time only.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional

from ..common.settings import CLUSTER_SETTINGS, Setting

__all__ = ["DispatchProfileRing", "RING", "record", "chrome_trace"]

SETTING_RING_SIZE = CLUSTER_SETTINGS.register(
    Setting.int_setting("dispatch_profile.ring.size", 2048,
                        scope="cluster", dynamic=False, min_value=64))

_SEQ = itertools.count(1)


class DispatchProfileRing:
    """Bounded per-process ring of per-dispatch timeline records."""

    def __init__(self, cap: Optional[int] = None, registry=None):
        if cap is None:
            raw = os.environ.get("ES_TPU_DISPATCH_PROFILE_CAP")
            try:
                cap = int(raw) if raw is not None \
                    else int(SETTING_RING_SIZE.default)
            except ValueError:
                cap = int(SETTING_RING_SIZE.default)
        self.cap = max(int(cap), 64)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.cap)
        self._dropped = 0
        self._emitted = 0
        self._registry = registry

    def record(self, **fields) -> dict:
        """Append one dispatch record. O(1); never raises (profiling
        must not fail the dispatch it profiles). Returns the record
        (empty dict on failure)."""
        try:
            rec = {"seq": next(_SEQ)}
            rec.update(fields)
            with self._lock:
                if len(self._ring) >= self.cap:
                    self._dropped += 1
                self._ring.append(rec)
                self._emitted += 1
            return rec
        except Exception:   # noqa: BLE001 — best-effort by contract
            return {}

    def records(self, since_ms: Optional[float] = None,
                limit: int = 256) -> List[dict]:
        """Chronological slice of the retained ring, capped to the
        NEWEST ``limit`` matches; ``since_ms`` is a wall epoch-ms floor
        on the dispatch's start."""
        with self._lock:
            snap = list(self._ring)
        if since_ms is not None:
            snap = [r for r in snap if r.get("ts_ms", 0) >= since_ms]
        if limit and limit > 0:
            snap = snap[-int(limit):]
        return snap

    def stats_doc(self) -> dict:
        with self._lock:
            return {"retained": len(self._ring), "cap": self.cap,
                    "emitted": self._emitted, "dropped": self._dropped}


#: PROCESS-scoped ring (the flightrec.DEFAULT singleton pattern —
#: in-process multi-node clusters share it; the cluster fan-in dedupes)
RING = DispatchProfileRing()


def record(**fields) -> dict:
    """Module entry the dispatcher loop uses."""
    return RING.record(**fields)


# ---------------------------------------------------------------------------
# Chrome trace-event rendering
# ---------------------------------------------------------------------------

def _track_pid(node: str, batcher: str) -> int:
    """Deterministic pid for one (node, batcher) process track — stable
    across nodes and processes so the cluster fan-in's merged events
    never conflate two nodes' tracks (and in-process duplicates from a
    shared ring collapse exactly)."""
    return (zlib.crc32(f"{node}\x00{batcher}".encode()) & 0x3FFFFFFF) | 1


def chrome_trace(records: List[dict], node: Optional[str] = None) -> dict:
    """Render dispatch records as Chrome trace-event JSON
    (perfetto-loadable): ``M`` metadata events name each (node,
    batcher) process and each dispatcher-thread track, ``X`` complete
    events carry one span per stage with the dispatch's args. Queue
    stages render on a per-batcher synthetic ``queue`` track (tid 0):
    they overlap the dispatcher threads' execute windows by design."""
    events: List[dict] = []
    named_pids: Dict[tuple, int] = {}
    named_tids = set()

    def ensure_process(rnode: str, batcher: str) -> int:
        key = (rnode, batcher)
        pid = named_pids.get(key)
        if pid is None:
            pid = named_pids[key] = _track_pid(rnode, batcher)
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "ts": 0, "args": {"name": f"{rnode} {batcher}"}})
        return pid

    def ensure_thread(pid: int, tid: int, name: str) -> None:
        if (pid, tid) not in named_tids:
            named_tids.add((pid, tid))
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "ts": 0, "args": {"name": name}})

    for r in records:
        rnode = str(r.get("node") or node or "local")
        batcher = str(r.get("batcher") or "?")
        pid = ensure_process(rnode, batcher)
        tid = int(r.get("thread") or 1)
        ensure_thread(pid, tid,
                      str(r.get("thread_name") or f"dispatcher-{tid}"))
        ensure_thread(pid, 0, "queue")
        args = {"rec": r.get("seq"), "kernel": r.get("kernel"),
                "compile_cache": r.get("compile_cache")}
        for k in ("batch", "bucket", "bytes", "audit", "docs_scanned"):
            if r.get(k) is not None:
                args[k] = r[k]
        for st in r.get("stages") or []:
            dur = max(float(st.get("end_ms", 0))
                      - float(st.get("start_ms", 0)), 0.0)
            events.append({
                "ph": "X", "name": str(st.get("name", "?")),
                "cat": str(r.get("kernel") or "dispatch"),
                "pid": pid,
                "tid": 0 if st.get("name") == "queue" else tid,
                # trace-event ts/dur are MICROSECONDS
                "ts": round(float(st.get("start_ms", 0)) * 1e3, 1),
                "dur": round(dur * 1e3, 1),
                "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
